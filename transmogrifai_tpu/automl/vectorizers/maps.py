"""Map vectorizers: per-key expansion of keyed features.

Reference: core/.../impl/feature/{OPMapVectorizer.scala:468,
TextMapPivotVectorizer, MultiPickListMapVectorizer, SmartTextMapVectorizer,
GeolocationMapVectorizer}. Fit discovers the key set per map feature (the
dynamic part), then each (feature, key) pair becomes a statically-shaped
column group: numeric keys impute+null-track, categorical keys pivot,
free-text keys smart-dispatch to pivot/hash, geolocation keys emit
(lat, lon, acc, null).
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ...data.dataset import Column
from ...data.vector import NULL_STRING, OTHER_STRING, VectorColumnMetadata, VectorMetadata
from ...stages.params import Param
from ...types import (
    BinaryMap, DateMap, FeatureType, GeolocationMap, IntegralMap,
    MultiPickListMap, NumericMap, OPMap, RealMap, TextMap,
)
from .base import SequenceVectorizer, VectorizerModel
from .categorical import clean_text_value
from .encoding import (
    category_counts, empty_mask, extract_key_columns, float_column,
    null_mask, pivot_block_multi, pivot_block_single, triple_block,
)
from .geo import geo_mean
from .text import tokenize_hash_counts

_CATEGORICAL_MAP_TYPES = (
    "PickListMap", "ComboBoxMap", "CountryMap", "StateMap", "CityMap",
    "PostalCodeMap", "IDMap",
)


def clean_key(k: str, clean: bool) -> str:
    return clean_text_value(k, clean) if clean else k


class MapVectorizerModel(VectorizerModel):
    """Fitted map vectorizer: per (feature, key) column plans."""

    input_types = (OPMap,)  # mirrors MapVectorizer

    def __init__(self, feature_plans: Sequence[Dict[str, Any]],
                 clean_keys: bool = False,
                 operation_name: str = "vecMap", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        # feature_plans[i]: {kind: 'real'|'binary'|'categorical'|'hash'|
        #                    'multipicklist'|'geo',
        #                    keys: [...], fills: {key: float} | vocab: {key: [...]},
        #                    bins: int, track_nulls: bool, clean_text: bool}
        self.feature_plans = [dict(p) for p in feature_plans]
        self.clean_keys = clean_keys

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        blocks: List[np.ndarray] = []
        for plan, c in zip(self.feature_plans, cols):
            kind = plan["kind"]
            keys = plan["keys"]
            track = plan["track_nulls"]
            clean = plan["clean_text"]
            key_clean = (lambda s: clean_key(s, True)) if self.clean_keys \
                else None
            keycols = extract_key_columns(c.data, keys, key_clean)

            def clean_fn(s, _c=clean):
                return clean_text_value(s, _c)

            def nulls_of(vals):
                return null_mask(vals).astype(np.float64)[:, None]

            for key in keys:
                vals = keycols[key]
                if kind in ("real", "binary"):
                    col = float_column(vals, plan["fills"].get(key, 0.0))
                    parts = [col[:, None]]
                    if track:
                        parts.append(nulls_of(vals))
                    blocks.append(np.concatenate(parts, axis=1))
                elif kind == "categorical":
                    vocab = plan["vocab"].get(key, [])
                    if vocab is None:  # high-cardinality key -> hash space
                        counts = tokenize_hash_counts(vals, plan["bins"])
                        parts = [counts]
                        if track:
                            parts.append(nulls_of(vals))
                        blocks.append(np.concatenate(parts, axis=1))
                    else:
                        blocks.append(pivot_block_single(
                            vals, vocab, track, clean_fn))
                elif kind == "multipicklist":
                    blocks.append(pivot_block_multi(
                        vals, plan["vocab"].get(key, []), track, clean_fn))
                elif kind == "geo":
                    triples = triple_block(
                        vals, plan["fills"].get(key, [0.0, 0.0, 0.0]))
                    if track:
                        empt = empty_mask(vals).astype(np.float64)[:, None]
                        triples = np.concatenate([triples, empt], axis=1)
                    blocks.append(triples)
                else:
                    raise ValueError(f"Unknown map plan kind {kind}")
        return np.concatenate(blocks, axis=1) if blocks else np.zeros((len(cols[0]), 0))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(feature_plans=self.feature_plans, clean_keys=self.clean_keys)
        return d


class MapVectorizer(SequenceVectorizer):
    """Key-discovering map vectorizer for every OPMap subtype."""

    input_types = (OPMap,)

    @classmethod
    def _declare_params(cls):
        return [
            Param("top_k", "pivot vocabulary cap per key", 20),
            Param("min_support", "min occurrences for pivot category", 10),
            Param("max_cardinality", "pivot if distinct <= this (text maps)", 30),
            Param("num_features", "hash bins for high-cardinality text keys", 512),
            Param("clean_text", "normalize category strings", True),
            Param("clean_keys", "normalize map keys", False),
            Param("track_nulls", "append null indicators", True),
            Param("allow_listed_keys", "restrict to these keys (None = all)", None),
            Param("block_listed_keys", "exclude these keys", None),
        ]

    def __init__(self, operation_name: str = "vecMap",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def _kind_of(self, t) -> str:
        name = t.__name__
        if issubclass(t, GeolocationMap):
            return "geo"
        if issubclass(t, MultiPickListMap):
            return "multipicklist"
        if issubclass(t, BinaryMap):
            return "binary"
        if issubclass(t, NumericMap):
            return "real"
        if name in _CATEGORICAL_MAP_TYPES:
            return "categorical"
        if issubclass(t, TextMap):
            return "smarttext"
        return "real"

    def fit_columns(self, *cols: Column) -> MapVectorizerModel:
        clean_keys_p = self.get_param("clean_keys")
        clean = self.get_param("clean_text")
        track = self.get_param("track_nulls")
        top_k = int(self.get_param("top_k"))
        min_support = int(self.get_param("min_support"))
        max_card = int(self.get_param("max_cardinality"))
        bins = int(self.get_param("num_features"))
        allow = self.get_param("allow_listed_keys")
        block = set(self.get_param("block_listed_keys") or ())

        plans: List[Dict[str, Any]] = []
        md_cols: List[VectorColumnMetadata] = []
        for f, c in zip(self.input_features, cols):
            kind = self._kind_of(f.feature_type)
            # discover keys
            key_counts: Counter = Counter()
            for m in c.data:
                if m:
                    for k in m:
                        key_counts[clean_key(str(k), clean_keys_p)] += 1
            keys = sorted(k for k in key_counts
                          if (allow is None or k in allow) and k not in block)
            plan: Dict[str, Any] = dict(kind=kind, keys=keys, track_nulls=track,
                                        clean_text=clean, bins=bins,
                                        fills={}, vocab={})
            key_clean = (lambda s: clean_key(s, True)) if clean_keys_p else None
            keycols = extract_key_columns(c.data, keys, key_clean)
            if kind in ("real", "binary"):
                for key in keys:
                    vals = keycols[key]
                    present = ~null_mask(vals)
                    plan["fills"][key] = (
                        float(float_column(vals, 0.0)[present].mean())
                        if kind == "real" and present.any() else 0.0)
            elif kind == "geo":
                for key in keys:
                    geo_vals = [v for v in keycols[key] if v]
                    plan["fills"][key] = geo_mean(geo_vals)
            elif kind in ("categorical", "multipicklist", "smarttext"):
                for key in keys:
                    counts, _ = category_counts(
                        keycols[key], lambda s: clean_text_value(s, clean),
                        multiset=(kind == "multipicklist"))
                    if kind == "smarttext" and len(counts) > max_card:
                        # high-cardinality free text -> hashing for this key
                        plan["vocab"][key] = None
                    else:
                        kept = [(v, n) for v, n in counts.items()
                                if n >= min_support and v != ""]
                        kept.sort(key=lambda kv: (-kv[1], kv[0]))
                        plan["vocab"][key] = [v for v, _ in kept[:top_k]]
            if kind == "smarttext":
                plan["kind"] = "categorical"  # vocab[key]=None marks hash keys
            plans.append(plan)
            md_cols.extend(self._metadata_for(f, plan))

        model = MapVectorizerModel(feature_plans=plans, clean_keys=clean_keys_p,
                                   operation_name=self.operation_name)
        model.set_metadata(VectorMetadata(name=self.output_name(), columns=md_cols))
        return model

    def _metadata_for(self, f, plan) -> List[VectorColumnMetadata]:
        out: List[VectorColumnMetadata] = []
        track = plan["track_nulls"]
        for key in plan["keys"]:
            if plan["kind"] in ("real", "binary"):
                out.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    grouping=key))
                if track:
                    out.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=key, indicator_value=NULL_STRING))
            elif plan["kind"] in ("categorical", "multipicklist"):
                vocab = plan["vocab"].get(key, [])
                if vocab is None:  # hashed key
                    for b in range(plan["bins"]):
                        out.append(VectorColumnMetadata(
                            parent_feature_name=f.name,
                            parent_feature_type=f.type_name,
                            grouping=key, descriptor_value=f"hash_{b}"))
                    if track:
                        out.append(VectorColumnMetadata(
                            parent_feature_name=f.name,
                            parent_feature_type=f.type_name,
                            grouping=key, indicator_value=NULL_STRING))
                    continue
                for v in vocab:
                    out.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=key, indicator_value=v))
                out.append(VectorColumnMetadata(
                    parent_feature_name=f.name, parent_feature_type=f.type_name,
                    grouping=key, indicator_value=OTHER_STRING))
                if track:
                    out.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=key, indicator_value=NULL_STRING))
            elif plan["kind"] == "geo":
                for d in ("lat", "lon", "accuracy"):
                    out.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=key, descriptor_value=d))
                if track:
                    out.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=key, indicator_value=NULL_STRING))
        return out


def map_vectorizer_for(map_type_name: str, defaults) -> MapVectorizer:
    return MapVectorizer(
        top_k=defaults.top_k, min_support=defaults.min_support,
        max_cardinality=defaults.max_categorical_cardinality,
        num_features=defaults.default_num_of_features,
        clean_text=defaults.clean_text, clean_keys=defaults.clean_keys,
        track_nulls=defaults.track_nulls)


class DateMapUnitCircleModel(VectorizerModel):
    """Fitted DateMap -> per-key [sin, cos] unit-circle blocks (reference
    DateMapToUnitCircleVectorizer.scala via RichMapFeature
    .toUnitCircle:716). Missing keys map to the origin (0, 0) exactly like
    the scalar DateToUnitCircleTransformer."""

    input_types = (OPMap,)  # mirrors DateMapUnitCircleVectorizer

    def __init__(self, key_sets: Sequence[List[str]] = (),
                 time_period: str = "HourOfDay", clean_keys: bool = False,
                 operation_name: str = "dateMapUnitCircle",
                 uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.key_sets = [list(ks) for ks in key_sets]
        self.time_period = str(time_period)
        self.clean_keys = bool(clean_keys)

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        from .dates import unit_circle
        key_clean = (lambda s: clean_key(s, True)) if self.clean_keys \
            else None
        blocks: List[np.ndarray] = []
        for keys, c in zip(self.key_sets, cols):
            keycols = extract_key_columns(c.data, keys, key_clean)
            for key in keys:
                ms = float_column(keycols[key], np.nan)
                s, co, _ = unit_circle(ms, self.time_period)
                blocks.append(np.stack([s, co], axis=1))
        n = len(cols[0].data) if cols else 0
        return (np.concatenate(blocks, axis=1) if blocks
                else np.zeros((n, 0)))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(key_sets=self.key_sets, time_period=self.time_period,
                 clean_keys=self.clean_keys)
        return d


class DateMapUnitCircleVectorizer(SequenceVectorizer):
    """Estimator: discover each DateMap's key set, emit [sin, cos] per key
    for one calendar period (reference RichMapFeature.toUnitCircle)."""

    input_types = (OPMap,)

    @classmethod
    def _declare_params(cls):
        return [
            Param("time_period", "HourOfDay|DayOfWeek|DayOfMonth|DayOfYear|"
                  "WeekOfYear|MonthOfYear", "HourOfDay"),
            Param("clean_keys", "normalize map keys", False),
            Param("allow_listed_keys", "restrict to these keys (None = all)",
                  None),
            Param("block_listed_keys", "exclude these keys", None),
        ]

    def __init__(self, operation_name: str = "dateMapUnitCircle",
                 uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    def fit_columns(self, *cols: Column) -> DateMapUnitCircleModel:
        clean_keys_p = bool(self.get_param("clean_keys"))
        allow = self.get_param("allow_listed_keys")
        block = set(self.get_param("block_listed_keys") or ())
        period = str(self.get_param("time_period"))

        key_sets: List[List[str]] = []
        md_cols: List[VectorColumnMetadata] = []
        for f, c in zip(self.input_features, cols):
            seen: Dict[str, None] = {}
            for m in c.data:
                if m:
                    for k in m:
                        seen.setdefault(clean_key(str(k), clean_keys_p))
            keys = [k for k in sorted(seen)
                    if (allow is None or k in set(allow)) and k not in block]
            key_sets.append(keys)
            for key in keys:
                for d in ("sin", "cos"):
                    md_cols.append(VectorColumnMetadata(
                        parent_feature_name=f.name,
                        parent_feature_type=f.type_name,
                        grouping=key, descriptor_value=f"{period}_{d}"))
        model = DateMapUnitCircleModel(
            key_sets, time_period=period, clean_keys=clean_keys_p,
            operation_name=self.operation_name)
        model.set_metadata(VectorMetadata(
            name=self.output_name() or "dateMapUnitCircle",
            columns=md_cols))
        return model
