"""Random hyperparameter search builder.

Reference: core/.../impl/selector/RandomParamBuilder.scala (196 LoC) —
random grids over uniform / log-uniform (exponential) / subset domains,
passed to a ModelSelector instead of the exhaustive default grids.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from ..stages.params import ParamMap


class RandomParamBuilder:
    """``RandomParamBuilder(seed).uniform("step_size", 0.01, 0.3)
    .exponential("reg_param", 1e-6, 1.0).subset("max_depth", [3, 6, 12])
    .build(10)``"""

    def __init__(self, seed: int = 42):
        self._rng = np.random.default_rng(seed)
        self._draws: List[tuple] = []

    def uniform(self, name: str, lo: float, hi: float) -> "RandomParamBuilder":
        if hi < lo:
            raise ValueError(f"{name}: hi < lo")
        self._draws.append(("uniform", name, float(lo), float(hi)))
        return self

    def exponential(self, name: str, lo: float, hi: float
                    ) -> "RandomParamBuilder":
        """Log-uniform (reference exponential): both bounds must be > 0."""
        if lo <= 0 or hi < lo:
            raise ValueError(f"{name}: need 0 < lo <= hi")
        self._draws.append(("exponential", name, float(lo), float(hi)))
        return self

    def uniform_int(self, name: str, lo: int, hi: int) -> "RandomParamBuilder":
        if hi < lo:
            raise ValueError(f"{name}: hi < lo")
        self._draws.append(("uniform_int", name, int(lo), int(hi)))
        return self

    def subset(self, name: str, choices: Sequence[Any]
               ) -> "RandomParamBuilder":
        if not choices:
            raise ValueError(f"{name}: empty choices")
        self._draws.append(("subset", name, list(choices), None))
        return self

    def build(self, n: int) -> List[ParamMap]:
        out: List[ParamMap] = []
        for _ in range(n):
            g: Dict[str, Any] = {}
            for kind, name, a, b in self._draws:
                if kind == "uniform":
                    g[name] = float(self._rng.uniform(a, b))
                elif kind == "exponential":
                    g[name] = float(np.exp(self._rng.uniform(np.log(a),
                                                             np.log(b))))
                elif kind == "uniform_int":
                    g[name] = int(self._rng.integers(a, b + 1))
                else:
                    g[name] = a[int(self._rng.integers(0, len(a)))]
            out.append(g)
        return out
