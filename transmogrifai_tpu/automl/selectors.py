"""Problem-type model-selector presets + default hyperparameter grids.

Reference: core/.../impl/classification/BinaryClassificationModelSelector.scala
(:59-61 default model types, :67-110 grids),
MultiClassificationModelSelector.scala, regression/RegressionModelSelector.scala,
selector/DefaultSelectorParams.scala:35-56.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..evaluators.evaluators import (
    BinaryClassificationEvaluator, Evaluator, Evaluators,
    MultiClassificationEvaluator, RegressionEvaluator,
)
from ..models.base import PredictorEstimator
from ..stages.params import ParamMap, param_grid
from .selector import ModelSelector
from .tuning.splitters import DataBalancer, DataCutter, DataSplitter, Splitter
from .tuning.validators import CrossValidation, TrainValidationSplit, Validator


class DefaultSelectorParams:
    """Reference DefaultSelectorParams.scala:35-56."""

    MAX_DEPTH = [3, 6, 12]
    MAX_BIN = [32]
    MIN_INSTANCES_PER_NODE = [10, 100]
    MIN_INFO_GAIN = [0.001, 0.01, 0.1]
    REGULARIZATION = [0.001, 0.01, 0.1, 0.2]
    MAX_ITER_LIN = [50]
    MAX_ITER_TREE = [20]
    SUBSAMPLE_RATE = [1.0]
    STEP_SIZE = [0.1]
    ELASTIC_NET = [0.1, 0.5]
    MAX_TREES = [50]
    STANDARDIZED = [True]
    TOL = [1e-6]
    FIT_INTERCEPT = [True]
    NB_SMOOTHING = [1.0]
    DIST_FAMILY = ["gaussian", "poisson"]
    NUM_ROUND_XGB = [100]
    ETA_XGB = [0.1, 0.3]
    MIN_CHILD_WEIGHT_XGB = [1.0, 5.0, 10.0]


D = DefaultSelectorParams


def _models_by_name() -> Dict[str, type]:
    from ..models import glm
    out = {
        "OpLogisticRegression": glm.OpLogisticRegression,
        "OpLinearSVC": glm.OpLinearSVC,
        "OpNaiveBayes": glm.OpNaiveBayes,
        "OpLinearRegression": glm.OpLinearRegression,
        "OpGeneralizedLinearRegression": glm.OpGeneralizedLinearRegression,
    }
    try:
        from ..models.mlp import OpMultilayerPerceptronClassifier
        out["OpMultilayerPerceptronClassifier"] = \
            OpMultilayerPerceptronClassifier
    except ImportError:
        pass
    try:
        from ..models import trees
        out.update({
            "OpRandomForestClassifier": trees.OpRandomForestClassifier,
            "OpRandomForestRegressor": trees.OpRandomForestRegressor,
            "OpGBTClassifier": trees.OpGBTClassifier,
            "OpGBTRegressor": trees.OpGBTRegressor,
            "OpDecisionTreeClassifier": trees.OpDecisionTreeClassifier,
            "OpDecisionTreeRegressor": trees.OpDecisionTreeRegressor,
            "OpXGBoostClassifier": trees.OpXGBoostClassifier,
            "OpXGBoostRegressor": trees.OpXGBoostRegressor,
        })
    except ImportError:
        pass
    return out


def default_grid_for(name: str) -> List[ParamMap]:
    """Default sweep grid per model type (reference grids :67-110)."""
    if name == "OpLogisticRegression":
        return param_grid(reg_param=D.REGULARIZATION,
                          elastic_net_param=D.ELASTIC_NET,
                          max_iter=D.MAX_ITER_LIN)
    if name == "OpLinearSVC":
        return param_grid(reg_param=D.REGULARIZATION,
                          max_iter=D.MAX_ITER_LIN)
    if name == "OpNaiveBayes":
        return param_grid(smoothing=D.NB_SMOOTHING)
    if name == "OpLinearRegression":
        return param_grid(reg_param=D.REGULARIZATION,
                          elastic_net_param=D.ELASTIC_NET,
                          max_iter=D.MAX_ITER_LIN)
    if name == "OpGeneralizedLinearRegression":
        return param_grid(family=D.DIST_FAMILY, reg_param=D.REGULARIZATION)
    if name in ("OpRandomForestClassifier", "OpRandomForestRegressor"):
        return param_grid(max_depth=D.MAX_DEPTH,
                          min_instances_per_node=D.MIN_INSTANCES_PER_NODE,
                          min_info_gain=D.MIN_INFO_GAIN,
                          num_trees=D.MAX_TREES)
    if name in ("OpGBTClassifier", "OpGBTRegressor"):
        return param_grid(max_depth=D.MAX_DEPTH,
                          min_instances_per_node=D.MIN_INSTANCES_PER_NODE,
                          min_info_gain=D.MIN_INFO_GAIN,
                          max_iter=D.MAX_ITER_TREE, step_size=D.STEP_SIZE)
    if name in ("OpDecisionTreeClassifier", "OpDecisionTreeRegressor"):
        return param_grid(max_depth=D.MAX_DEPTH,
                          min_instances_per_node=D.MIN_INSTANCES_PER_NODE,
                          min_info_gain=D.MIN_INFO_GAIN)
    if name in ("OpXGBoostClassifier", "OpXGBoostRegressor"):
        return param_grid(max_depth=D.MAX_DEPTH, eta=D.ETA_XGB,
                          min_child_weight=D.MIN_CHILD_WEIGHT_XGB,
                          num_round=D.NUM_ROUND_XGB)
    return [dict()]


def _resolve_models(model_types: Sequence[str], problem_type: str,
                    models_and_params: Optional[Sequence[
                        Tuple[PredictorEstimator, List[ParamMap]]]],
                    seed: int) -> List[Tuple[PredictorEstimator, List[ParamMap]]]:
    if models_and_params is not None:
        return list(models_and_params)
    registry = _models_by_name()
    out: List[Tuple[PredictorEstimator, List[ParamMap]]] = []
    for name in model_types:
        cls = registry.get(name)
        if cls is None:
            continue  # model family not built yet / not in this install
        est = cls()
        if problem_type not in est.problem_types:
            raise ValueError(f"{name} does not support {problem_type}")
        if est.has_param("seed"):
            est.set_param("seed", seed)
        out.append((est, default_grid_for(name)))
    if not out:
        raise ValueError(f"No available models among {list(model_types)}")
    return out


class _SelectorFactory:
    problem_type: str = "binary"
    default_model_types: Tuple[str, ...] = ()
    default_evaluator = staticmethod(lambda: Evaluator())
    default_splitter = staticmethod(lambda seed: Splitter(seed=seed))

    @classmethod
    def apply(cls, splitter: Optional[Splitter] = None,
              evaluator: Optional[Evaluator] = None,
              num_folds: int = 3, seed: int = 42, stratify: bool = False,
              parallelism: int = 8,
              model_types: Optional[Sequence[str]] = None,
              models_and_parameters: Optional[Sequence[
                  Tuple[PredictorEstimator, List[ParamMap]]]] = None,
              ) -> ModelSelector:
        return cls.with_cross_validation(
            splitter=splitter, evaluator=evaluator, num_folds=num_folds,
            seed=seed, stratify=stratify, parallelism=parallelism,
            model_types=model_types,
            models_and_parameters=models_and_parameters)

    @classmethod
    def with_cross_validation(cls, splitter: Optional[Splitter] = None,
                              evaluator: Optional[Evaluator] = None,
                              num_folds: int = 3, seed: int = 42,
                              stratify: bool = False, parallelism: int = 8,
                              model_types: Optional[Sequence[str]] = None,
                              models_and_parameters=None) -> ModelSelector:
        ev = evaluator or cls.default_evaluator()
        validator = CrossValidation(ev, num_folds=num_folds, seed=seed,
                                    stratify=stratify, parallelism=parallelism)
        return cls._build(validator, splitter, seed, model_types,
                          models_and_parameters)

    @classmethod
    def with_train_validation_split(cls, splitter: Optional[Splitter] = None,
                                    evaluator: Optional[Evaluator] = None,
                                    train_ratio: float = 0.75, seed: int = 42,
                                    stratify: bool = False,
                                    parallelism: int = 8,
                                    model_types: Optional[Sequence[str]] = None,
                                    models_and_parameters=None) -> ModelSelector:
        ev = evaluator or cls.default_evaluator()
        validator = TrainValidationSplit(ev, train_ratio=train_ratio,
                                         seed=seed, stratify=stratify,
                                         parallelism=parallelism)
        return cls._build(validator, splitter, seed, model_types,
                          models_and_parameters)

    @classmethod
    def _build(cls, validator: Validator, splitter: Optional[Splitter],
               seed: int, model_types, models_and_parameters) -> ModelSelector:
        split = splitter if splitter is not None else cls.default_splitter(seed)
        models = _resolve_models(
            model_types if model_types is not None else cls.default_model_types,
            cls.problem_type, models_and_parameters, seed)
        sel = ModelSelector(validator, split, models,
                            operation_name=f"{cls.problem_type}ModelSelector")
        sel.problem_type = cls.problem_type
        return sel


class BinaryClassificationModelSelector(_SelectorFactory):
    """Reference BinaryClassificationModelSelector.scala (defaults :59-61:
    LR/RF/GBT/SVC on; NB/DT/XGB off)."""

    problem_type = "binary"
    default_model_types = ("OpLogisticRegression", "OpRandomForestClassifier",
                           "OpGBTClassifier", "OpLinearSVC")
    default_evaluator = staticmethod(Evaluators.BinaryClassification.au_pr)
    default_splitter = staticmethod(lambda seed: DataBalancer(seed=seed))


class MultiClassificationModelSelector(_SelectorFactory):
    """Reference MultiClassificationModelSelector.scala (defaults: LR/RF on)."""

    problem_type = "multiclass"
    default_model_types = ("OpLogisticRegression", "OpRandomForestClassifier")
    default_evaluator = staticmethod(Evaluators.MultiClassification.error)
    default_splitter = staticmethod(lambda seed: DataCutter(seed=seed))


class RegressionModelSelector(_SelectorFactory):
    """Reference RegressionModelSelector.scala (defaults: LinReg/RF/GBT on)."""

    problem_type = "regression"
    default_model_types = ("OpLinearRegression", "OpRandomForestRegressor",
                           "OpGBTRegressor")
    default_evaluator = staticmethod(Evaluators.Regression.rmse)
    default_splitter = staticmethod(lambda seed: DataSplitter(seed=seed))
