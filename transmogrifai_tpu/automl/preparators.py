"""SanityChecker: automated feature validation.

Reference: core/.../impl/preparators/SanityChecker.scala:236 (fitFn:535,
reasonsToRemove:783, categoricalTests:420, defaults :721-736) and
SanityCheckerMetadata.scala.

TPU-first: every statistic is an XLA reduction over the HBM feature matrix.
Since the one-pass statistics engine (ops/stats_engine.py,
docs/performance.md "One-pass statistics engine") a pearson-mode fit makes
EXACTLY ONE device pass over X: per-column moments, label correlations, the
capped feature-feature Pearson matrix, label moments and every categorical
contingency table (one batched matmul against an on-device one-hot label,
replacing both the reduceByKey at SanityChecker.scala:440 and the previous
one-device-round-trip-per-group host loop) all come out of a single
blocked/jitted scan. Spearman keeps its rank pre-pass, run blocked on
device, and feeds the ranks through the same moment engine.
TMOG_STATS_FUSED=0 restores the legacy multi-pass path (ops/stats called
per statistic). The fitted model is a static index-gather that XLA fuses
into the downstream program.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..data.dataset import Column, Dataset
from ..data.vector import VectorColumnMetadata, VectorMetadata
from ..ops import stats as S
from ..ops import stats_engine as SE
from ..stages.base import Estimator, Transformer
from ..stages.params import Param
from ..types import ColumnKind, OPVector, RealNN
from ..utils.uid import make_uid

_TEXT_PARENTS = {"Text", "TextArea", "TextMap", "TextAreaMap"}


@dataclass
class ColumnStatistics:
    """Per-column stats + removal reasons (reference ColumnStatistics)."""

    name: str
    column: Optional[VectorColumnMetadata]
    is_label: bool
    count: float
    mean: float
    min: float
    max: float
    variance: float
    corr_label: Optional[float] = None
    cramers_v: Optional[float] = None
    parent_corr: Optional[float] = None
    parent_cramers_v: Optional[float] = None
    max_rule_confidences: List[float] = field(default_factory=list)
    supports: List[float] = field(default_factory=list)

    def reasons_to_remove(self, min_variance: float, min_correlation: float,
                          max_correlation: float, max_cramers_v: float,
                          max_rule_confidence: float,
                          min_required_rule_support: float,
                          remove_feature_group: bool,
                          protect_text_shared_hash: bool,
                          removed_groups: Sequence[str]) -> List[str]:
        if self.is_label:
            return []
        reasons = []
        if self.variance <= min_variance:
            reasons.append(
                f"variance {self.variance} lower than min variance {min_variance}")
        if self.corr_label is not None and np.isfinite(self.corr_label):
            if abs(self.corr_label) < min_correlation:
                reasons.append(f"correlation {self.corr_label} lower than "
                               f"min correlation {min_correlation}")
            if abs(self.corr_label) > max_correlation:
                reasons.append(f"correlation {self.corr_label} higher than "
                               f"max correlation {max_correlation}")
        if self.cramers_v is not None and self.cramers_v > max_cramers_v:
            reasons.append(f"Cramer's V {self.cramers_v} higher than "
                           f"max Cramer's V {max_cramers_v}")
        for conf, sup in zip(self.max_rule_confidences, self.supports):
            if conf > max_rule_confidence and sup > min_required_rule_support:
                reasons.append(
                    f"association rule confidence {conf} above "
                    f"{max_rule_confidence} with support {sup} above "
                    f"{min_required_rule_support}")
                break
        group = self.feature_group()
        if group is not None and group in removed_groups:
            reasons.append(f"other feature in indicator group {group} flagged "
                           "for removal via rule confidence checks")
        if remove_feature_group and not (
                protect_text_shared_hash and self.is_text_shared_hash()):
            if self.parent_cramers_v is not None and \
                    self.parent_cramers_v > max_cramers_v:
                reasons.append(
                    f"Cramer's V {self.parent_cramers_v} for something in "
                    f"parent feature set higher than max Cramer's V "
                    f"{max_cramers_v}")
            if self.parent_corr is not None and self.parent_corr > max_correlation:
                reasons.append(
                    f"correlation {self.parent_corr} for something in parent "
                    f"feature set higher than max correlation {max_correlation}")
        return reasons

    def feature_group(self) -> Optional[str]:
        if self.column is None or self.column.grouping is None:
            return None
        return f"{self.column.parent_feature_name}_{self.column.grouping}"

    def is_text_shared_hash(self) -> bool:
        c = self.column
        return (c is not None and c.parent_feature_type in _TEXT_PARENTS
                and c.grouping is None and c.indicator_value is None)


@dataclass
class CategoricalGroupStats:
    """Contingency-test results for one indicator group (reference
    CategoricalGroupStats in SanityCheckerMetadata.scala)."""

    group: str
    categorical_features: List[str]
    contingency_matrix: List[List[float]]
    cramers_v: float
    chi2: float
    mutual_info: float
    pointwise_mutual_info: List[List[float]]
    max_rule_confidences: List[float]
    supports: List[float]


@dataclass
class SanityCheckerSummary:
    """Everything the checker measured (reference SanityCheckerSummary)."""

    correlation_type: str
    names: List[str]
    column_stats: List[Dict[str, Any]]
    categorical_stats: List[Dict[str, Any]]
    dropped: List[str]
    drop_reasons: Dict[str, List[str]]
    sample_fraction: float
    correlations_matrix: Optional[List[List[float]]] = None
    # discrete label domain + per-value counts when the label was treated
    # as categorical (feeds ModelInsights LabelSummary.distribution)
    label_distribution: Optional[Dict[str, List[float]]] = None
    # dropped column name -> parent raw feature (resolved from the
    # PRE-slice metadata, which the fitted model no longer carries)
    dropped_parents: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        from dataclasses import asdict
        return asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "SanityCheckerSummary":
        return SanityCheckerSummary(**d)


class SanityCheckerModel(Transformer):
    """Fitted checker: static index slice of the feature vector (reference
    SanityCheckerModel:697 indicesToKeep)."""

    input_types = (RealNN, OPVector)
    output_type = OPVector

    def __init__(self, indices_to_keep: Sequence[int],
                 metadata: Optional[VectorMetadata] = None,
                 summary: Optional[SanityCheckerSummary] = None,
                 operation_name: str = "sanityCheck",
                 uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.indices_to_keep = [int(i) for i in indices_to_keep]
        self.metadata = metadata
        self.summary = summary

    def get_jax_fn(self):
        idx = jnp.asarray(np.asarray(self.indices_to_keep, np.int32))

        def keep(_label, vec):
            return jnp.take(vec, idx, axis=-1)

        return keep

    def transform_columns(self, *cols: Column) -> Column:
        vec = cols[-1]
        data = vec.data[:, self.indices_to_keep]
        return Column(kind=ColumnKind.VECTOR,
                      data=np.ascontiguousarray(data),
                      metadata=self.output_metadata() or
                      (vec.metadata.select(self.indices_to_keep)
                       if vec.metadata else None))

    def transform_value(self, *vals):
        vec = np.asarray(vals[-1].value, np.float32)
        return OPVector(vec[self.indices_to_keep])

    def output_metadata(self) -> Optional[VectorMetadata]:
        return self.metadata

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(indices_to_keep=self.indices_to_keep,
                 metadata=self.metadata.to_json() if self.metadata else None,
                 summary=self.summary.to_json() if self.summary else None)
        return d

    @classmethod
    def from_save_args(cls, args: Dict[str, Any]) -> "SanityCheckerModel":
        return cls(
            indices_to_keep=args["indices_to_keep"],
            metadata=(VectorMetadata.from_json(args["metadata"])
                      if args.get("metadata") else None),
            summary=(SanityCheckerSummary.from_json(args["summary"])
                     if args.get("summary") else None),
            operation_name=args.get("operation_name", "sanityCheck"),
            uid=args.get("uid"))


class SanityChecker(Estimator):
    """Estimator2(RealNN label, OPVector) -> cleaned OPVector."""

    input_types = (RealNN, OPVector)
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        # defaults: reference SanityChecker.scala:721-736
        return [
            Param("check_sample", "fraction of data to check", 1.0),
            Param("sample_lower_limit", "min rows sampled", 1000),
            Param("sample_upper_limit", "max rows sampled", 1_000_000),
            Param("sample_seed", "sampling seed", 42),
            Param("remove_bad_features", "actually drop flagged columns", False),
            Param("max_correlation", "max |corr| with label", 0.95),
            Param("min_correlation", "min |corr| with label", 0.0),
            Param("min_variance", "min column variance", 1e-5),
            Param("max_cramers_v", "max Cramer's V vs label", 0.95),
            Param("correlation_type", "pearson|spearman", "pearson",
                  lambda v: v in ("pearson", "spearman")),
            Param("categorical_label", "force categorical-label tests", None),
            Param("remove_feature_group", "drop whole flagged groups", True),
            Param("protect_text_shared_hash", "keep shared text hash cols", False),
            Param("max_rule_confidence", "label-leakage rule confidence", 1.0),
            Param("min_required_rule_support", "rule support threshold", 1.0),
            Param("feature_label_corr_only", "skip full corr matrix", False),
            Param("max_corr_matrix_columns",
                  "widest vector for which the full d x d correlation matrix "
                  "is stored in the summary", 256),
        ]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("sanityCheck", uid=uid, **params)

    # -- sampling ----------------------------------------------------------
    def _fraction(self, total: int) -> float:
        """Reference SanityChecker.fraction:525."""
        ck = float(self.get_param("check_sample"))
        min_frac = min(1.0, float(self.get_param("sample_lower_limit")) / max(total, 1))
        max_frac = max(0.0, float(self.get_param("sample_upper_limit")) / max(total, 1))
        return max(min(ck, max_frac), min_frac)

    def fit_columns(self, *cols: Column) -> SanityCheckerModel:
        label_col, vec_col = cols
        y_all = np.asarray(label_col.data, np.float64).astype(np.float32)
        X_all = vec_col.data
        if X_all.ndim == 1:
            X_all = X_all[:, None]
        n_total = len(y_all)

        frac = self._fraction(n_total)
        if frac < 1.0:
            rng = np.random.default_rng(int(self.get_param("sample_seed")))
            take = rng.uniform(size=n_total) < frac
            X, y = X_all[take], y_all[take]
        else:
            X, y = X_all, y_all
        n = len(y)

        meta = vec_col.metadata
        names = (meta.column_names() if meta is not None
                 else [f"f{i}" for i in range(X.shape[1])])
        columns = (list(meta.columns) if meta is not None
                   else [None] * X.shape[1])

        # distinct label domain + per-value counts in ONE host pass over
        # the label only (np.unique(return_counts) — the previous
        # (y[:, None] == distinct[None, :]).sum(0) broadcast materialized
        # an O(n * k) boolean matrix, ~4GB at 10M rows x 100 classes)
        distinct, distinct_counts = np.unique(y, return_counts=True)
        cat_param = self.get_param("categorical_label")
        is_cat = (bool(cat_param) if cat_param is not None
                  else len(distinct) < min(100.0, n * 0.1))

        # full feature-feature matrix (one X^T X Gram) unless the user opts
        # out (reference featureLabelCorrOnly, SanityChecker.scala:193)
        # cap on columns for which the full d x d matrix is materialized and
        # stored in the summary: beyond this the matrix costs O(d^2) host
        # memory + JSON size for little diagnostic value (the drop logic only
        # needs corr-with-label)
        corr_matrix_cap = int(self.get_param("max_corr_matrix_columns"))
        want_matrix = (not bool(self.get_param("feature_label_corr_only"))
                       and self.get_param("correlation_type") == "pearson"
                       and X.shape[1] <= corr_matrix_cap)
        corr_matrix: Optional[np.ndarray] = None
        do_cat = is_cat and meta is not None and len(distinct) > 1
        group_stats: List[CategoricalGroupStats] = []
        cramers_by_col: Dict[int, float] = {}
        conf_by_col: Dict[int, Tuple[List[float], List[float]]] = {}

        if SE.fused_enabled():
            # -- fused route: ONE engine pass over X -----------------------
            # a raised max_corr_matrix_columns can exceed the engine's Gram
            # cap; the matrix then computes on the legacy kernel (one extra
            # pass for that rare config) instead of failing the fit
            matrix_fused = want_matrix and X.shape[1] <= SE.GRAM_MAX_D
            (counts, means, mins, maxs, variances, corr, corr_matrix,
             label_stats_tuple, cont) = self._fused_device_stats(
                X, y, distinct if do_cat else None, columns, matrix_fused)
            if want_matrix and not matrix_fused:
                corr_matrix = np.asarray(
                    S.pearson_matrix(jnp.asarray(X, jnp.float32)))
            if do_cat and cont is not None:
                group_stats, cramers_by_col, conf_by_col = \
                    self._categorical_from_contingency(
                        cont, columns, names,
                        distinct_counts.astype(np.float64))
        else:
            # -- legacy multi-pass route (kill switch TMOG_STATS_FUSED=0) --
            Xj = jnp.asarray(X, jnp.float32)
            yj = jnp.asarray(y, jnp.float32)
            cs = S.col_stats(Xj)
            if self.get_param("correlation_type") == "spearman":
                corr = np.asarray(S.spearman_with_label(Xj, yj))
            else:
                corr = np.asarray(S.pearson_with_label(Xj, yj))
            if want_matrix:
                corr_matrix = np.asarray(S.pearson_matrix(Xj))
            label_cs = S.col_stats(yj[:, None])
            counts = np.asarray(cs.count)
            means = np.asarray(cs.mean)
            mins = np.asarray(cs.min)
            maxs = np.asarray(cs.max)
            variances = np.asarray(cs.variance)
            label_stats_tuple = (
                float(np.asarray(label_cs.count)[0]),
                float(np.asarray(label_cs.mean)[0]),
                float(np.asarray(label_cs.variance)[0]),
                float(np.asarray(label_cs.min)[0]),
                float(np.asarray(label_cs.max)[0]))
            if do_cat:
                group_stats, cramers_by_col, conf_by_col = \
                    self._categorical_tests(X, y, columns, names, distinct)

        # -- assemble per-column statistics --------------------------------
        col_stats_list: List[ColumnStatistics] = []
        for i, nm in enumerate(names):
            col_stats_list.append(ColumnStatistics(
                name=nm, column=columns[i], is_label=False,
                count=float(counts[i]), mean=float(means[i]),
                min=float(mins[i]), max=float(maxs[i]),
                variance=float(variances[i]),
                corr_label=float(corr[i]) if np.isfinite(corr[i]) else None,
                cramers_v=cramers_by_col.get(i),
                max_rule_confidences=conf_by_col.get(i, ([], []))[0],
                supports=conf_by_col.get(i, ([], []))[1],
            ))
        l_count, l_mean, l_var, l_min, l_max = label_stats_tuple
        label_stats = ColumnStatistics(
            name=self.input_names()[0] if self.input_names() else "label",
            column=None, is_label=True, count=l_count, mean=l_mean,
            min=l_min, max=l_max, variance=l_var)

        # parent-level maxima (reference maxByParent / corrParentMap)
        by_parent_corr: Dict[str, float] = {}
        by_parent_cv: Dict[str, float] = {}
        for st in col_stats_list:
            if st.column is None:
                continue
            p = st.column.parent_feature_name
            if st.corr_label is not None and not st.column.is_null_indicator:
                v = abs(st.corr_label)
                if np.isfinite(v):
                    by_parent_corr[p] = max(by_parent_corr.get(p, 0.0), v)
            if st.cramers_v is not None:
                by_parent_cv[p] = max(by_parent_cv.get(p, 0.0), st.cramers_v)
        for st in col_stats_list:
            if st.column is None:
                continue
            p = st.column.parent_feature_name
            if p in by_parent_corr:
                st.parent_corr = by_parent_corr[p]
            if p in by_parent_cv:
                st.parent_cramers_v = by_parent_cv[p]

        # rule-confidence group removals propagate to the whole group
        removed_groups = [
            st.feature_group() for st in col_stats_list
            if st.feature_group() is not None and any(
                conf > float(self.get_param("max_rule_confidence")) and
                sup > float(self.get_param("min_required_rule_support"))
                for conf, sup in zip(st.max_rule_confidences, st.supports))
        ]

        drop_reasons: Dict[str, List[str]] = {}
        drop_indices: List[int] = []
        for i, st in enumerate(col_stats_list):
            reasons = st.reasons_to_remove(
                min_variance=float(self.get_param("min_variance")),
                min_correlation=float(self.get_param("min_correlation")),
                max_correlation=float(self.get_param("max_correlation")),
                max_cramers_v=float(self.get_param("max_cramers_v")),
                max_rule_confidence=float(self.get_param("max_rule_confidence")),
                min_required_rule_support=float(
                    self.get_param("min_required_rule_support")),
                remove_feature_group=bool(self.get_param("remove_feature_group")),
                protect_text_shared_hash=bool(
                    self.get_param("protect_text_shared_hash")),
                removed_groups=removed_groups)
            if reasons:
                drop_reasons[st.name] = reasons
                drop_indices.append(i)

        if bool(self.get_param("remove_bad_features")):
            keep = [i for i in range(X.shape[1]) if i not in set(drop_indices)]
            if not keep:  # never drop everything
                keep = list(range(X.shape[1]))
        else:
            keep = list(range(X.shape[1]))

        summary = SanityCheckerSummary(
            correlation_type=self.get_param("correlation_type"),
            names=names,
            column_stats=[{
                "name": st.name, "count": st.count, "mean": st.mean,
                "min": st.min, "max": st.max, "variance": st.variance,
                "corr_label": st.corr_label, "cramers_v": st.cramers_v,
                "parent_corr": st.parent_corr,
                "parent_cramers_v": st.parent_cramers_v,
            } for st in [label_stats] + col_stats_list],
            categorical_stats=[{
                "group": g.group, "categorical_features": g.categorical_features,
                "cramers_v": g.cramers_v, "chi2": g.chi2,
                "mutual_info": g.mutual_info,
                "pointwise_mutual_info": g.pointwise_mutual_info,
                "contingency_matrix": g.contingency_matrix,
                "max_rule_confidences": g.max_rule_confidences,
                "supports": g.supports,
            } for g in group_stats],
            dropped=[names[i] for i in drop_indices],
            drop_reasons=drop_reasons,
            sample_fraction=frac,
            correlations_matrix=(corr_matrix.tolist()
                                 if corr_matrix is not None else None),
            label_distribution=(
                {"domain": [float(v) for v in distinct],
                 "counts": [float(c) for c in distinct_counts]}
                if is_cat else None),
            dropped_parents={
                names[i]: columns[i].parent_feature_name
                for i in drop_indices if columns[i] is not None},
        )
        out_meta = meta.select(keep) if meta is not None else None
        return SanityCheckerModel(indices_to_keep=keep, metadata=out_meta,
                                  summary=summary,
                                  operation_name=self.operation_name)

    # -- fused one-pass statistics ----------------------------------------
    @staticmethod
    def _grouped_columns(columns: Sequence[Optional[VectorColumnMetadata]]
                         ) -> Dict[str, List[int]]:
        """Indicator groups: columns carrying both grouping and
        indicator_value, keyed parent_grouping (reference :420)."""
        groups: Dict[str, List[int]] = {}
        for i, c in enumerate(columns):
            if c is None or c.grouping is None or c.indicator_value is None:
                continue
            groups.setdefault(f"{c.parent_feature_name}_{c.grouping}",
                              []).append(i)
        return groups

    def _fused_device_stats(self, X, y, distinct, columns, want_matrix):
        """ONE engine pass: moments + correlations (+ Pearson matrix +
        batched contingency) for pearson mode; spearman adds the blocked
        device rank pre-pass and a second moment pass over the ranks."""
        groups = self._grouped_columns(columns)
        distinct_dev = distinct if groups else None
        clip = None
        if distinct_dev is not None:
            # MultiPickList parents: multi-hot counts clip to 1 (ref :428).
            # Group-wise in the reference; per-column here with every
            # member of an MPL-touched group marked — same result.
            clip = np.zeros(X.shape[1], bool)
            for idxs in groups.values():
                if any(columns[i].parent_feature_type == "MultiPickList"
                       for i in idxs):
                    clip[idxs] = True
            if not clip.any():
                clip = None
        st = SE.run_stats(X, y, distinct=distinct_dev, clip=clip,
                          corr_matrix=want_matrix, label="sanity_stats")
        if self.get_param("correlation_type") == "spearman":
            rx, ry = SE.rank_matrices(X, y)
            corr = SE.run_stats(rx, ry, label="sanity_spearman").corr_label
        else:
            corr = st.corr_label
        label_stats = (st.label_count, st.label_mean, st.label_variance,
                       st.label_min, st.label_max)
        return (st.count, st.mean, st.min, st.max, st.variance, corr,
                st.corr_matrix, label_stats, st.contingency)

    def _categorical_from_contingency(self, cont: np.ndarray,
                                      columns, names,
                                      label_totals: np.ndarray):
        """Per-group contingency statistics off the engine's batched
        [d, C] table — host numpy on tiny [k, C] slices, zero device
        round-trips (the legacy path dispatched one contingency matmul
        PLUS one contingency_stats program per group)."""
        groups = self._grouped_columns(columns)
        group_stats: List[CategoricalGroupStats] = []
        cramers_by_col: Dict[int, float] = {}
        conf_by_col: Dict[int, Tuple[List[float], List[float]]] = {}
        for group, idxs in groups.items():
            table = np.asarray(cont[idxs], np.float64)
            if len(idxs) == 1:
                # single indicator: synthesize the complement row (ref :477)
                table = np.concatenate(
                    [table, (label_totals - table[0])[None, :]], axis=0)
            st = S.contingency_stats_host(table)
            k = len(idxs)
            confs = [float(v) for v in st.max_rule_confidences[:k]]
            sups = [float(v) for v in st.supports[:k]]
            cv = float(st.cramers_v)
            for j, i in enumerate(idxs):
                cramers_by_col[i] = cv
                conf_by_col[i] = ([confs[j]], [sups[j]])
            group_stats.append(CategoricalGroupStats(
                group=group,
                categorical_features=[names[i] for i in idxs],
                contingency_matrix=[[float(v) for v in row]
                                    for row in table],
                cramers_v=cv, chi2=float(st.chi2),
                mutual_info=float(st.mutual_info),
                pointwise_mutual_info=[[float(v) for v in row]
                                       for row in st.pointwise_mutual_info],
                max_rule_confidences=confs, supports=sups))
        return group_stats, cramers_by_col, conf_by_col

    # -- contingency machinery (legacy multi-pass path) -------------------
    def _categorical_tests(self, X: np.ndarray, y: np.ndarray,
                           columns: Sequence[Optional[VectorColumnMetadata]],
                           names: Sequence[str], distinct: np.ndarray):
        """Reference categoricalTests:420: per indicator group, contingency
        matrix of indicator columns vs label classes."""
        label_idx = {float(v): j for j, v in enumerate(distinct)}
        Y = np.zeros((len(y), len(distinct)), np.float32)
        Y[np.arange(len(y)), [label_idx[float(v)] for v in y]] = 1.0

        # one grouping rule for both routes: the fused path's contingency
        # slicing must select exactly these groups or the kill switch
        # silently changes results
        groups = self._grouped_columns(columns)

        group_stats: List[CategoricalGroupStats] = []
        cramers_by_col: Dict[int, float] = {}
        conf_by_col: Dict[int, Tuple[List[float], List[float]]] = {}
        label_totals = Y.sum(axis=0)

        for group, idxs in groups.items():
            # MultiPickList parents: clip multi-hot counts to 1 (reference :428)
            is_mpl = any(columns[i].parent_feature_type == "MultiPickList"
                         for i in idxs)
            G = X[:, idxs]
            if is_mpl:
                G = np.minimum(G, 1.0)
            table = np.asarray(S.contingency_table(
                jnp.asarray(G, jnp.float32), jnp.asarray(Y)))
            if len(idxs) == 1:
                # single indicator: synthesize the complement row (ref :477)
                table = np.concatenate([table, (label_totals - table[0])[None, :]],
                                       axis=0)
            st = S.contingency_stats(jnp.asarray(table))
            k = len(idxs)
            confs = [float(v) for v in np.asarray(st.max_rule_confidences)[:k]]
            sups = [float(v) for v in np.asarray(st.supports)[:k]]
            cv = float(np.asarray(st.cramers_v))
            for j, i in enumerate(idxs):
                cramers_by_col[i] = cv
                conf_by_col[i] = ([confs[j]], [sups[j]])
            group_stats.append(CategoricalGroupStats(
                group=group,
                categorical_features=[names[i] for i in idxs],
                contingency_matrix=[[float(v) for v in row] for row in table],
                cramers_v=cv, chi2=float(np.asarray(st.chi2)),
                mutual_info=float(np.asarray(st.mutual_info)),
                pointwise_mutual_info=[[float(v) for v in row]
                                       for row in np.asarray(
                                           st.pointwise_mutual_info)],
                max_rule_confidences=confs, supports=sups))
        return group_stats, cramers_by_col, conf_by_col
