"""Transmogrifier: automatic per-type default vectorization.

Reference: core/.../impl/feature/Transmogrifier.scala:92 — groups features by
static type and applies each group's default vectorizer, then combines the
group vectors. Defaults mirror TransmogrifierDefaults (Transmogrifier.scala:52-90).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Type

from ..features.feature import Feature
from ..types import (
    Base64, Binary, City, ComboBox, Country, Currency, Date, DateList,
    DateTime, Email, FeatureType, Geolocation, ID, Integral, MultiPickList,
    OPMap, OPVector, Percent, Phone, PickList, PostalCode, Real, RealNN,
    State, Street, Text, TextArea, TextList, URL,
)
from .vectorizers.categorical import OneHotVectorizer
from .vectorizers.combiner import VectorsCombiner
from .vectorizers.numeric import (
    BinaryVectorizer, IntegralVectorizer, NumericVectorizer, RealNNVectorizer,
)


@dataclass
class TransmogrifierDefaults:
    """Reference Transmogrifier.scala:52-90."""

    default_num_of_features: int = 512
    max_num_of_features: int = 16384
    top_k: int = 20
    min_support: int = 10
    fill_value: float = 0.0
    binary_fill_value: bool = False
    clean_text: bool = True
    clean_keys: bool = False
    fill_with_mode: bool = True
    fill_with_mean: bool = True
    track_nulls: bool = True
    track_invalid: bool = False
    track_text_len: bool = False
    min_doc_frequency: int = 0
    max_categorical_cardinality: int = 30
    reference_date_ms: Optional[int] = None
    circular_date_periods: Tuple[str, ...] = (
        "HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear")


DEFAULTS = TransmogrifierDefaults()

# dispatch order matters: most-specific first (a PickList is a Text).
# Street pivots like a PickList (Transmogrifier.scala:338 — the reference
# ships no smarter Street default either).
_CATEGORICAL_TEXT = (PickList, ComboBox, Country, State, City, PostalCode,
                     ID, Street)


def transmogrify(features: Sequence[Feature],
                 label: Optional[Feature] = None,
                 defaults: TransmogrifierDefaults = DEFAULTS) -> Feature:
    """Vectorize features by type and combine into one OPVector feature
    (reference Transmogrifier.transmogrify:102-348 + .transmogrify() dsl).

    ``label`` is consumed by label-aware vectorizers (the reference's
    decision-tree bucketizers); groups without a label-aware default ignore
    it, matching the reference when no response is in scope."""
    vector_feats = vectorize_by_type(features, label=label, defaults=defaults)
    if len(vector_feats) == 1:
        return vector_feats[0]
    combiner = VectorsCombiner()
    return combiner.set_input(*vector_feats).get_output()


def vectorize_by_type(features: Sequence[Feature],
                      label: Optional[Feature] = None,
                      defaults: TransmogrifierDefaults = DEFAULTS
                      ) -> List[Feature]:
    """One vectorizer per type group; returns the group vector features."""
    groups: Dict[str, List[Feature]] = {}
    order: List[str] = []
    for f in features:
        key = _group_key(f.feature_type)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(f)

    out: List[Feature] = []
    for key in order:
        out.append(_vectorize_group(key, groups[key], defaults))
    return out


def _derivations():
    """key -> (derivation transformer, target vectorizer group) for
    structured text types (Transmogrifier.scala:277-340 via
    dsl/RichTextFeature.scala): Email/URL domain pivots
    (RichEmailFeature.vectorize:608, RichURLFeature.vectorize:666 —
    valid-URL domains only), phone validity bits
    (RichPhoneFeature.vectorize:558), Base64 MIME pivots
    (RichBase64Feature.vectorize:711)."""
    from ..transformers.text import (
        EmailToPickList, MimeTypeDetector, PhoneNumberParser,
        UrlToDomainPickList,
    )
    return {"email": (EmailToPickList, "categorical"),
            "url": (UrlToDomainPickList, "categorical"),
            "base64": (MimeTypeDetector, "categorical"),
            "phone": (PhoneNumberParser, "binary")}


def _vectorize_group(key: str, feats: List[Feature],
                     d: TransmogrifierDefaults) -> Feature:
    """Derive-then-vectorize for structured text groups; plain
    per-group default vectorizer otherwise. The derived group reuses
    _vectorizer_for's default for its target group, so categorical/binary
    defaults stay single-sourced."""
    derivation = _derivations().get(key)
    if derivation is not None:
        transformer_cls, target = derivation
        feats = [transformer_cls().set_input(f).get_output() for f in feats]
        key = target
    stage = _vectorizer_for(key, d)
    return stage.set_input(*feats).get_output()


def _group_key(t: Type[FeatureType]) -> str:
    if issubclass(t, RealNN):
        return "realnn"
    if issubclass(t, Binary):
        return "binary"
    if issubclass(t, (Date, DateTime)) and issubclass(t, Integral):
        return "date"
    if issubclass(t, Integral):
        return "integral"
    if issubclass(t, Real):  # Real, Percent, Currency
        return "real"
    if issubclass(t, MultiPickList):
        return "multipicklist"
    if issubclass(t, _CATEGORICAL_TEXT):
        return "categorical"
    # structured text types get derivation-then-vectorize defaults
    # (Transmogrifier.scala:277-340): domain pivots for Email/URL, phone
    # validity, MIME pivot for Base64 — generic hashing would discard the
    # structure these types declare
    if issubclass(t, Email):
        return "email"
    if issubclass(t, Phone):
        return "phone"
    if issubclass(t, URL):
        return "url"
    if issubclass(t, Base64):
        return "base64"
    if issubclass(t, (TextArea, Text)):
        return "text"
    if issubclass(t, TextList):
        return "textlist"
    if issubclass(t, DateList):
        return "datelist"
    if issubclass(t, Geolocation):
        return "geolocation"
    if issubclass(t, OPVector):
        return "vector"
    if issubclass(t, OPMap):
        return f"map_{t.__name__}"
    raise TypeError(f"No default vectorizer for feature type {t.__name__}")


def _vectorizer_for(key: str, d: TransmogrifierDefaults):
    if key == "realnn":
        return RealNNVectorizer()
    if key == "real":
        return NumericVectorizer(
            fill_mode="mean" if d.fill_with_mean else "constant",
            fill_value=d.fill_value, track_nulls=d.track_nulls)
    if key == "integral":
        return IntegralVectorizer(
            fill_mode="mode" if d.fill_with_mode else "constant",
            track_nulls=d.track_nulls)
    if key == "binary":
        return BinaryVectorizer(fill_value=float(d.binary_fill_value),
                                track_nulls=d.track_nulls)
    if key == "categorical":
        return OneHotVectorizer(top_k=d.top_k, min_support=d.min_support,
                                clean_text=d.clean_text,
                                track_nulls=d.track_nulls)
    if key == "multipicklist":
        return OneHotVectorizer(multiset=True, top_k=d.top_k,
                                min_support=d.min_support,
                                clean_text=d.clean_text,
                                track_nulls=d.track_nulls)
    if key == "text":
        from .vectorizers.text import SmartTextVectorizer
        return SmartTextVectorizer(
            max_cardinality=d.max_categorical_cardinality,
            num_features=d.default_num_of_features, top_k=d.top_k,
            min_support=d.min_support, track_nulls=d.track_nulls)
    if key == "date":
        from .vectorizers.dates import DateVectorizer
        return DateVectorizer(reference_date_ms=d.reference_date_ms,
                              circular_periods=list(d.circular_date_periods),
                              track_nulls=d.track_nulls)
    if key == "datelist":
        from .vectorizers.dates import DateListVectorizer
        return DateListVectorizer(reference_date_ms=d.reference_date_ms)
    if key == "geolocation":
        from .vectorizers.geo import GeolocationVectorizer
        return GeolocationVectorizer(track_nulls=d.track_nulls)
    if key == "textlist":
        from .vectorizers.text import TextListHashingVectorizer
        return TextListHashingVectorizer(num_features=d.default_num_of_features)
    if key == "vector":
        return VectorsCombiner()
    if key.startswith("map_"):
        from .vectorizers.maps import map_vectorizer_for
        return map_vectorizer_for(key[4:], d)
    raise TypeError(f"No vectorizer for group {key}")
