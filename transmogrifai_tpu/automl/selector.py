"""ModelSelector: validated model search + final refit.

Reference: core/.../impl/selector/ModelSelector.scala:73 (fit:135 — splitter
prep, validator.validate, best-estimator refit on the full prepared train
set, train/holdout evaluation, ModelSelectorSummary metadata; SelectedModel
:216) and ModelSelectorSummary.scala.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..evaluators.evaluators import Evaluator
from ..models.base import PredictionModel, PredictorEstimator
from ..models.prediction import make_prediction_column
from ..stages.params import ParamMap
from .tuning.splitters import PreparedData, Splitter
from .tuning.validators import BestEstimator, Validator


@dataclass
class ModelSelectorSummary:
    """Validation results metadata (reference ModelSelectorSummary.scala)."""

    validation_type: str
    validation_parameters: Dict[str, Any]
    data_prep_parameters: Dict[str, Any]
    data_prep_results: Dict[str, Any]
    evaluation_metric: str
    problem_type: str
    best_model_uid: str
    best_model_name: str
    best_model_type: str
    best_grid: ParamMap
    validation_results: List[Dict[str, Any]] = field(default_factory=list)
    train_evaluation: Dict[str, float] = field(default_factory=dict)
    holdout_evaluation: Dict[str, float] = field(default_factory=dict)
    # direction of evaluation_metric as the EVALUATOR declared it — name
    # lookup alone misranks custom smaller-is-better metrics; None (old
    # saved summaries) falls back to the name-based table
    metric_larger_better: Optional[bool] = None

    def to_json(self) -> Dict[str, Any]:
        from dataclasses import asdict
        return asdict(self)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ModelSelectorSummary":
        return ModelSelectorSummary(**d)

    def pretty(self) -> str:
        """Human summary mirroring the reference's summaryPretty tables."""
        lines = [
            f"Evaluated {len(self.validation_results)} model configurations "
            f"({self.validation_type}, metric: {self.evaluation_metric})",
            f"Selected: {self.best_model_name} "
            f"(uid {self.best_model_uid}) grid={self.best_grid}",
        ]
        larger = (self.metric_larger_better
                  if self.metric_larger_better is not None
                  else _larger_better(self.evaluation_metric))
        ranked = sorted(
            self.validation_results,
            key=lambda v: v.get("mean_metric", float("nan")),
            reverse=larger)
        from ..utils.table import format_table
        lines.append(format_table(
            ["Model", "Grid", self.evaluation_metric],
            [[v["model_name"], str(v.get("grid", {})),
              float(v.get("mean_metric", float("nan")))]
             for v in ranked[:20]],
            title="Evaluated models"))
        if self.train_evaluation:
            lines.append("Train evaluation: " + ", ".join(
                f"{k}={v:.6f}" for k, v in sorted(self.train_evaluation.items())
                if isinstance(v, float)))
        if self.holdout_evaluation:
            lines.append("Holdout evaluation: " + ", ".join(
                f"{k}={v:.6f}" for k, v in sorted(self.holdout_evaluation.items())
                if isinstance(v, float)))
        return "\n".join(lines)


def _larger_better(metric: str) -> bool:
    return Evaluator.larger_better_metric(metric)


def _remap_labels(arr: np.ndarray, mapping: Dict[int, int]) -> np.ndarray:
    """Vectorized label remap that is safe on empty arrays."""
    out = np.asarray(arr, np.float32).copy()
    for src, dst in mapping.items():
        out[np.asarray(arr) == src] = dst
    return out


class SelectedModel(PredictionModel):
    """The fitted winner (reference SelectedModel, ModelSelector.scala:216):
    delegates scoring to the wrapped best model; carries the summary."""

    def __init__(self, best_model: PredictionModel,
                 summary: ModelSelectorSummary,
                 label_map: Optional[Dict[int, int]] = None,
                 operation_name: str = "modelSelector",
                 uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.best_model = best_model
        self.summary = summary
        self.label_map = label_map

    def predict_arrays(self, X):
        pred, raw, prob = self.best_model.predict_arrays(X)
        if self.label_map:
            inv = {v: k for k, v in self.label_map.items()}
            if any(k != v for k, v in inv.items()):
                pred = _remap_labels(pred, inv)
        return pred, raw, prob

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(
            best_model_class=type(self.best_model).__name__,
            best_model_args=self.best_model.save_args(),
            summary=self.summary.to_json(),
            label_map={str(k): v for k, v in (self.label_map or {}).items()},
        )
        return d

    @classmethod
    def from_save_args(cls, args: Dict[str, Any]) -> "SelectedModel":
        """Reference ModelSelector.scala:235-240 — the wrapped best model is
        re-instantiated from its own class + args on load."""
        from ..stages.registry import build_stage
        best = build_stage(args["best_model_class"], args["best_model_args"])
        return cls(
            best_model=best,
            summary=ModelSelectorSummary.from_json(args["summary"]),
            label_map={int(k): int(v)
                       for k, v in (args.get("label_map") or {}).items()} or None,
            operation_name=args.get("operation_name", "modelSelector"),
            uid=args.get("uid"))


class ModelSelector(PredictorEstimator):
    """Estimator2(RealNN label, OPVector features) -> Prediction running the
    validated sweep (reference ModelSelector.scala:73)."""

    problem_type = "binary"

    def __init__(self, validator: Validator, splitter: Optional[Splitter],
                 models: Sequence[Tuple[PredictorEstimator, List[ParamMap]]],
                 evaluators: Sequence[Evaluator] = (),
                 operation_name: str = "modelSelector",
                 uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.validator = validator
        self.splitter = splitter
        self.models = list(models)
        self.extra_evaluators = list(evaluators)
        #: across-time GLM warm start ({"beta": [d] raw-unit coefs,
        #: "intercept": float}) — the retrain refit worker seeds it from
        #: the serving champion (retrain/refit.apply_champion_shortcuts)
        #: and the streamed round driver starts every lane there instead
        #: of at zero (ops/glm_sweep `warm_seed`). None = cold start.
        self.warm_seed = None

    # -- the sweep ---------------------------------------------------------
    def fit_arrays(self, X: np.ndarray, y: np.ndarray,
                   w: Optional[np.ndarray] = None) -> SelectedModel:
        n = len(y)
        if w is None:
            w = np.ones(n, np.float32)

        if self.splitter is not None and self.splitter.reserve_test_fraction > 0:
            train_idx, test_idx = self.splitter.split(n)
        else:
            train_idx, test_idx = np.arange(n), np.arange(0)

        y_train = y[train_idx]
        prep = (self.splitter.prepare(y_train) if self.splitter is not None
                else PreparedData(indices=np.arange(len(train_idx)),
                                  weights=np.ones(len(train_idx), np.float32)))
        use_idx = train_idx[prep.indices]
        Xt, yt = X[use_idx], y[use_idx]
        wt = w[use_idx] * prep.weights
        if prep.label_map and any(k != v for k, v in prep.label_map.items()):
            yt = _remap_labels(yt, prep.label_map)

        self.validator.warm_seed = self.warm_seed
        best: BestEstimator = self.validator.validate(
            self.models, Xt, yt, wt, problem_type=self.problem_type)

        # refit winner on the full prepared train set (reference :159)
        best_model = best.estimator.fit_arrays(Xt, yt, wt)

        evaluator = self.validator.evaluator
        train_eval = self._evaluate(evaluator, best_model, Xt, yt, wt)
        holdout_eval: Dict[str, float] = {}
        if len(test_idx):
            yh = y[test_idx]
            if prep.label_map and any(k != v for k, v in prep.label_map.items()):
                keep = np.isin(yh, list(prep.label_map.keys()))
                test_idx = test_idx[keep]
                yh = _remap_labels(yh[keep], prep.label_map)
            if len(test_idx):
                holdout_eval = self._evaluate(
                    evaluator, best_model, X[test_idx], yh, w[test_idx])

        summary = ModelSelectorSummary(
            validation_type=type(self.validator).__name__,
            validation_parameters=self._validator_params(),
            data_prep_parameters=(self.splitter.save_args()
                                  if self.splitter else {}),
            data_prep_results=prep.summary,
            evaluation_metric=evaluator.default_metric,
            metric_larger_better=bool(evaluator.is_larger_better()),
            problem_type=self.problem_type,
            best_model_uid=best.estimator.uid,
            best_model_name=best.name,
            best_model_type=type(best.estimator).__name__,
            best_grid=best.best_grid,
            validation_results=(
                # workflow-level CV results (leakage-free in-fold DAG refits,
                # stashed by Workflow._run_workflow_cv) come first
                list(getattr(self, "_extra_validation_results", []))
                + [{"model_name": v.model_name, "model_uid": v.model_uid,
                    "grid": v.grid, "metric_name": v.metric_name,
                    "fold_metrics": v.fold_metrics,
                    "mean_metric": v.mean_metric}
                   for v in best.validated]),
            train_evaluation=train_eval,
            holdout_evaluation=holdout_eval,
        )
        return SelectedModel(best_model, summary,
                             label_map=prep.label_map,
                             operation_name=self.operation_name)

    def _evaluate(self, evaluator: Evaluator, model: PredictionModel,
                  X: np.ndarray, y: np.ndarray,
                  w: np.ndarray) -> Dict[str, Any]:
        pred, raw, prob = model.predict_arrays(X)
        col = make_prediction_column(pred, raw, prob)
        out: Dict[str, Any] = dict(evaluator.evaluate_all(y, col, w))
        for ev in self.extra_evaluators:
            for k, v in ev.evaluate_all(y, col, w).items():
                out.setdefault(f"{ev.name}_{k}", v)
        # floats are the metric scalars; dicts carry structured curves
        # (multiclass threshold_metrics) into the summary JSON — the
        # pretty printer formats floats only
        return {k: v for k, v in out.items()
                if isinstance(v, (float, dict))}

    def _validator_params(self) -> Dict[str, Any]:
        v = self.validator
        out: Dict[str, Any] = {"seed": v.seed, "stratify": v.stratify}
        if hasattr(v, "num_folds"):
            out["num_folds"] = v.num_folds
        if hasattr(v, "train_ratio"):
            out["train_ratio"] = v.train_ratio
        return out

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d["problem_type"] = self.problem_type
        return d
