"""Data splitting/balancing + validation (CV / train-validation split).

Reference: core/.../impl/tuning/{Splitter,DataSplitter,DataBalancer,
DataCutter,OpValidator,OpCrossValidation,OpTrainValidationSplit}.scala.
"""
from .splitters import (
    DataBalancer,
    DataCutter,
    DataSplitter,
    PreparedData,
    Splitter,
)
from .validators import (
    BestEstimator,
    CrossValidation,
    TrainValidationSplit,
    ValidatedModel,
    Validator,
)

__all__ = [
    "BestEstimator",
    "CrossValidation",
    "DataBalancer",
    "DataCutter",
    "DataSplitter",
    "PreparedData",
    "Splitter",
    "TrainValidationSplit",
    "ValidatedModel",
    "Validator",
]
