"""Validators: cross-validation and train/validation split.

Reference: core/.../impl/tuning/{OpValidator.scala:94, OpCrossValidation.scala:41,
OpTrainValidationSplit.scala:34}. The reference evaluates every
(model x ParamMap) per fold on an 8-thread pool (OpValidator.scala:318) with
physical per-fold datasets (MLUtils.kFold).

TPU-first redesign: folds are *weight masks* over the in-HBM feature matrix —
no data movement between folds. For GLM-family estimators the whole
(fold x grid) sweep is ONE jitted program: `vmap` over fold masks and
hyperparameter leaves, fit by fixed-iteration Newton, score with one matmul,
evaluate with mask-weighted metric kernels. Non-vmappable estimators (trees,
naive Bayes) fall back to a per-(fold, grid) loop over sliced arrays.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...evaluators.evaluators import Evaluator
from ...models.base import PredictionModel, PredictorEstimator
from ...models.prediction import make_prediction_column
from ...ops import metrics_ops as M
from ...stages.params import ParamMap


@dataclass
class ValidatedModel:
    """Validation record for one (estimator, grid point) — reference
    ModelEvaluation entries in ModelSelectorSummary."""

    model_name: str
    model_uid: str
    grid: ParamMap
    metric_name: str
    fold_metrics: List[float]
    # which sweep kernel produced these metrics ("streamed" | "vmapped" |
    # "mask_folds" | "sequential") — callers attributing timings/FLOPs
    # (bench.py MFU accounting) read it off the validation result
    route: str = ""

    @property
    def mean_metric(self) -> float:
        vals = [v for v in self.fold_metrics if np.isfinite(v)]
        return float(np.mean(vals)) if vals else float("nan")


@dataclass
class BestEstimator:
    """Winner of validation (reference OpValidator.wrapBestEstimator:147)."""

    name: str
    estimator: PredictorEstimator  # configured with the best grid
    best_grid: ParamMap
    best_metric: float
    validated: List[ValidatedModel] = field(default_factory=list)


# In-sweep AuPR/AuROC switch from exact sorts to O(n) histogram kernels above
# this many rows (the winner's final metrics remain exact); see
# ops/metrics_ops.au_pr_binned for the approximation contract.
BINNED_RANK_METRIC_MIN_ROWS = 2_000_000
RANK_METRIC_BINS = 4096

# HBM budget the auto grid-chunker assumes for one sweep call. Each vmapped
# lane (fold x grid point) materializes one [n, d] X-scaled product for the
# Gram matmul, so lanes are capped at budget / (n * d * itemsize).
SWEEP_LANE_BUDGET_BYTES = 12e9


def _metric_fn(problem_type: str, metric: str, n_classes: int = 2,
               rank_bins: Optional[int] = None) -> Callable:
    """Pure-jax (scores, labels, weights, margin_threshold) -> scalar used
    inside the vmapped sweep. Binary scores are margins (monotone in
    probability, so rank metrics match); thresholded metrics use the margin
    equivalent of the evaluator's probability threshold (logit for
    probabilistic models). The threshold is a traced scalar so distinct
    evaluator thresholds do NOT trigger sweep-kernel recompiles. Multiclass
    scores are [n, c] logits; argmax is invariant to softmax, so class
    metrics come straight from the confusion matmul
    (OpMultiClassificationEvaluator.scala:58)."""
    if problem_type == "binary":
        if metric == "au_pr":
            if rank_bins:
                return lambda s, y, w, thr: M.au_pr_binned(s, y, w, rank_bins)
            return lambda s, y, w, thr: M.au_pr(s, y, w)
        if metric == "au_roc":
            if rank_bins:
                return lambda s, y, w, thr: M.au_roc_binned(s, y, w, rank_bins)
            return lambda s, y, w, thr: M.au_roc(s, y, w)
        def bin_m(s, y, w, thr, _m=metric):
            return getattr(M.binary_metrics(s, y, w, threshold=thr), _m)
        return bin_m
    if problem_type == "multiclass":
        def multi_m(s, y, w, thr, _m=metric, _k=n_classes):
            pred = jnp.argmax(s, axis=1)
            return getattr(M.multiclass_metrics(pred, y, _k, w), _m)
        return multi_m
    if problem_type == "regression":
        def reg_m(p, y, w, thr, _m=metric):
            return getattr(M.regression_metrics(p, y, w), _m)
        return reg_m
    raise ValueError(f"No vmapped metric for problem type {problem_type}")


# Rows above which GLM sweeps route through the streaming lane-batched
# kernel (ops/glm_sweep.py): one X pass per Newton iteration for ALL
# (fold x grid) lanes instead of one per lane. Below it, the per-lane
# vmapped program is simpler and compile-cheaper. Since the autotuning
# PR this is the HAND default of a plan-time decision (docs/planning.md)
# — but reassigning the module global still pins the route outright
# (hand beats model, same precedence as an env knob): tests and
# bench.py's vmapped-retry path rely on exactly that.
STREAMED_SWEEP_MIN_ROWS = 200_000
_STREAMED_SWEEP_MIN_ROWS_HAND = STREAMED_SWEEP_MIN_ROWS

def grid_fuse_max_failures() -> int:
    """Consecutive config-fused route failures tolerated before the
    sweep raises (ADVICE r5): the per-config fallback is the correctness
    baseline, but a fused route that dies on EVERY group is a broken
    kernel/driver that must surface, not a warning stream to scroll
    past. Read per sweep, like every other TMOG_GRID_FUSE_* knob."""
    return int(os.environ.get("TMOG_GRID_FUSE_MAX_FAILURES", "3"))

def _lanes_metric_fn(metric: str, problem_type: str, rank_bins):
    """(scores [L, n], labels [n], w_lanes [L, n]) -> [L] metric values
    when the metric has a lane-batched binned kernel, else None. Single
    source of the guard for every sweep path (streamed eval, tree fold
    metrics)."""
    if not (rank_bins and problem_type == "binary"):
        return None
    if metric == "au_pr":
        return lambda s, y, wl: M.au_pr_binned_lanes(s, y, wl, rank_bins)
    if metric == "au_roc":
        return lambda s, y, wl: M.au_roc_binned_lanes(s, y, wl, rank_bins)
    return None


@partial(jax.jit,
         static_argnames=("metric", "problem_type", "n_classes",
                          "rank_bins", "chunk", "use_lanes"))
def _streamed_eval(X, y, vw, Bc, b0c, thr, *, metric, problem_type,
                   n_classes=2, rank_bins=None, chunk=8, use_lanes=True):
    """Metrics for one fold's grid chunk of streamed-sweep coefficients:
    scores in one MXU contraction; binned rank metrics go through the
    lane-batched kernel (one pallas histogram for the whole chunk on TPU
    instead of per-lane scatter-adds), everything else vmaps. Mesh
    callers pass use_lanes=False (a pallas_call must not consume
    row-sharded operands; GSPMD partitions the vmapped kernels instead)."""
    from ...ops.glm_sweep import sweep_scores_fold
    s = sweep_scores_fold(X, Bc, b0c)                   # [n, chunk]
    lanes_fn = _lanes_metric_fn(metric, problem_type, rank_bins) \
        if use_lanes else None
    if lanes_fn is not None:
        wl = jnp.broadcast_to(vw[None, :], (s.shape[1], vw.shape[0]))
        return lanes_fn(s.T, y, wl)
    mfn = _metric_fn(problem_type, metric, n_classes, rank_bins)
    return jax.vmap(lambda col: mfn(col, y, vw, thr), in_axes=1)(s)


# _streamed_eval's executables bake the lanes-kernel (pallas) choice in;
# the kill switch clears them on toggle
from ...ops import pallas_hist as _pallas_hist  # noqa: E402
_pallas_hist.register_cache_consumer(_streamed_eval)


@partial(jax.jit,
         static_argnames=("fit_one", "metric", "problem_type", "n_classes",
                          "rank_bins"))
def _sweep(X, y, w, fold_masks, regs, alphas, margin_threshold, *, fit_one,
           metric, problem_type, n_classes=2, rank_bins=None):
    """The sweep kernel: metrics[F, G] for F fold masks x G grid points.

    One XLA program: on a row-sharded X every Gram-matrix reduction inside
    fit_one becomes an ICI psum; fold/grid axes are embarrassingly parallel
    (vmap) and can additionally be laid out on the `model` mesh axis.
    Multiclass fit_one returns (B [d, c], b0 [c]) and the same `X @ beta + b0`
    scoring broadcasts to [n, c] logits.
    """
    mfn = _metric_fn(problem_type, metric, n_classes, rank_bins)

    def one(mask, reg, alpha):
        beta, b0 = fit_one(X, y, mask * w, reg, alpha)
        # keep a bf16 X bf16 in the scoring dot too (beta is f32 solver
        # state; plain X @ beta would materialize a full f32 copy of X)
        score = jnp.matmul(X, beta.astype(X.dtype),
                           preferred_element_type=jnp.float32) + b0
        return mfn(score, y, (1.0 - mask) * w, margin_threshold)

    per_grid = jax.vmap(lambda m: jax.vmap(partial(one, m))(regs, alphas))
    return per_grid(fold_masks)


class Validator:
    """Base validator (reference OpValidator.scala:94)."""

    def __init__(self, evaluator: Evaluator, seed: int = 42,
                 stratify: bool = False, parallelism: int = 8,
                 grid_chunk: Optional[int] = None,
                 sweep_dtype: Optional[Any] = None,
                 mask_fold_trees: bool = True,
                 mesh: Optional[Any] = None):
        self.evaluator = evaluator
        self.seed = int(seed)
        self.stratify = bool(stratify)
        # kept for API parity; device vmap replaces the thread pool
        self.parallelism = int(parallelism)
        # optional sweep checkpoint (resume skips finished model x grid cells)
        self.checkpoint_path: Optional[str] = None
        # round/pass telemetry of the LAST streamed GLM sweep (bench.py's
        # executed-FLOP accounting reads it; also mirrored into
        # utils/metrics.collector.sweep_convergence when collection is on)
        self.last_streamed_telemetry: Optional[Dict[str, Any]] = None
        self._external_mask_tag = ""  # set per validate() call
        # grid points swept per XLA call (None = auto from the HBM budget);
        # checkpoints land after every chunk, so a preempted vmapped sweep
        # resumes mid-grid
        self.grid_chunk = grid_chunk
        # on-device dtype of the sweep's feature matrix; jnp.bfloat16 halves
        # HBM per lane (solvers keep f32 state — ops/glm._solver_dtype)
        self.sweep_dtype = sweep_dtype
        # trees: fit every fold as a weight mask over ONE device-binned
        # matrix (no host slicing). NB quantile bin edges then come from the
        # full column (features only, never labels) rather than per-fold
        # train rows — set False to force physically split refits
        self.mask_fold_trees = bool(mask_fold_trees)
        # optional jax.sharding.Mesh (parallel/mesh.py axes): the sweep's
        # feature matrix/labels/weights shard rows over the `batch` axis,
        # fold masks shard their row dim — every Gram/histogram reduction
        # inside the jitted sweep then becomes an ICI psum inserted by
        # GSPMD; program text is unchanged (SURVEY §2.9 translation of
        # Spark partitioning). Rows pad to the axis size with zero weights,
        # which every kernel treats as absent.
        self.mesh = mesh

    # -- folds -------------------------------------------------------------
    def fold_masks(self, y: np.ndarray) -> np.ndarray:
        """[F, n] float32 train-membership masks (1=train, 0=validation)."""
        raise NotImplementedError

    def _assign_folds(self, y: np.ndarray, n_folds: int) -> np.ndarray:
        """Per-row fold id; stratified round-robin within each class when
        stratify is on (reference prepareStratification:203)."""
        rng = np.random.default_rng(self.seed)
        n = len(y)
        fold_of = np.empty(n, np.int32)
        if self.stratify:
            for cls in np.unique(y):
                idx = np.flatnonzero(y == cls)
                rng.shuffle(idx)
                fold_of[idx] = np.arange(len(idx)) % n_folds
        else:
            perm = rng.permutation(n)
            fold_of[perm] = np.arange(n) % n_folds
        return fold_of

    # -- validation --------------------------------------------------------
    def validate(self, models: Sequence[Tuple[PredictorEstimator, List[ParamMap]]],
                 X: np.ndarray, y: np.ndarray,
                 w: Optional[np.ndarray] = None,
                 problem_type: str = "binary",
                 masks: Optional[np.ndarray] = None) -> BestEstimator:
        """`masks` overrides self.fold_masks(y) — the workflow-level CV
        (leakage-free in-fold DAG refits, OpValidator.applyDAG:228) feeds
        one fold-fitted matrix at a time with that fold's single mask, so
        its inner (model x grid) sweep rides the same device routes."""
        if w is None:
            w = np.ones_like(y, np.float32)
        if masks is None:
            masks = self.fold_masks(y)
            self._external_mask_tag = ""
        else:
            # checkpoint cells must be keyed by WHICH masks ran: external
            # per-fold masks can share a data fingerprint across calls
            import hashlib
            self._external_mask_tag = hashlib.sha1(
                np.ascontiguousarray(masks, np.float32).tobytes()
            ).hexdigest()[:12]
        metric = self.evaluator.default_metric
        larger = self.evaluator.is_larger_better()

        # a user-supplied metric (Evaluators.custom) has no device kernel:
        # every candidate goes through the sequential per-fold route, which
        # is the only one that calls evaluator.evaluate on host columns
        device_metric = getattr(self.evaluator, "device_metric", True)

        validated: List[ValidatedModel] = []
        for est, grids in models:
            if not grids:
                grids = [dict()]
            if not device_metric:
                validated.extend(self._validate_sequential(
                    est, grids, X, y, w, masks))
            elif self._streamable(est, grids, problem_type, X,
                                  masks.shape[0]):
                validated.extend(self._validate_streamed(
                    est, grids, X, y, w, masks, metric, problem_type))
            elif self._vmappable(est, grids, problem_type):
                validated.extend(self._validate_vmapped(
                    est, grids, X, y, w, masks, metric, problem_type))
            elif (self.mask_fold_trees
                  and getattr(est, "supports_mask_folds", False)
                  and problem_type in getattr(est, "problem_types", ())):
                validated.extend(self._validate_mask_folds(
                    est, grids, X, y, w, masks, metric, problem_type))
            else:
                validated.extend(self._validate_sequential(
                    est, grids, X, y, w, masks))

        if not validated:
            raise ValueError("No models to validate")
        key = (lambda v: v.mean_metric if np.isfinite(v.mean_metric)
               else (-np.inf if larger else np.inf))
        best = max(validated, key=key) if larger else min(validated, key=key)
        winner = next(e for e, _ in models
                      if e.uid == best.model_uid).copy(**best.grid)
        return BestEstimator(name=best.model_name, estimator=winner,
                             best_grid=best.grid,
                             best_metric=best.mean_metric, validated=validated)

    # -- vmapped GLM path --------------------------------------------------
    @staticmethod
    def _constant_off_axis(est: PredictorEstimator, grids: List[ParamMap],
                           axes) -> bool:
        """Every non-axis grid key must be constant across the grid (those
        become static jit args via copy)."""
        others = {k for g in grids for k in g if k not in axes}
        for k in others:
            vals = {repr(g.get(k, est.get_param(k))) for g in grids}
            if len(vals) > 1:
                return False
        return True

    @staticmethod
    def _vmappable(est: PredictorEstimator, grids: List[ParamMap],
                   problem_type: str) -> bool:
        if not getattr(est, "supports_grid_vmap", False):
            return False
        if problem_type == "multiclass":
            if not getattr(est, "supports_multiclass_vmap", False):
                return False
        elif problem_type not in ("binary", "regression"):
            return False
        _, axes = est.batched_fit_fn()
        return Validator._constant_off_axis(est, grids, axes)

    def _streamable(self, est: PredictorEstimator, grids: List[ParamMap],
                    problem_type: str, X, n_folds: int) -> bool:
        """Large binary/regression GLM sweeps route through the streaming
        lane-batched kernel (ops/glm_sweep.py) — under a mesh, its
        shard_map variant (per-shard row scans, psum'd accumulators).
        Past TRI_MAX_D features the kernel switches internally to
        feature-tiled Gram accumulation, so width no longer excludes the
        route; the remaining guard is the per-iteration [L, d, d]
        Hessian-assembly + batched-solve footprint against the sweep HBM
        budget (lanes L = folds x grid points)."""
        if getattr(est, "streamed_loss", None) is None:
            return False
        if problem_type not in ("binary", "regression"):
            return False
        # an assigned across-time warm seed (retrain refit) is only
        # consumable by the streamed rounds kernel — a seeded refit
        # takes this route regardless of scale, else the seed would be
        # silently dropped (and warm_seeded honestly reported False).
        # The row floor is a plan-time decision (docs/planning.md): the
        # measured crossover between the streamed and vmapped kernels
        # at this (feat, lanes) shape, falling back to the hand
        # STREAMED_SWEEP_MIN_ROWS on a cold corpus / TMOG_PLAN=0 /
        # planner fault. A REASSIGNED module global is a hand override
        # and wins over the model — the same precedence an explicitly
        # set TMOG_* var gets
        min_rows = STREAMED_SWEEP_MIN_ROWS
        if min_rows == _STREAMED_SWEEP_MIN_ROWS_HAND:
            try:
                from ...planner.plan import glm_streamed_min_rows
                min_rows = glm_streamed_min_rows(
                    X.shape[1], n_folds * max(len(grids), 1))
            except Exception:
                min_rows = STREAMED_SWEEP_MIN_ROWS
        if X.shape[0] < min_rows \
                and getattr(self, "warm_seed", None) is None:
            return False
        from ...ops.glm_sweep import streamed_route_ok
        lanes = n_folds * max(len(grids), 1)
        if not streamed_route_ok(X.shape[1], lanes,
                                 SWEEP_LANE_BUDGET_BYTES):
            return False
        _, axes = est.batched_fit_fn()
        return self._constant_off_axis(est, grids, axes)

    # -- shared helpers for the device-sweep paths --------------------------
    def _margin_threshold(self, est) -> float:
        """Thresholded metrics: probability threshold t maps to margin
        logit(t) for probabilistic models; margin models cut at 0 (their
        decision rule)."""
        thr = float(getattr(self.evaluator, "threshold", 0.5))
        if getattr(est, "produces_probabilities", True) and 0.0 < thr < 1.0:
            return float(np.log(thr / (1.0 - thr)))
        return 0.0

    def _rank_bins(self, n_rows: int) -> Optional[int]:
        return RANK_METRIC_BINS if n_rows >= BINNED_RANK_METRIC_MIN_ROWS \
            else None

    def _auto_grid_chunk(self, n: int, d: int, n_folds: int,
                         itemsize: int, n_grids: int) -> int:
        if self.grid_chunk is not None:
            return max(1, int(self.grid_chunk))
        lane_bytes = max(n * d * itemsize, 1)
        if self.mesh is not None:  # rows shard: per-chip lane cost shrinks
            from ...parallel.mesh import BATCH_AXIS
            lane_bytes = max(
                lane_bytes // max(self.mesh.shape.get(BATCH_AXIS, 1), 1), 1)
        lanes = max(int(SWEEP_LANE_BUDGET_BYTES / lane_bytes), 1)
        # cap: total vmap lanes also scale XLA compile time — past ~8 grid
        # points per program the compile cost outweighs the dispatch savings
        return int(np.clip(lanes // max(n_folds, 1), 1, min(n_grids, 8)))

    def _device_arrays(self, X, y, w, masks, dtype):
        """Place sweep arrays on device; with a mesh, rows pad to the batch
        axis (zero weight = inert everywhere: fits see mask*w, metrics see
        (1-mask)*w) and shard across it."""
        if self.mesh is None:
            return (jnp.asarray(X, dtype), jnp.asarray(y, jnp.float32),
                    jnp.asarray(w, jnp.float32),
                    jnp.asarray(masks, jnp.float32))
        from ...parallel.mesh import (
            BATCH_AXIS, batch_sharding, mesh_is_multiprocess,
            pad_rows_to_multiple, sharded_along,
        )
        if mesh_is_multiprocess(self.mesh):
            # SPMD pod sweep: X/y/w/masks hold THIS PROCESS's rows; each
            # block lands as the process's batch-axis stripe of a global
            # array (same pad semantics as the single-host branch below:
            # X repeats its last row, weights pad 0 = inert, masks pad 1)
            from ...parallel import multihost as MH
            layout = MH.row_layout(np.asarray(X).shape[0], self.mesh)
            return (
                MH.host_local_block(
                    np.asarray(np.asarray(X), jnp.dtype(dtype)),
                    self.mesh, layout, pad_value=None),
                MH.host_local_block(np.asarray(y, np.float32),
                                    self.mesh, layout),
                MH.host_local_block(np.asarray(w, np.float32),
                                    self.mesh, layout),
                MH.host_local_block(np.asarray(masks, np.float32),
                                    self.mesh, layout, pad_value=1.0,
                                    axis=1),
            )
        nb = self.mesh.shape[BATCH_AXIS]
        # X pads by repeating the last real row (pad_value=None): tree
        # quantile binning is unweighted, so synthetic values would shift
        # bin edges. Labels/weights pad with zeros — inert in every
        # weighted reduction; masks pad with 1s (irrelevant under w=0).
        X, _ = pad_rows_to_multiple(np.asarray(X), nb, pad_value=None)
        y, _ = pad_rows_to_multiple(np.asarray(y, np.float32), nb)
        w, _ = pad_rows_to_multiple(np.asarray(w, np.float32), nb)
        masks = pad_rows_to_multiple(
            np.asarray(masks, np.float32).T, nb, pad_value=1.0)[0].T
        # device_put host arrays DIRECTLY with the sharding: jnp.asarray
        # first would commit the whole matrix to device 0 before resharding
        # — an OOM at exactly the >1-chip scale the mesh exists for
        put = jax.device_put
        return (
            put(np.asarray(X, jnp.dtype(dtype)), batch_sharding(self.mesh, 2)),
            put(np.asarray(y, np.float32), batch_sharding(self.mesh, 1)),
            put(np.asarray(w, np.float32), batch_sharding(self.mesh, 1)),
            put(np.asarray(masks, np.float32),
                sharded_along(self.mesh, 1, 2)),
        )

    def _sweep_path(self, base: str) -> str:
        """Checkpoint path tag: a mesh run pads rows (shifting tree bin
        edges and f32 reduction orders), so its metrics must not be
        replayed into a differently-sharded resume; externally supplied
        fold masks (workflow-level CV calls validate() once per fold,
        possibly on identical matrices when the in-fold DAG has no
        estimators) must not replay one fold's cells into another."""
        if self._external_mask_tag:
            base = f"{base}:masks{self._external_mask_tag}"
        if self.mesh is None:
            return base
        from ...parallel.mesh import BATCH_AXIS
        return f"{base}:mesh{self.mesh.shape.get(BATCH_AXIS, 1)}"

    def _cell_bookkeeping(self, est, grids, X, y, metric, n_folds,
                          path: str = ""):
        """(checkpoint, per-grid keys, finished results) — cell-level
        records shared across resumes of the SAME sweep path. `path` names
        the compute path and its statistically relevant knobs (mask-fold
        vs physically-split binning, sweep dtype): metrics from one path
        must never be replayed into another, since they can legitimately
        differ enough to flip the winner."""
        from .checkpoint import data_fingerprint, sweep_key
        ckpt = self._checkpoint()
        if ckpt is None:
            return None, [None] * len(grids), {}
        data_fp = data_fingerprint(X, y)
        base_params = est.param_values() if hasattr(est, "param_values") \
            else None
        # a custom metric is an arbitrary function: its identity must be
        # part of the cell key, or editing the function silently replays
        # the OLD function's cached fold metrics (the name alone is not a
        # fingerprint the way built-in metric names are)
        metric_key = getattr(self.evaluator, "metric_key", metric)
        keys = [sweep_key(type(est).__name__, g, n_folds,
                          self.seed, self.stratify, metric_key,
                          data_fp=data_fp, base_params=base_params,
                          path=path)
                for g in grids]
        results = {}
        for gi, key in enumerate(keys):
            done = ckpt.get(key)
            if done is not None:
                results[gi] = [float(v) for v in done["fold_metrics"]]
        return ckpt, keys, results

    def _validate_vmapped(self, est, grids, X, y, w, masks, metric,
                          problem_type) -> List[ValidatedModel]:
        """GLM-family sweep: ONE jitted program per grid chunk (vmap over
        folds x chunk). Chunking bounds the per-call HBM footprint — each
        lane materializes an [n, d] product for the Gram matmul — and gives
        the checkpoint mid-grid granularity (VERDICT r1 weak #9: the
        flagship vmapped sweep previously restarted from zero)."""
        base = est.copy(**{k: v for k, v in grids[0].items()})
        n_classes = int(np.max(y)) + 1 if problem_type == "multiclass" else 2
        if problem_type == "multiclass":
            fit_one, _ = base.batched_fit_fn(n_classes=n_classes)
        else:
            fit_one, _ = base.batched_fit_fn()
        regs, alphas = self._grid_axis_arrays(est, grids)
        margin_thr = self._margin_threshold(est)

        dtype = self.sweep_dtype or jnp.float32
        ckpt, keys, results = self._cell_bookkeeping(
            est, grids, X, y, metric, masks.shape[0],
            path=self._sweep_path(f"vmapped:{jnp.dtype(dtype).name}"))
        pending = [gi for gi in range(len(grids)) if gi not in results]
        if pending:
            from ...utils.metrics import collector
            Xd, yd, wd, md = self._device_arrays(X, y, w, masks, dtype)
            thr_d = jnp.asarray(margin_thr, jnp.float32)
            rank_bins = self._rank_bins(X.shape[0])
            chunk = self._auto_grid_chunk(
                X.shape[0], X.shape[1], masks.shape[0],
                jnp.dtype(dtype).itemsize, len(pending))
            for start in range(0, len(pending), chunk):
                idx = pending[start:start + chunk]
                # pad the tail chunk so every call shares one compiled shape
                padded = idx + [idx[-1]] * (chunk - len(idx))
                with collector.trace_span(
                        f"glm_vmapped:{type(est).__name__}",
                        kind="sweep_fit", folds=int(masks.shape[0]),
                        chunk=chunk):
                    out = _sweep(Xd, yd, wd, md,
                                 jnp.asarray(regs[padded]),
                                 jnp.asarray(alphas[padded]), thr_d,
                                 fit_one=fit_one, metric=metric,
                                 problem_type=problem_type,
                                 n_classes=n_classes, rank_bins=rank_bins)
                    out = np.asarray(out)  # [F, chunk]
                for j, gi in enumerate(idx):
                    fm = [float(v) for v in out[:, j]]
                    results[gi] = fm
                    if ckpt is not None:
                        ckpt.record(keys[gi], type(est).__name__, grids[gi],
                                    fm, metric)
                    self._cell_event(est, gi, fm, "vmapped")
        return [
            ValidatedModel(model_name=type(est).__name__, model_uid=est.uid,
                           grid=g, metric_name=metric,
                           fold_metrics=results[gi], route="vmapped")
            for gi, g in enumerate(grids)
        ]

    @staticmethod
    def _grid_axis_arrays(est, grids) -> Tuple[np.ndarray, np.ndarray]:
        """Per-grid (regs, alphas) along the estimator's sweep axes —
        shared by the vmapped and streamed paths."""
        _, axes = est.batched_fit_fn()
        regs = np.array([g.get(axes[0], est.get_param(axes[0]))
                         for g in grids], np.float32)
        second = axes[1] if len(axes) > 1 else None
        alphas = np.array([g.get(second, est.get_param(second)) if second
                           else 0.0 for g in grids], np.float32)
        return regs, alphas

    # -- streamed GLM path --------------------------------------------------
    _STREAMED_EVAL_CHUNK = 8

    def _round_checkpoint(self, keys, pending, fit_kwargs):
        """(RoundCheckpoint, key, resumable state) for the round driver —
        keyed by the pending cells' sweep keys (which already fold in the
        data fingerprint, masks, base params and compute path) plus the
        solver knobs, so state from a different sweep is never replayed."""
        if self.checkpoint_path is None or keys[0] is None:
            return None, None, None
        import hashlib
        import json as _json

        from .checkpoint import RoundCheckpoint
        payload = _json.dumps(
            [[keys[gi] for gi in pending],
             {k: repr(v) for k, v in sorted(fit_kwargs.items())},
             os.environ.get("TMOG_GLM_ROUND_ITERS", "")], sort_keys=True)
        rkey = hashlib.sha256(payload.encode()).hexdigest()[:24]
        rc = RoundCheckpoint(self.checkpoint_path + ".glm_rounds.npz")
        return rc, rkey, rc.load(rkey)

    @staticmethod
    def _cell_event(est, gi, fm, route):
        """One `sweep_cell_landed` event per finished (model x grid) cell
        (all fold metrics exist) — the resumable unit of the sweep
        checkpoint, streamed so `tail -f events.jsonl` shows sweep
        progress cell by cell."""
        from ...utils.metrics import collector
        finite = [v for v in fm if np.isfinite(v)]
        collector.event(
            "sweep_cell_landed", model=type(est).__name__,
            grid_index=int(gi), route=route, n_folds=len(fm),
            mean_metric=float(np.mean(finite)) if finite else None)

    def _record_sweep_telemetry(self, est, info):
        self.last_streamed_telemetry = dict(info,
                                            model=type(est).__name__)
        from ...utils.metrics import collector
        if collector.enabled:
            collector.sweep_convergence(
                family=type(est).__name__, kernel=info["kernel"],
                rounds=info.get("glm_rounds", 0),
                data_passes=info.get("data_passes", 0),
                lane_passes=info.get("lane_passes", 0),
                lanes_total=info.get("lanes_total", 0),
                lanes_retired=info.get("lanes_retired", 0),
                active_per_round=info.get("active_per_round", ()),
                iters_per_round=info.get("iters_per_round", ()),
                bucket_sizes=info.get("bucket_sizes", ()))

    def _streamed_fit(self, est, fit_kwargs, Xd, yd, wd, md, regs_p,
                      alphas_p, keys, pending):
        """Fit every pending (fold x grid) lane through the best streamed
        kernel for the loss (docs/performance.md "Convergence-aware GLM
        sweep"): squared loss -> sufficient-statistics Gram fast path
        (ONE streaming pass for the whole sweep); IRLS losses -> the
        host-driven round loop with per-lane retirement and bucket-ladder
        compaction (round-granular checkpointing when a checkpoint path is
        set); TMOG_GLM_GRAM=0 / TMOG_GLM_ROUNDS=0 fall back to the legacy
        single-program global-max route. Returns (B [F, Gp, d] jnp RAW
        units, b0, telemetry info dict, round-checkpoint or None — the
        CALLER clears it only after the cells land in the JSONL
        checkpoint, so a preemption during metric evaluation still
        resumes from the fully-retired round state instead of
        refitting)."""
        from ...ops import glm_sweep as GS

        loss = fit_kwargs["loss"]
        F = int(md.shape[0])
        L = F * len(pending)
        if loss == "squared" and GS.env_on("TMOG_GLM_GRAM"):
            fk = {k: v for k, v in fit_kwargs.items() if k != "loss"}
            mi, tl = fk.pop("max_iter"), fk.pop("tol")
            if self.mesh is not None:
                B, b0, giters = GS.sweep_glm_squared_gram_sharded(
                    self.mesh, Xd, yd, wd, md, regs_p, alphas_p, mi, tl,
                    **fk)
            else:
                B, b0, giters = GS.sweep_glm_squared_gram(
                    Xd, yd, wd, md, regs_p, alphas_p, mi, tl, **fk)
            info = {"route": "streamed", "kernel": "gram",
                    "glm_rounds": 1, "data_passes": 1, "lane_passes": F,
                    "padded_lane_passes": F,  # the Gram pass never pads
                    "lanes_total": L, "lanes_retired": L,
                    "gram_solve_iters": int(giters)}
            return B, b0, info, None
        if loss != "squared" and GS.env_on("TMOG_GLM_ROUNDS"):
            rc, rkey, state = self._round_checkpoint(keys, pending,
                                                     fit_kwargs)
            from ...utils.metrics import collector

            def on_round(st):
                # one event per retirement boundary: the tail of
                # events.jsonl IS the live convergence picture of a
                # multi-hour sweep (GLM round retired / checkpoint saved)
                if rc is not None:
                    rc.save(rkey, st)
                    collector.event("round_checkpoint_written",
                                    path=rc.path, rounds=int(st["rounds"]))
                collector.event(
                    "glm_round_retired", rounds=int(st["rounds"]),
                    lanes_retired=int(st["retired"].sum()),
                    lanes_active=int((~st["retired"]).sum()),
                    lane_passes=int(st["lane_passes"]))
            # across-time warm seed (retrain refit): the previous
            # champion's raw coefficients, threaded selector -> validator
            # (ModelSelector.fit_arrays). The sweep ignores a seed whose
            # dimension disagrees with this vectorization.
            seed = getattr(self, "warm_seed", None)
            seed_t = None
            if isinstance(seed, dict) and seed.get("beta") is not None:
                seed_t = (np.asarray(seed["beta"], np.float32),
                          float(seed.get("intercept", 0.0)))
            B, b0, info = GS.sweep_glm_streamed_rounds(
                Xd, yd, wd, md, np.asarray(regs_p), np.asarray(alphas_p),
                mesh=self.mesh, state=state, on_round=on_round,
                warm_seed=seed_t, **fit_kwargs)
            return jnp.asarray(B), jnp.asarray(b0), info, rc
        if self.mesh is not None:
            B, b0 = GS.sweep_glm_streamed_sharded(
                self.mesh, Xd, yd, wd, md, regs_p, alphas_p, **fit_kwargs)
        else:
            B, b0 = GS.sweep_glm_streamed(Xd, yd, wd, md, regs_p,
                                          alphas_p, **fit_kwargs)
        return B, b0, {"route": "streamed", "kernel": "global",
                       "lanes_total": L}, None

    def _validate_streamed(self, est, grids, X, y, w, masks, metric,
                           problem_type) -> List[ValidatedModel]:
        """Streamed convergence-aware sweep: every pending (fold x grid)
        cell fits through _streamed_fit (Gram fast path / retirement round
        driver / legacy single program); metrics then run per fold in grid
        chunks of one scoring matmul each."""
        regs, alphas = self._grid_axis_arrays(est, grids)
        # constant off-axis grid keys (admitted by _constant_off_axis) must
        # bind exactly as on the vmapped path: est.copy(**grids[0])
        base = est.copy(**{k: v for k, v in grids[0].items()})
        margin_thr = self._margin_threshold(est)
        dtype = self.sweep_dtype or jnp.float32
        # stale telemetry must never survive into a sweep that runs no fit
        # (fully checkpoint-resumed): bench would pair a previous sweep's
        # lane_passes with this sweep's near-zero wall
        self.last_streamed_telemetry = None
        ckpt, keys, results = self._cell_bookkeeping(
            est, grids, X, y, metric, masks.shape[0],
            path=self._sweep_path(f"streamed:{jnp.dtype(dtype).name}"))
        pending = [gi for gi in range(len(grids)) if gi not in results]
        if pending:
            from ...utils.metrics import collector
            Xd, yd, wd, md = self._device_arrays(X, y, w, masks, dtype)
            fit_kwargs = dict(
                loss=est.streamed_loss,
                max_iter=int(base.get_param("max_iter")),
                tol=float(base.get_param("tol")),
                fit_intercept=bool(base.get_param("fit_intercept"))
                if base.has_param("fit_intercept") else True,
                standardize=bool(base.get_param("standardization"))
                if base.has_param("standardization") else True)
            with collector.trace_span(
                    f"glm_streamed:{type(est).__name__}", kind="sweep_fit",
                    folds=int(masks.shape[0]), grids=len(pending)) as sp:
                B, b0, sweep_info, round_ckpt = self._streamed_fit(
                    est, fit_kwargs, Xd, yd, wd, md,
                    jnp.asarray(regs[pending]), jnp.asarray(alphas[pending]),
                    keys, pending)
                if sp is not None:
                    sp.attrs["kernel"] = sweep_info.get("kernel")
            self._record_sweep_telemetry(est, sweep_info)
            rank_bins = self._rank_bins(X.shape[0])
            thr_d = jnp.asarray(margin_thr, jnp.float32)
            chunk = min(self._STREAMED_EVAL_CHUNK, len(pending))
            out = np.empty((masks.shape[0], len(pending)), np.float64)
            with collector.trace_span(
                    f"glm_streamed_eval:{type(est).__name__}",
                    kind="sweep_eval", cells=len(pending)):
                for f in range(masks.shape[0]):
                    vw = (1.0 - md[f]) * wd
                    for s in range(0, len(pending), chunk):
                        idx = list(range(s, min(s + chunk, len(pending))))
                        padded = idx + [idx[-1]] * (chunk - len(idx))
                        vals = _streamed_eval(
                            Xd, yd, vw, B[f, jnp.asarray(padded)],
                            b0[f, jnp.asarray(padded)], thr_d, metric=metric,
                            problem_type=problem_type, rank_bins=rank_bins,
                            chunk=chunk, use_lanes=self.mesh is None)
                        out[f, idx] = np.asarray(vals)[:len(idx)]
            for j, gi in enumerate(pending):
                fm = [float(v) for v in out[:, j]]
                results[gi] = fm
                if ckpt is not None:
                    ckpt.record(keys[gi], type(est).__name__, grids[gi],
                                fm, metric)
                self._cell_event(est, gi, fm, "streamed")
            if round_ckpt is not None:
                # only NOW are all cells in the JSONL checkpoint: a
                # preemption during the evaluation above resumes from the
                # fully-retired round state instead of refitting
                round_ckpt.clear()
        return [
            ValidatedModel(model_name=type(est).__name__, model_uid=est.uid,
                           grid=g, metric_name=metric,
                           fold_metrics=results[gi], route="streamed")
            for gi, g in enumerate(grids)
        ]

    # -- mask-fold tree path ------------------------------------------------
    def _validate_mask_folds(self, est, grids, X, y, w, masks, metric,
                             problem_type) -> List[ValidatedModel]:
        """Tree-family sweep with folds as weight masks: the feature matrix
        is quantile-binned ONCE on device, then every (grid, fold) fit runs
        against it with the fold's training mask as sample weights — no host
        slicing, no per-fold data movement (VERDICT r1: the sequential
        fallback re-sliced X per fold, 'exactly the Spark-era shape'). The
        fold axis is vmapped; grids stay sequential because tree params
        (depth, rounds) are XLA-static."""
        n_classes = int(np.max(y)) + 1 if problem_type == "multiclass" else 2
        margin_thr = self._margin_threshold(est)
        ckpt, keys, results = self._cell_bookkeeping(
            est, grids, X, y, metric, masks.shape[0],
            path=self._sweep_path(
                "mask_folds:host" if (self.mesh is None
                                      and est._host_route())
                else "mask_folds"))
        pending = [gi for gi in range(len(grids)) if gi not in results]
        fused_gis: Dict[int, str] = {}   # cell -> fused route label
        # ("mask_folds:grid_fused" / ":grid_fused_sharded" on a mesh) —
        # route attribution for bench/MFU readers
        # consecutive fused-route failure escalation: one sweep-level
        # warning on first failure, silent per-config fallback while the
        # streak stays short, a raise once it reaches the cap
        fuse_fail_streak = 0
        fuse_failures = 0
        fuse_max_failures = grid_fuse_max_failures()
        if pending:
            # trees only read X through quantile binning, so the bf16 sweep
            # dtype is safe here too and halves the resident matrix
            Xd, yd, wd, md = self._device_arrays(
                X, y, w, masks, self.sweep_dtype or jnp.float32)
            rank_bins = self._rank_bins(X.shape[0])
            mfn = _metric_fn(problem_type, metric, n_classes, rank_bins)
            thr_d = jnp.asarray(margin_thr, jnp.float32)
            # mesh runs keep the vmapped metric (pallas must not consume
            # row-sharded operands)
            lanes_fn = _lanes_metric_fn(metric, problem_type, rank_bins) \
                if self.mesh is None else None

            @jax.jit
            def fold_metrics(scores, y_, w_, m_, t_):
                if lanes_fn is not None:
                    # scores [F, n]: all folds through ONE lane-batched
                    # binned-counts kernel (pallas on TPU; a fold-vmapped
                    # scatter-add would serialize there)
                    return lanes_fn(scores, y_, (1.0 - m_) * w_[None, :])

                def per_fold(s, m):
                    return mfn(s, y_, (1.0 - m) * w_, t_)
                return jax.vmap(per_fold)(scores, m_)

            # the binned context depends on max_bins, which may itself be a
            # grid axis — group grids by value and bin once per GROUP,
            # releasing each multi-GB [n, d] binned matrix before the next
            # (three live contexts at the 10M config would eat the HBM
            # budget the lane chunker assumes)
            def bins_of(gi):
                g = grids[gi]
                if "max_bins" in g:
                    return g["max_bins"]
                return est.get_param("max_bins") \
                    if est.has_param("max_bins") else None

            groups: Dict[Any, List[int]] = {}
            for gi in pending:
                groups.setdefault(bins_of(gi), []).append(gi)
            multicls = problem_type == "multiclass"
            from ...utils.metrics import collector

            # config-fusion gate, resolved ONCE per sweep through the
            # plan-time autotuner (docs/planning.md): an explicitly-set
            # TMOG_GRID_FUSE wins either way (hand beats model, logged
            # as plan_override); otherwise fusion turns on only when the
            # corpus measured the fused route faster AND the planned
            # out-block clears the compile-knee term — the 20-minute
            # Mosaic compile r5 paid is now rejected at plan time. Cold
            # corpus keeps today's opt-in default (off).
            def depth_of(gi):
                g = grids[gi]
                if "max_depth" in g:
                    return int(g["max_depth"])
                return int(est.get_param("max_depth")) \
                    if est.has_param("max_depth") else 0
            n_shards = 1
            if self.mesh is not None:
                from ...parallel.mesh import BATCH_AXIS
                n_shards = max(self.mesh.shape.get(BATCH_AXIS, 1), 1)
            try:
                from ...planner.plan import grid_fuse_enabled
                plan_fuse_on = grid_fuse_enabled(
                    n_rows=X.shape[0], n_feat=X.shape[1],
                    n_folds=masks.shape[0], n_grids=len(pending),
                    depth=max((depth_of(gi) for gi in pending),
                              default=0),
                    n_bins=int(max((b for b in groups if b), default=0)
                               or 0),
                    n_shards=n_shards)
            except Exception:
                # the degraded path must keep today's hand behavior
                # EXACTLY: the pre-planner gate was an opt-IN whitelist
                # (env_on's falsy-list parse would flip fusion ON for
                # nonstandard truthy spellings like "yes")
                plan_fuse_on = os.environ.get(
                    "TMOG_GRID_FUSE", "").strip().lower() \
                    in ("1", "true", "on")
            for _, group in sorted(groups.items(), key=lambda kv: str(kv[0])):
                # n_valid: mesh runs pad rows (repeat-last) — the quantile
                # sketch must see only the real rows so mesh and meshless
                # sweeps grow from identical bin edges
                ctx = est.copy(**grids[group[0]]).mask_sweep_context(
                    Xd, n_valid=X.shape[0], mesh=self.mesh)

                def record(gi, scores_f, route=None):
                    out = np.asarray(fold_metrics(scores_f, yd, wd, md,
                                                  thr_d))
                    fm = [float(v) for v in out]
                    results[gi] = fm
                    if ckpt is not None:
                        ckpt.record(keys[gi], type(est).__name__, grids[gi],
                                    fm, metric)
                    self._cell_event(est, gi, fm, route or "mask_folds")

                # config fusion: grid points whose structural signature
                # matches fit ONE fold-fused device program (lanes =
                # configs x folds) — one histogram pass serves them all
                sig_of = getattr(est, "grid_fuse_signature", lambda g: None)
                sig_groups: Dict[Any, List[int]] = {}
                for gi in group:
                    sig = sig_of(grids[gi])
                    key = ("solo", gi) if sig is None else ("fuse", sig)
                    sig_groups.setdefault(key, []).append(gi)
                for key, gis in sig_groups.items():
                    fused = None
                    # the widened-M hist programs are bitwise-correct
                    # (ops-level parity suite) but their Mosaic compiles
                    # ran 20+ minutes at the 2M x 20-lane shape on first
                    # hardware contact — plan_fuse_on (resolved above)
                    # keeps fusion opt-in until measured evidence clears
                    # both the wall and the compile knee
                    if key[0] == "fuse" and len(gis) > 1 and plan_fuse_on:
                        try:
                            fused = est.mask_fit_scores_grid(
                                ctx, yd, wd, md, [grids[gi] for gi in gis],
                                n_classes=n_classes, multiclass=multicls,
                                mesh=self.mesh)
                        except Exception as e:  # never lose the sweep to
                            # the fast path: per-config route is the
                            # correctness baseline — but a route that
                            # fails REPEATEDLY is a broken kernel, not a
                            # per-config nuisance: count the streak, warn
                            # once at sweep level, raise at the cap
                            fuse_fail_streak += 1
                            fuse_failures += 1
                            collector.event(
                                "fused_route_fallback",
                                model=type(est).__name__,
                                error_type=type(e).__name__,
                                streak=fuse_fail_streak,
                                configs=len(gis))
                            if fuse_fail_streak >= fuse_max_failures:
                                raise RuntimeError(
                                    f"config-fused sweep route failed "
                                    f"{fuse_fail_streak} consecutive "
                                    f"times (last: {type(e).__name__}: "
                                    f"{e}); the fused kernel path is "
                                    f"dead — fix it or unset "
                                    f"TMOG_GRID_FUSE") from e
                            import logging
                            logger = logging.getLogger(__name__)
                            if fuse_failures == 1:
                                logger.warning(
                                    "config-fused sweep failed (%s); "
                                    "falling back per-config (further "
                                    "failures logged at DEBUG; raising "
                                    "after %d consecutive)", e,
                                    fuse_max_failures)
                            else:
                                logger.debug(
                                    "config-fused sweep failure %d: %s",
                                    fuse_failures, e)
                            fused = None
                    if fused is not None:
                        fuse_fail_streak = 0
                        # the estimator stamps which fused form ran
                        # (sharded on a mesh) right before returning
                        grid_route = "mask_folds:" + getattr(
                            est, "_last_grid_route", "grid_fused")
                        for k, gi in enumerate(gis):
                            record(gi, fused[k], route=grid_route)
                            fused_gis[gi] = grid_route
                        continue
                    for gi in gis:
                        est_g = est.copy(**grids[gi])
                        record(gi, est_g.mask_fit_scores(
                            ctx, yd, wd, md, n_classes=n_classes,
                            multiclass=multicls))
                del ctx  # free the binned matrix before the next group
            if fuse_failures:
                import logging
                logging.getLogger(__name__).warning(
                    "config-fused sweep: %d group(s) fell back to the "
                    "per-config route this sweep", fuse_failures)
        return [
            ValidatedModel(model_name=type(est).__name__, model_uid=est.uid,
                           grid=g, metric_name=metric,
                           fold_metrics=results[gi],
                           route=fused_gis.get(gi, "mask_folds"))
            for gi, g in enumerate(grids)
        ]

    # -- sequential fallback ----------------------------------------------
    def _checkpoint(self):
        if self.checkpoint_path is None:
            return None
        from .checkpoint import SweepCheckpoint
        return SweepCheckpoint(self.checkpoint_path)

    def _validate_sequential(self, est, grids, X, y, w, masks
                             ) -> List[ValidatedModel]:
        metric = self.evaluator.default_metric
        ckpt, keys, results = self._cell_bookkeeping(
            est, grids, X, y, metric, masks.shape[0],
            path=self._sweep_path(
                "sequential:host"
                if getattr(est, "_host_route", lambda: False)()
                else "sequential"))
        for gi, g in enumerate(grids):
            if gi in results:
                continue
            est_g = est.copy(**g)
            fold_vals: List[float] = []
            for f in range(masks.shape[0]):
                tr = masks[f] > 0
                va = ~tr
                model = est_g.fit_arrays(X[tr], y[tr], w[tr])
                pred, raw, prob = model.predict_arrays(X[va])
                col = make_prediction_column(pred, raw, prob)
                fold_vals.append(self.evaluator.evaluate(y[va], col, w[va]))
            results[gi] = fold_vals
            if ckpt is not None:
                ckpt.record(keys[gi], type(est).__name__, g, fold_vals,
                            metric)
            self._cell_event(est, gi, fold_vals, "sequential")
        return [
            ValidatedModel(model_name=type(est).__name__, model_uid=est.uid,
                           grid=g, metric_name=metric,
                           fold_metrics=results[gi], route="sequential")
            for gi, g in enumerate(grids)
        ]


class CrossValidation(Validator):
    """k-fold CV (reference OpCrossValidation.scala:41; NumFolds default 3)."""

    def __init__(self, evaluator: Evaluator, num_folds: int = 3,
                 seed: int = 42, stratify: bool = False, parallelism: int = 8,
                 **kwargs):
        super().__init__(evaluator, seed=seed, stratify=stratify,
                         parallelism=parallelism, **kwargs)
        if num_folds < 2:
            raise ValueError("num_folds must be >= 2")
        self.num_folds = int(num_folds)

    def fold_masks(self, y: np.ndarray) -> np.ndarray:
        fold_of = self._assign_folds(y, self.num_folds)
        masks = np.ones((self.num_folds, len(y)), np.float32)
        for f in range(self.num_folds):
            masks[f, fold_of == f] = 0.0
        return masks


class TrainValidationSplit(Validator):
    """Single split (reference OpTrainValidationSplit.scala:34;
    TrainRatio default 0.75)."""

    def __init__(self, evaluator: Evaluator, train_ratio: float = 0.75,
                 seed: int = 42, stratify: bool = False, parallelism: int = 8,
                 **kwargs):
        super().__init__(evaluator, seed=seed, stratify=stratify,
                         parallelism=parallelism, **kwargs)
        if not 0.0 < train_ratio < 1.0:
            raise ValueError("train_ratio must be in (0, 1)")
        self.train_ratio = float(train_ratio)

    def fold_masks(self, y: np.ndarray) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        n = len(y)
        mask = np.ones((1, n), np.float32)
        if self.stratify:
            for cls in np.unique(y):
                idx = np.flatnonzero(y == cls)
                rng.shuffle(idx)
                n_val = int(round(len(idx) * (1.0 - self.train_ratio)))
                mask[0, idx[:n_val]] = 0.0
        else:
            perm = rng.permutation(n)
            n_val = int(round(n * (1.0 - self.train_ratio)))
            mask[0, perm[:n_val]] = 0.0
        return mask
