"""Validators: cross-validation and train/validation split.

Reference: core/.../impl/tuning/{OpValidator.scala:94, OpCrossValidation.scala:41,
OpTrainValidationSplit.scala:34}. The reference evaluates every
(model x ParamMap) per fold on an 8-thread pool (OpValidator.scala:318) with
physical per-fold datasets (MLUtils.kFold).

TPU-first redesign: folds are *weight masks* over the in-HBM feature matrix —
no data movement between folds. For GLM-family estimators the whole
(fold x grid) sweep is ONE jitted program: `vmap` over fold masks and
hyperparameter leaves, fit by fixed-iteration Newton, score with one matmul,
evaluate with mask-weighted metric kernels. Non-vmappable estimators (trees,
naive Bayes) fall back to a per-(fold, grid) loop over sliced arrays.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...evaluators.evaluators import Evaluator
from ...models.base import PredictionModel, PredictorEstimator
from ...models.prediction import make_prediction_column
from ...ops import metrics_ops as M
from ...stages.params import ParamMap


@dataclass
class ValidatedModel:
    """Validation record for one (estimator, grid point) — reference
    ModelEvaluation entries in ModelSelectorSummary."""

    model_name: str
    model_uid: str
    grid: ParamMap
    metric_name: str
    fold_metrics: List[float]

    @property
    def mean_metric(self) -> float:
        vals = [v for v in self.fold_metrics if np.isfinite(v)]
        return float(np.mean(vals)) if vals else float("nan")


@dataclass
class BestEstimator:
    """Winner of validation (reference OpValidator.wrapBestEstimator:147)."""

    name: str
    estimator: PredictorEstimator  # configured with the best grid
    best_grid: ParamMap
    best_metric: float
    validated: List[ValidatedModel] = field(default_factory=list)


def _metric_fn(problem_type: str, metric: str, n_classes: int = 2) -> Callable:
    """Pure-jax (scores, labels, weights, margin_threshold) -> scalar used
    inside the vmapped sweep. Binary scores are margins (monotone in
    probability, so rank metrics match); thresholded metrics use the margin
    equivalent of the evaluator's probability threshold (logit for
    probabilistic models). The threshold is a traced scalar so distinct
    evaluator thresholds do NOT trigger sweep-kernel recompiles. Multiclass
    scores are [n, c] logits; argmax is invariant to softmax, so class
    metrics come straight from the confusion matmul
    (OpMultiClassificationEvaluator.scala:58)."""
    if problem_type == "binary":
        if metric == "au_pr":
            return lambda s, y, w, thr: M.au_pr(s, y, w)
        if metric == "au_roc":
            return lambda s, y, w, thr: M.au_roc(s, y, w)
        def bin_m(s, y, w, thr, _m=metric):
            return getattr(M.binary_metrics(s, y, w, threshold=thr), _m)
        return bin_m
    if problem_type == "multiclass":
        def multi_m(s, y, w, thr, _m=metric, _k=n_classes):
            pred = jnp.argmax(s, axis=1)
            return getattr(M.multiclass_metrics(pred, y, _k, w), _m)
        return multi_m
    if problem_type == "regression":
        def reg_m(p, y, w, thr, _m=metric):
            return getattr(M.regression_metrics(p, y, w), _m)
        return reg_m
    raise ValueError(f"No vmapped metric for problem type {problem_type}")


@partial(jax.jit,
         static_argnames=("fit_one", "metric", "problem_type", "n_classes"))
def _sweep(X, y, w, fold_masks, regs, alphas, margin_threshold, *, fit_one,
           metric, problem_type, n_classes=2):
    """The sweep kernel: metrics[F, G] for F fold masks x G grid points.

    One XLA program: on a row-sharded X every Gram-matrix reduction inside
    fit_one becomes an ICI psum; fold/grid axes are embarrassingly parallel
    (vmap) and can additionally be laid out on the `model` mesh axis.
    Multiclass fit_one returns (B [d, c], b0 [c]) and the same `X @ beta + b0`
    scoring broadcasts to [n, c] logits.
    """
    mfn = _metric_fn(problem_type, metric, n_classes)

    def one(mask, reg, alpha):
        beta, b0 = fit_one(X, y, mask * w, reg, alpha)
        score = X @ beta + b0
        return mfn(score, y, (1.0 - mask) * w, margin_threshold)

    per_grid = jax.vmap(lambda m: jax.vmap(partial(one, m))(regs, alphas))
    return per_grid(fold_masks)


class Validator:
    """Base validator (reference OpValidator.scala:94)."""

    def __init__(self, evaluator: Evaluator, seed: int = 42,
                 stratify: bool = False, parallelism: int = 8):
        self.evaluator = evaluator
        self.seed = int(seed)
        self.stratify = bool(stratify)
        # kept for API parity; device vmap replaces the thread pool
        self.parallelism = int(parallelism)
        # optional sweep checkpoint (resume skips finished model x grid cells)
        self.checkpoint_path: Optional[str] = None

    # -- folds -------------------------------------------------------------
    def fold_masks(self, y: np.ndarray) -> np.ndarray:
        """[F, n] float32 train-membership masks (1=train, 0=validation)."""
        raise NotImplementedError

    def _assign_folds(self, y: np.ndarray, n_folds: int) -> np.ndarray:
        """Per-row fold id; stratified round-robin within each class when
        stratify is on (reference prepareStratification:203)."""
        rng = np.random.default_rng(self.seed)
        n = len(y)
        fold_of = np.empty(n, np.int32)
        if self.stratify:
            for cls in np.unique(y):
                idx = np.flatnonzero(y == cls)
                rng.shuffle(idx)
                fold_of[idx] = np.arange(len(idx)) % n_folds
        else:
            perm = rng.permutation(n)
            fold_of[perm] = np.arange(n) % n_folds
        return fold_of

    # -- validation --------------------------------------------------------
    def validate(self, models: Sequence[Tuple[PredictorEstimator, List[ParamMap]]],
                 X: np.ndarray, y: np.ndarray,
                 w: Optional[np.ndarray] = None,
                 problem_type: str = "binary") -> BestEstimator:
        if w is None:
            w = np.ones_like(y, np.float32)
        masks = self.fold_masks(y)
        metric = self.evaluator.default_metric
        larger = self.evaluator.is_larger_better()

        validated: List[ValidatedModel] = []
        for est, grids in models:
            if not grids:
                grids = [dict()]
            if self._vmappable(est, grids, problem_type):
                validated.extend(self._validate_vmapped(
                    est, grids, X, y, w, masks, metric, problem_type))
            else:
                validated.extend(self._validate_sequential(
                    est, grids, X, y, w, masks))

        if not validated:
            raise ValueError("No models to validate")
        key = (lambda v: v.mean_metric if np.isfinite(v.mean_metric)
               else (-np.inf if larger else np.inf))
        best = max(validated, key=key) if larger else min(validated, key=key)
        winner = next(e for e, _ in models
                      if e.uid == best.model_uid).copy(**best.grid)
        return BestEstimator(name=best.model_name, estimator=winner,
                             best_grid=best.grid,
                             best_metric=best.mean_metric, validated=validated)

    # -- vmapped GLM path --------------------------------------------------
    @staticmethod
    def _vmappable(est: PredictorEstimator, grids: List[ParamMap],
                   problem_type: str) -> bool:
        if not getattr(est, "supports_grid_vmap", False):
            return False
        if problem_type == "multiclass":
            if not getattr(est, "supports_multiclass_vmap", False):
                return False
        elif problem_type not in ("binary", "regression"):
            return False
        _, axes = est.batched_fit_fn()
        # every non-axis grid key must be constant across the grid (those
        # become static jit args via copy)
        others = {k for g in grids for k in g if k not in axes}
        for k in others:
            vals = {repr(g.get(k, est.get_param(k))) for g in grids}
            if len(vals) > 1:
                return False
        return True

    def _validate_vmapped(self, est, grids, X, y, w, masks, metric,
                          problem_type) -> List[ValidatedModel]:
        base = est.copy(**{k: v for k, v in grids[0].items()})
        n_classes = int(np.max(y)) + 1 if problem_type == "multiclass" else 2
        if problem_type == "multiclass":
            fit_one, axes = base.batched_fit_fn(n_classes=n_classes)
        else:
            fit_one, axes = base.batched_fit_fn()
        regs = np.array([g.get(axes[0], est.get_param(axes[0]))
                         for g in grids], np.float32)
        second = axes[1] if len(axes) > 1 else None
        alphas = np.array([g.get(second, est.get_param(second)) if second
                           else 0.0 for g in grids], np.float32)
        # thresholded metrics: probability threshold t maps to margin logit(t)
        # for probabilistic models; margin models cut at 0 (their decision rule)
        thr = float(getattr(self.evaluator, "threshold", 0.5))
        if getattr(est, "produces_probabilities", True) and 0.0 < thr < 1.0:
            margin_thr = float(np.log(thr / (1.0 - thr)))
        else:
            margin_thr = 0.0
        out = _sweep(jnp.asarray(X, jnp.float32), jnp.asarray(y, jnp.float32),
                     jnp.asarray(w, jnp.float32),
                     jnp.asarray(masks, jnp.float32),
                     jnp.asarray(regs), jnp.asarray(alphas),
                     jnp.asarray(margin_thr, jnp.float32),
                     fit_one=fit_one, metric=metric,
                     problem_type=problem_type, n_classes=n_classes)
        out = np.asarray(out)  # [F, G]
        return [
            ValidatedModel(model_name=type(est).__name__, model_uid=est.uid,
                           grid=g, metric_name=metric,
                           fold_metrics=[float(v) for v in out[:, gi]])
            for gi, g in enumerate(grids)
        ]

    # -- sequential fallback ----------------------------------------------
    def _checkpoint(self):
        if self.checkpoint_path is None:
            return None
        from .checkpoint import SweepCheckpoint
        return SweepCheckpoint(self.checkpoint_path)

    def _validate_sequential(self, est, grids, X, y, w, masks
                             ) -> List[ValidatedModel]:
        from .checkpoint import data_fingerprint, sweep_key
        metric = self.evaluator.default_metric
        ckpt = self._checkpoint()
        data_fp = data_fingerprint(X, y) if ckpt is not None else ""
        base_params = est.param_values() if hasattr(est, "param_values") \
            else None
        out: List[ValidatedModel] = []
        for g in grids:
            key = sweep_key(type(est).__name__, g, masks.shape[0],
                            self.seed, self.stratify, metric,
                            data_fp=data_fp, base_params=base_params)
            if ckpt is not None:
                done = ckpt.get(key)
                if done is not None:
                    out.append(ValidatedModel(
                        model_name=type(est).__name__, model_uid=est.uid,
                        grid=g, metric_name=metric,
                        fold_metrics=[float(v)
                                      for v in done["fold_metrics"]]))
                    continue
            est_g = est.copy(**g)
            fold_vals: List[float] = []
            for f in range(masks.shape[0]):
                tr = masks[f] > 0
                va = ~tr
                model = est_g.fit_arrays(X[tr], y[tr], w[tr])
                pred, raw, prob = model.predict_arrays(X[va])
                col = make_prediction_column(pred, raw, prob)
                fold_vals.append(self.evaluator.evaluate(y[va], col, w[va]))
            if ckpt is not None:
                ckpt.record(key, type(est).__name__, g, fold_vals, metric)
            out.append(ValidatedModel(
                model_name=type(est).__name__, model_uid=est.uid, grid=g,
                metric_name=metric, fold_metrics=fold_vals))
        return out


class CrossValidation(Validator):
    """k-fold CV (reference OpCrossValidation.scala:41; NumFolds default 3)."""

    def __init__(self, evaluator: Evaluator, num_folds: int = 3,
                 seed: int = 42, stratify: bool = False, parallelism: int = 8):
        super().__init__(evaluator, seed=seed, stratify=stratify,
                         parallelism=parallelism)
        if num_folds < 2:
            raise ValueError("num_folds must be >= 2")
        self.num_folds = int(num_folds)

    def fold_masks(self, y: np.ndarray) -> np.ndarray:
        fold_of = self._assign_folds(y, self.num_folds)
        masks = np.ones((self.num_folds, len(y)), np.float32)
        for f in range(self.num_folds):
            masks[f, fold_of == f] = 0.0
        return masks


class TrainValidationSplit(Validator):
    """Single split (reference OpTrainValidationSplit.scala:34;
    TrainRatio default 0.75)."""

    def __init__(self, evaluator: Evaluator, train_ratio: float = 0.75,
                 seed: int = 42, stratify: bool = False, parallelism: int = 8):
        super().__init__(evaluator, seed=seed, stratify=stratify,
                         parallelism=parallelism)
        if not 0.0 < train_ratio < 1.0:
            raise ValueError("train_ratio must be in (0, 1)")
        self.train_ratio = float(train_ratio)

    def fold_masks(self, y: np.ndarray) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        n = len(y)
        mask = np.ones((1, n), np.float32)
        if self.stratify:
            for cls in np.unique(y):
                idx = np.flatnonzero(y == cls)
                rng.shuffle(idx)
                n_val = int(round(len(idx) * (1.0 - self.train_ratio)))
                mask[0, idx[:n_val]] = 0.0
        else:
            perm = rng.permutation(n)
            n_val = int(round(n * (1.0 - self.train_ratio)))
            mask[0, perm[:n_val]] = 0.0
        return mask
