"""Sweep checkpoint/resume.

Reference gap filled per SURVEY §5: the reference has no mid-sweep recovery
(Spark task retry is its whole failure story); the TPU build checkpoints the
model-selection sweep so a preempted run resumes without refitting finished
(model x grid) cells — deterministic replay comes from the seeded fold
assignment (Validator._assign_folds) plus this record.

Format: JSON-lines, one record per validated (model, grid) with its fold
metrics, keyed by a stable hash of (model class, grid, folds, seed,
stratify, metric). Orbax-style atomic append (write + flush) keeps partial
lines out.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional


def data_fingerprint(X, y) -> str:
    """Cheap, stable fingerprint of the sweep's training data: shape plus a
    hash of the label vector and a strided feature sample. Folded into
    sweep_key so a checkpoint file reused after the data changes invalidates
    instead of silently replaying stale fold metrics."""
    import numpy as np

    X = np.asarray(X)
    y = np.asarray(y)
    h = hashlib.sha256()
    h.update(str(X.shape).encode())
    h.update(np.ascontiguousarray(y[:65536]).tobytes())
    stride = max(1, X.shape[0] // 1024)
    h.update(np.ascontiguousarray(X[::stride][:1024]).tobytes())
    return h.hexdigest()[:16]


def sweep_key(model_class: str, grid: Dict[str, Any], n_folds: int,
              seed: int, stratify: bool, metric: str,
              data_fp: str = "", base_params: Optional[Dict[str, Any]] = None,
              path: str = "") -> str:
    payload = json.dumps(
        {"model": model_class, "grid": {k: grid[k] for k in sorted(grid)},
         "folds": n_folds, "seed": seed, "stratify": stratify,
         "metric": metric, "data": data_fp,
         # compute path + its statistically relevant knobs (e.g.
         # "mask_folds" vs "sequential" tree fits, sweep dtype) — metrics
         # from different paths are not interchangeable
         "path": path,
         "base": {k: base_params[k] for k in sorted(base_params)}
         if base_params else {}},
        sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


class RoundCheckpoint:
    """Round-granular state of a convergence-aware streamed GLM sweep
    (ops/glm_sweep.sweep_glm_streamed_rounds): retired-lane coefficients +
    active-lane state persisted after EVERY retirement boundary, so a
    preempted streamed sweep resumes at the last finished round instead of
    restarting the whole family. Finer-grained than SweepCheckpoint's
    (model x grid) cells — those only land once every fold metric of a
    cell exists, which for the streamed route means the entire fit.

    One .npz per sweep path (atomic tmp+replace), keyed by the sweep's
    cell keys + solver knobs: a mismatched key is IGNORED (fresh start),
    never replayed — the key already folds in the data fingerprint, fold
    masks, estimator base params and compute path via sweep_key."""

    _META_SCALARS = ("rounds", "data_passes", "lane_passes",
                     "padded_lane_passes", "warmed")
    _META_LISTS = ("active_per_round", "iters_per_round", "bucket_sizes")
    _ARRAYS = ("B", "b0", "delta", "iters", "retired")

    def __init__(self, path: str):
        self.path = path

    def load(self, key: str) -> Optional[Dict[str, Any]]:
        import numpy as np

        if not os.path.exists(self.path):
            return None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                if str(z["key"]) != key:
                    return None
                state: Dict[str, Any] = {k: z[k].copy()
                                         for k in self._ARRAYS}
                meta = json.loads(str(z["meta"]))
            for k in self._META_SCALARS:
                state[k] = meta[k]
            for k in self._META_LISTS:
                state[k] = list(meta[k])
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return None  # torn/foreign/schema-drifted file — refit
            # rather than trust it (a matching key from an older code
            # revision can still lack current meta fields)
        return state

    def save(self, key: str, state: Dict[str, Any]) -> None:
        import numpy as np

        meta = {k: state[k] for k in self._META_SCALARS}
        meta.update({k: [int(v) for v in state[k]]
                     for k in self._META_LISTS})
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, key=np.str_(key), meta=np.str_(json.dumps(meta)),
                     **{k: np.asarray(state[k]) for k in self._ARRAYS})
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def clear(self) -> None:
        """Remove the state file once the sweep completed (its results now
        live in the cell-level SweepCheckpoint records)."""
        try:
            os.remove(self.path)
        except OSError:
            pass


class SweepCheckpoint:
    """Append-only record of finished sweep cells."""

    def __init__(self, path: str):
        self.path = path
        self._done: Dict[str, Dict[str, Any]] = {}
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                        self._done[rec["key"]] = rec
                    except (json.JSONDecodeError, KeyError):
                        continue  # torn tail line from a crash — ignore

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        return self._done.get(key)

    def record(self, key: str, model_name: str, grid: Dict[str, Any],
               fold_metrics: List[float], metric_name: str) -> None:
        rec = {"key": key, "model_name": model_name, "grid": grid,
               "fold_metrics": fold_metrics, "metric_name": metric_name}
        self._done[key] = rec
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def __len__(self) -> int:
        return len(self._done)
