"""Splitters: test holdout + class rebalancing / label cutting.

Reference: core/.../impl/tuning/Splitter.scala:47 (reserveTestFraction 0.1),
DataSplitter.scala:62 (regression), DataBalancer.scala:73 (binary up/down
sampling to a target minority fraction, maxTrainingSample cap),
DataCutter.scala:76 (multiclass label filtering).

TPU-first: splits are index/weight computations on the host label vector
(tiny), never data movement of the feature matrix. Balancing emits per-row
*sample weights* plus (when downsampling is required to respect
max_training_sample) a kept-row index set; GLM solvers consume the weights
directly so the device matrix stays put in HBM.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclass
class PreparedData:
    """Training data after splitter preparation.

    indices: rows of the original train set to use (post up/down-sampling)
    weights: per-kept-row sample weights
    summary: what the splitter decided (recorded in ModelSelectorSummary)
    label_map: for DataCutter — old label -> new contiguous label
    """

    indices: np.ndarray
    weights: np.ndarray
    summary: Dict[str, Any] = field(default_factory=dict)
    label_map: Optional[Dict[int, int]] = None


class Splitter:
    """Base: reserve a test holdout fraction (reference Splitter.scala:57)."""

    def __init__(self, seed: int = 42, reserve_test_fraction: float = 0.1):
        if not 0.0 <= reserve_test_fraction < 1.0:
            raise ValueError("reserve_test_fraction must be in [0, 1)")
        self.seed = int(seed)
        self.reserve_test_fraction = float(reserve_test_fraction)

    def split(self, n_rows: int) -> Tuple[np.ndarray, np.ndarray]:
        """(train_indices, test_indices) — random holdout."""
        rng = np.random.default_rng(self.seed)
        perm = rng.permutation(n_rows)
        n_test = int(round(n_rows * self.reserve_test_fraction))
        return np.sort(perm[n_test:]), np.sort(perm[:n_test])

    def prepare(self, y: np.ndarray) -> PreparedData:
        """Rebalance/cut the (already holdout-split) train labels. Default:
        keep everything, unit weights."""
        n = len(y)
        return PreparedData(indices=np.arange(n), weights=np.ones(n, np.float32))

    def save_args(self) -> Dict[str, Any]:
        return {"kind": type(self).__name__, "seed": self.seed,
                "reserve_test_fraction": self.reserve_test_fraction}


class DataSplitter(Splitter):
    """Regression splitter: holdout only (reference DataSplitter.scala:62)."""


class DataBalancer(Splitter):
    """Binary-classification rebalancer (reference DataBalancer.scala:73).

    If the minority-class fraction is below ``sample_fraction``, downsample
    the majority (and/or upsample the minority) so the minority fraction
    reaches the target, respecting ``max_training_sample``. Already-balanced
    data is only subsampled if it exceeds ``max_training_sample``.
    """

    def __init__(self, seed: int = 42, reserve_test_fraction: float = 0.1,
                 sample_fraction: float = 0.1,
                 max_training_sample: int = 1_000_000):
        super().__init__(seed=seed, reserve_test_fraction=reserve_test_fraction)
        if not 0.0 < sample_fraction < 0.5:
            raise ValueError("sample_fraction must be in (0, 0.5)")
        self.sample_fraction = float(sample_fraction)
        self.max_training_sample = int(max_training_sample)

    def prepare(self, y: np.ndarray) -> PreparedData:
        rng = np.random.default_rng(self.seed)
        n = len(y)
        pos = np.flatnonzero(y == 1.0)
        neg = np.flatnonzero(y != 1.0)
        n_pos, n_neg = len(pos), len(neg)
        small, big = (pos, neg) if n_pos < n_neg else (neg, pos)
        s, b = len(small), len(big)
        f = self.sample_fraction
        summary: Dict[str, Any] = {
            "positive_count": int(n_pos), "negative_count": int(n_neg),
            "sample_fraction": f, "max_training_sample": self.max_training_sample,
        }

        if s == 0 or b == 0:
            summary["already_balanced"] = True
            return PreparedData(indices=np.arange(n),
                                weights=np.ones(n, np.float32), summary=summary)

        if s / n >= f:
            # already balanced: only cap total size (reference :230)
            summary["already_balanced"] = True
            if n > self.max_training_sample:
                keep = rng.choice(n, self.max_training_sample, replace=False)
                keep.sort()
                summary["down_sample_fraction"] = self.max_training_sample / n
                return PreparedData(indices=keep,
                                    weights=np.ones(len(keep), np.float32),
                                    summary=summary)
            return PreparedData(indices=np.arange(n),
                                weights=np.ones(n, np.float32), summary=summary)

        # target: s' / (s' + b') = f   (reference getProportions:84)
        summary["already_balanced"] = False
        max_train = self.max_training_sample
        big_target = s * (1.0 - f) / f      # keep small as-is, shrink big
        if s + big_target <= max_train:
            down = min(big_target / b, 1.0)
            up = 1.0
        else:
            # cap total at max_train while hitting fraction f
            small_target = max_train * f
            up = small_target / s
            down = (max_train * (1.0 - f)) / b
            down = min(down, 1.0)
        summary["down_sample_fraction"] = float(down)
        summary["up_sample_fraction"] = float(up)

        big_keep = rng.choice(big, max(int(round(b * down)), 1), replace=False)
        if up > 1.0:
            extra = rng.choice(small, int(round(s * (up - 1.0))), replace=True)
            small_keep = np.concatenate([small, extra])
        elif up < 1.0:
            small_keep = rng.choice(small, max(int(round(s * up)), 1),
                                    replace=False)
        else:
            small_keep = small
        idx = np.concatenate([small_keep, big_keep])
        idx.sort()
        return PreparedData(indices=idx, weights=np.ones(len(idx), np.float32),
                            summary=summary)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(sample_fraction=self.sample_fraction,
                 max_training_sample=self.max_training_sample)
        return d


class DataCutter(Splitter):
    """Multiclass label cutter (reference DataCutter.scala:76): keep at most
    ``max_label_categories`` labels each with at least ``min_label_fraction``
    of rows; drop rows of other labels and relabel contiguously."""

    def __init__(self, seed: int = 42, reserve_test_fraction: float = 0.1,
                 max_label_categories: int = 100,
                 min_label_fraction: float = 0.0):
        super().__init__(seed=seed, reserve_test_fraction=reserve_test_fraction)
        if not 0.0 <= min_label_fraction < 0.5:
            raise ValueError("min_label_fraction must be in [0, 0.5)")
        self.max_label_categories = int(max_label_categories)
        self.min_label_fraction = float(min_label_fraction)

    def prepare(self, y: np.ndarray) -> PreparedData:
        labels, counts = np.unique(y[~np.isnan(y)], return_counts=True)
        n = len(y)
        frac_ok = counts / n >= self.min_label_fraction
        kept = labels[frac_ok]
        kept_counts = counts[frac_ok]
        if len(kept) > self.max_label_categories:
            order = np.argsort(-kept_counts)[: self.max_label_categories]
            kept = kept[np.sort(order)]
        kept_set = set(float(v) for v in kept)
        dropped = [float(v) for v in labels if float(v) not in kept_set]
        label_map = {int(v): i for i, v in enumerate(sorted(kept_set))}
        mask = np.isin(y, list(kept_set))
        idx = np.flatnonzero(mask)
        summary = {
            "labels_kept": sorted(kept_set),
            "labels_dropped": dropped,
            "labels_dropped_total": len(dropped),
        }
        return PreparedData(indices=idx, weights=np.ones(len(idx), np.float32),
                            summary=summary, label_map=label_map)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(max_label_categories=self.max_label_categories,
                 min_label_fraction=self.min_label_fraction)
        return d


def splitter_from_args(d: Dict[str, Any]) -> Splitter:
    kinds = {c.__name__: c for c in (Splitter, DataSplitter, DataBalancer,
                                     DataCutter)}
    args = dict(d)
    cls = kinds[args.pop("kind")]
    return cls(**args)
