"""AutoML: transmogrification, validation, model selection.

Reference: core/.../impl/{feature,preparators,tuning,selector,
classification,regression}.
"""
from .preparators import (
    SanityChecker,
    SanityCheckerModel,
    SanityCheckerSummary,
)
from .random_param import RandomParamBuilder
from .selector import ModelSelector, ModelSelectorSummary, SelectedModel
from .selectors import (
    BinaryClassificationModelSelector,
    DefaultSelectorParams,
    MultiClassificationModelSelector,
    RegressionModelSelector,
    default_grid_for,
)
from .transmogrifier import (
    DEFAULTS as TRANSMOGRIFIER_DEFAULTS,
    TransmogrifierDefaults,
    transmogrify,
    vectorize_by_type,
)
from .tuning import (
    BestEstimator,
    CrossValidation,
    DataBalancer,
    DataCutter,
    DataSplitter,
    Splitter,
    TrainValidationSplit,
    ValidatedModel,
    Validator,
)

__all__ = [
    "BestEstimator",
    "BinaryClassificationModelSelector",
    "CrossValidation",
    "DataBalancer",
    "DataCutter",
    "DataSplitter",
    "DefaultSelectorParams",
    "ModelSelector",
    "RandomParamBuilder",
    "ModelSelectorSummary",
    "MultiClassificationModelSelector",
    "RegressionModelSelector",
    "SanityChecker",
    "SanityCheckerModel",
    "SanityCheckerSummary",
    "SelectedModel",
    "Splitter",
    "TrainValidationSplit",
    "TransmogrifierDefaults",
    "TRANSMOGRIFIER_DEFAULTS",
    "ValidatedModel",
    "Validator",
    "default_grid_for",
    "transmogrify",
    "vectorize_by_type",
]
