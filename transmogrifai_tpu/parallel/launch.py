"""Localhost pod launcher: a coordinator + N coordinated child processes.

The multi-host story (docs/performance.md "Multi-host pod scaling") needs
a way to run REAL `jax.distributed` pods on one box — for the 2-process
tests, the `bench.py --multihost` A/B, and the ci.sh kill/resume smoke —
without every caller re-inventing the fragile parts: free-port races,
per-child env assembly, pipe draining, and above all CONTAINMENT. A pod
is only as alive as its coordinator (child 0 hosts the coordination
service): if it dies, every other child blocks inside
`jax.distributed.initialize` or the next collective for minutes. This
launcher guarantees no child outlives the launch call:

* a wall-clock deadline kills the whole pod (SIGKILL, then reap);
* any child exiting nonzero kills the rest after a short grace (they
  are wedged in a collective that can never complete);
* the coordinator exiting — even cleanly — starts the same grace for
  stragglers;
* an optional chaos hook (`kill_on` marker -> SIGKILL `kill_target`)
  drives the elastic-resume smoke: kill one worker mid-round, relaunch
  the pod, and the RoundCheckpoint resumes at the last finished round.

Children communicate results by printing ``RESULT|{json}`` lines; the
launcher parses every such line per child. Each child gets
TMOG_COORD_ADDR / TMOG_PROC_COUNT / TMOG_PROC_ID (which
`multihost.initialize()` reads, bringing up gloo CPU collectives before
the backend exists) and a CPU platform with
``--xla_force_host_platform_device_count`` virtual devices.
"""
from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence

RESULT_PREFIX = "RESULT|"

_DEV_COUNT_RE = re.compile(r"--xla_force_host_platform_device_count=\d+")


def free_port() -> int:
    """A currently-free localhost TCP port. Inherently racy (the socket
    closes before the coordinator rebinds it) — callers retry a failed
    launch once on a fresh port."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def pod_env(port: int, process_id: int, n_procs: int,
            devices_per_proc: int,
            extra_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The environment one pod child runs under: CPU platform with
    `devices_per_proc` virtual devices, TMOG_* coordination vars (the
    spellings `multihost.initialize()` prefers), the legacy JAX_*
    spellings cleared so an outer distributed context cannot leak in,
    and the repo importable."""
    env = dict(os.environ)
    for stale in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                  "JAX_PROCESS_ID"):
        env.pop(stale, None)
    flags = _DEV_COUNT_RE.sub("", env.get("XLA_FLAGS", "")).strip()
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(flags + " " if flags else "")
        + f"--xla_force_host_platform_device_count={devices_per_proc}",
        TMOG_MULTIHOST="1",
        TMOG_COORD_ADDR=f"127.0.0.1:{port}",
        TMOG_PROC_COUNT=str(n_procs),
        TMOG_PROC_ID=str(process_id),
    )
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pp = env.get("PYTHONPATH", "")
    if repo not in pp.split(os.pathsep):
        env["PYTHONPATH"] = repo + (os.pathsep + pp if pp else "")
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    return env


class ChildResult(NamedTuple):
    process_id: int
    returncode: Optional[int]     # None: never exited (killed unreaped)
    results: List[dict]           # parsed RESULT| payloads, in order
    stdout: List[str]
    stderr_tail: str
    killed: bool                  # containment or chaos hook killed it


class PodResult(NamedTuple):
    ok: bool
    error: Optional[str]          # first failure description
    children: List[ChildResult]
    wall_s: float

    def result(self, process_id: int = 0) -> Optional[dict]:
        """The last RESULT| payload of one child (None if absent)."""
        r = self.children[process_id].results
        return r[-1] if r else None


class _Child:
    def __init__(self, process_id: int, proc: subprocess.Popen):
        self.process_id = process_id
        self.proc = proc
        self.stdout: List[str] = []
        self.stderr: List[str] = []
        self.killed = False
        self._threads: List[threading.Thread] = []

    def start_readers(self, on_line) -> None:
        for stream, sink in ((self.proc.stdout, self.stdout),
                             (self.proc.stderr, self.stderr)):
            t = threading.Thread(target=self._drain,
                                 args=(stream, sink, on_line), daemon=True)
            t.start()
            self._threads.append(t)

    def _drain(self, stream, sink: List[str], on_line) -> None:
        try:
            for line in iter(stream.readline, ""):
                line = line.rstrip("\n")
                sink.append(line)
                if sink is self.stdout and on_line is not None:
                    on_line(self.process_id, line)
        except ValueError:
            pass  # stream closed during kill
        finally:
            try:
                stream.close()
            except OSError:
                pass

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.killed = True
            try:
                self.proc.kill()
            except OSError:
                pass

    def finish(self) -> ChildResult:
        rc = self.proc.poll()
        for t in self._threads:
            t.join(timeout=5.0)
        results = []
        for line in self.stdout:
            if line.startswith(RESULT_PREFIX):
                try:
                    results.append(json.loads(line[len(RESULT_PREFIX):]))
                except ValueError:
                    pass
        return ChildResult(
            process_id=self.process_id, returncode=rc, results=results,
            stdout=self.stdout,
            stderr_tail="\n".join(self.stderr)[-2000:],
            killed=self.killed)


def launch_local_pod(payload: str, *, n_procs: int = 2,
                     devices_per_proc: int = 2, timeout: float = 240.0,
                     extra_env: Optional[Dict[str, str]] = None,
                     per_process_env: Optional[
                         Sequence[Optional[Dict[str, str]]]] = None,
                     kill_on: Optional[str] = None, kill_target: int = 1,
                     grace_s: float = 3.0, trace_dir: Optional[str] = None,
                     debug_sleep_ms: int = 0, debug_sleep_target: int = 1,
                     python: str = sys.executable) -> PodResult:
    """Run `payload` (python source) as an `n_procs` localhost CPU pod.

    Every child runs the SAME source (SPMD — it learns its rank from
    TMOG_PROC_ID via `multihost.initialize()`); `per_process_env` adds
    per-rank overrides on top of `extra_env`. Returns once every child
    is reaped — no code path leaves a live child behind.

    `kill_on`/`kill_target`: when the marker substring appears on ANY
    child's stdout, SIGKILL child `kill_target` — the chaos hook the
    RoundCheckpoint resume smoke drives. The launch then reports
    ok=False with error "chaos-killed", and the caller relaunches.

    `trace_dir` turns the pod flight recorder on (TMOG_PODTRACE=1,
    per-rank artifacts under `trace_dir/rank-<k>/` — see
    parallel/podtrace.py). With a trace dir the reaper stops being
    blind: both the deadline kill and the dead-coordinator kill read
    every rank's heartbeat file and name the likely straggler — rank,
    last-known round and phase, beat age — in the returned error.
    `debug_sleep_ms`/`debug_sleep_target` inject a per-round stall into
    one rank (the chaos straggler the ci.sh pod stage asserts on)."""
    port = free_port()
    children: List[_Child] = []
    chaos_fired = threading.Event()
    if trace_dir is not None:
        extra_env = dict(extra_env or {})
        extra_env.setdefault("TMOG_PODTRACE", "1")
        extra_env["TMOG_PODTRACE_DIR"] = str(trace_dir)
    if debug_sleep_ms and trace_dir is not None:
        ppe: List[Optional[Dict[str, str]]] = [
            dict(per_process_env[i]) if per_process_env
            and i < len(per_process_env) and per_process_env[i] else {}
            for i in range(n_procs)]
        if 0 <= debug_sleep_target < n_procs:
            ppe[debug_sleep_target]["TMOG_PODTRACE_DEBUG_SLEEP_MS"] = \
                str(int(debug_sleep_ms))
        per_process_env = ppe
    hb_dir = trace_dir if trace_dir is not None else \
        (extra_env or {}).get("TMOG_PODTRACE_DIR")

    def straggler_note(rcs) -> str:
        """Heartbeat-derived blame table appended to reaper errors —
        empty string when no flight recorder ran."""
        if not hb_dir:
            return ""
        try:
            from . import podtrace
            text, _ = podtrace.straggler_table(hb_dir, rcs=rcs)
            return "\n" + text if text else ""
        except Exception:
            return ""

    def on_line(pid: int, line: str) -> None:
        if kill_on and kill_on in line and not chaos_fired.is_set():
            chaos_fired.set()
            if kill_target < len(children):
                children[kill_target].kill()

    t0 = time.perf_counter()
    try:
        for i in range(n_procs):
            env = pod_env(port, i, n_procs, devices_per_proc, extra_env)
            if per_process_env and i < len(per_process_env) \
                    and per_process_env[i]:
                env.update({k: str(v)
                            for k, v in per_process_env[i].items()})
            proc = subprocess.Popen(
                [python, "-c", payload], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, start_new_session=True)
            children.append(_Child(i, proc))
        for c in children:
            c.start_readers(on_line)

        deadline = time.monotonic() + timeout
        error: Optional[str] = None
        grace_until: Optional[float] = None
        while True:
            rcs = [c.proc.poll() for c in children]
            if all(rc is not None for rc in rcs):
                break
            now = time.monotonic()
            if now >= deadline:
                error = error or (f"pod timeout after {timeout:.0f}s; "
                                  f"rcs={rcs}" + straggler_note(rcs))
                for c in children:
                    c.kill()
                deadline = now + 10.0  # bounded reap wait post-kill
                continue
            # containment: a failed child — or ANY exited coordinator —
            # means the stragglers are wedged in a collective that can
            # never complete; give them a short grace, then kill
            failed = next((i for i, rc in enumerate(rcs)
                           if rc is not None and rc != 0), None)
            coordinator_gone = rcs[0] is not None
            if (failed is not None or coordinator_gone) \
                    and grace_until is None:
                grace_until = now + grace_s
                # first cause wins: a child found dead AFTER the
                # deadline kill is the reaper's own SIGKILL, not a new
                # root cause — it must not clobber the timeout error
                # (which carries the heartbeat blame table)
                if failed is not None:
                    error = error or (
                        f"child {failed} exited rc={rcs[failed]}"
                        + (" (chaos-killed)"
                           if chaos_fired.is_set() else ""))
            if grace_until is not None and now >= grace_until:
                if error is None and any(rc is None for rc in rcs):
                    error = (f"coordinator exited rc={rcs[0]} with "
                             f"children still running; rcs={rcs}"
                             + straggler_note(rcs))
                if error is not None:
                    for c in children:
                        c.kill()
                grace_until = None
            time.sleep(0.05)
    finally:
        for c in children:
            c.kill()
        for c in children:
            try:
                c.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass

    results = [c.finish() for c in children]
    if error is None:
        bad = next((r for r in results if r.returncode != 0), None)
        if bad is not None:
            error = (f"child {bad.process_id} rc={bad.returncode}: "
                     f"{bad.stderr_tail[-400:]}")
    if chaos_fired.is_set():
        error = error or "chaos-killed"
    wall = time.perf_counter() - t0
    try:
        from ..utils.metrics import collector
        if collector.enabled:
            collector.event(
                "multihost_pod", procs=n_procs,
                devices_per_proc=devices_per_proc,
                wall_seconds=round(wall, 3),
                ok=error is None,
                chaos_killed=chaos_fired.is_set(),
                error=(error or "")[:200])
    except Exception:
        pass
    return PodResult(ok=error is None, error=error, children=results,
                     wall_s=wall)
