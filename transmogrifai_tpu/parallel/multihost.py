"""Multi-host (DCN) scale-out entry points.

The reference delegates cross-machine execution to Spark: the driver ships
closures to executors, readers produce per-partition rows, reduceByKey
shuffles over the cluster network (SURVEY §2.9). The TPU-native analogue
is JAX multi-process SPMD: every host runs this same program, owns a slice
of the global row axis, and XLA inserts the collectives (psum over ICI
within a slice, DCN across slices) wherever a sharded reduction appears —
the Gram matrices, gradient histograms and metric sums of the sweep
kernels need no code changes.

This module holds the process-level plumbing that Spark's driver/executor
split used to provide:

- `initialize()`         — jax.distributed bring-up (coordinator + rank
                           from args, TMOG_COORD_ADDR / TMOG_PROC_COUNT /
                           TMOG_PROC_ID, or the JAX_COORDINATOR_ADDRESS /
                           JAX_NUM_PROCESSES / JAX_PROCESS_ID spellings),
                           including the CPU gloo collectives bring-up
                           jax 0.4.x needs before the backend exists;
- `global_mesh()`        — a Mesh over ALL processes' devices;
- `padded_global_rows(n)`— the device-count row multiple arrays pad to;
- `process_row_range(n)` — which REAL rows of a global dataset this host
                           loads (the reader-partition analogue: each host
                           reads only its slice; padding is all-tail);
- `host_local_rows(...)` — assemble a GLOBAL row-sharded jax.Array from
                           this host's local rows (jax.make_array_from_
                           process_local_data); padded rows carry
                           pad_value and are masked by `mesh.row_mask`
                           exactly like the single-host sweep padding
                           (zero weight = inert in every reduction);
- `stripe_paths(...)`    — this process's contiguous stripe of the
                           deterministic (mtime, path) file listing, so
                           each host opens ONLY its own shard files;
- `row_layout(...)` /
  `host_local_block(...)`— the uneven-block generalization the file-
                           striped ingest needs: per-process real row
                           counts are allgathered once, every block pads
                           to one uniform per-process length, and the
                           engines' weight vectors zero the padding;
- `fetch_local(x)` /
  `fetch_global(x)`      — the two documented host fetches of a
                           row-sharded global array: local rows only
                           (never crosses a process boundary) vs the
                           all-gathered global view (SHD005's fold).

Single-process use degrades to the local mesh: every helper works
unchanged with one process, which is how the unit tests cover it.
"""
from __future__ import annotations

import os
from typing import NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .mesh import BATCH_AXIS, make_mesh


_initialized = False


def multihost_enabled() -> bool:
    """TMOG_MULTIHOST: master opt-in for environment-driven multi-host
    behavior — reader-level file striping and workflow auto-initialize.
    Explicit API use (the launch helper, the 2proc tests) does not need
    it; the knob exists so a single launch script can flip a whole
    pipeline run without touching call sites."""
    v = os.environ.get("TMOG_MULTIHOST", "").strip().lower()
    return v not in ("", "0", "false", "off", "no")


def _env_first(*names: str) -> str:
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return ""


def _enable_cpu_collectives() -> None:
    """Configure gloo CPU cross-process collectives BEFORE backend init.

    jax 0.4.x ships `make_gloo_tcp_collectives` in jaxlib, but two traps
    make it unreachable by accident: the `jax_cpu_collectives_implementation`
    enum flag never reads the JAX_CPU_COLLECTIVES_IMPLEMENTATION env var
    (0.4.x flag holders are config-API only), and the TFRT CPU client is
    created without collectives unless the flag is already set — after
    which every multi-process program fails to compile with "Multiprocess
    computations aren't implemented on the CPU backend". So this must run
    before `jax.distributed.initialize` / the first device touch, via the
    config API. No-op when the flag is already set, absent (other jax
    versions), or gloo is missing — TPU/GPU backends bring their own
    collectives and ignore it entirely."""
    import jax
    try:
        cur = getattr(jax.config, "jax_cpu_collectives_implementation",
                      None)
        if cur in (None, "", "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # flag/gloo unavailable: the backend decides, as before


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up jax.distributed; single-process calls are safe no-ops.

    Arguments fall back to TMOG_COORD_ADDR / TMOG_PROC_COUNT /
    TMOG_PROC_ID, then the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID spellings. An explicit coordinator with an unknown
    process count raises (silently degrading a requested distributed run
    to one process would compute per-host-only results). Only a REAL
    bring-up latches: an early no-arg call does not block a later
    configured one."""
    global _initialized
    if _initialized:
        return
    explicit = coordinator_address is not None
    coordinator_address = coordinator_address or _env_first(
        "TMOG_COORD_ADDR", "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(_env_first("TMOG_PROC_COUNT",
                                       "JAX_NUM_PROCESSES") or 0)
    if process_id is None:
        process_id = int(_env_first("TMOG_PROC_ID",
                                    "JAX_PROCESS_ID") or 0)
    if not coordinator_address:
        return  # single-process; a later configured call may still init
    if num_processes <= 0:
        raise ValueError(
            "initialize: coordinator_address given but num_processes "
            "unknown — pass it or set TMOG_PROC_COUNT/JAX_NUM_PROCESSES")
    if num_processes == 1 and not explicit:
        return
    _enable_cpu_collectives()
    import jax
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    try:
        # pod flight recorder: per-rank TraceTree/EventLog + heartbeat
        # into TMOG_PODTRACE_DIR/rank-<k>/ (no-op unless TMOG_PODTRACE)
        from . import podtrace
        podtrace.start(process_id=int(process_id),
                       processes=int(num_processes))
    except Exception:
        pass  # telemetry must never break distributed bring-up
    try:
        from ..utils.metrics import collector
        if collector.enabled:
            collector.event(
                "multihost_init", processes=int(num_processes),
                process_id=int(process_id),
                coordinator=str(coordinator_address),
                devices=len(jax.devices()),
                local_devices=int(jax.local_device_count()))
    except Exception:
        pass  # telemetry must never break distributed bring-up


def finalize() -> None:
    """Explicit jax.distributed teardown (idempotent no-op when never
    initialized). Pod children call it before exiting: the atexit-time
    teardown has been observed to race gloo's background threads on
    rare exits and wedge the interpreter — which the launch helper then
    has to SIGKILL. An explicit shutdown while every peer is still
    alive is instant."""
    global _initialized
    if not _initialized:
        return
    try:
        # save this rank's flight-recorder artifacts while every peer
        # is still alive (a rank that dies before here leaves a torn
        # dir, which merge_pod degrades to a partial report)
        from . import podtrace
        podtrace.finish()
    except Exception:
        pass
    import jax
    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    _initialized = False


def process_count() -> int:
    import jax
    return jax.process_count()


def process_index() -> int:
    import jax
    return jax.process_index()


def is_multiprocess() -> bool:
    return process_count() > 1


def global_mesh(n_model: int = 1):
    """(batch, model) Mesh over every device of every process.

    The batch axis spans hosts: row-sharded arrays then reduce over DCN
    between slices exactly where the reference's Spark shuffle sat."""
    import jax

    n_dev = len(jax.devices())
    if n_dev % n_model:
        raise ValueError(f"{n_dev} devices not divisible by "
                         f"model axis {n_model}")
    return make_mesh(n_batch=n_dev // n_model, n_model=n_model)


def padded_global_rows(n_rows: int) -> int:
    """Global row counts pad up to a device-count multiple (row-sharded
    dims must divide the batch axis; mesh.row_mask masks the tail)."""
    import jax
    nd = len(jax.devices())
    return -(-n_rows // nd) * nd


def process_row_range(n_rows: int) -> Tuple[int, int]:
    """[start, stop) of the REAL rows this process loads.

    The padded row space splits uniformly across processes (equal device
    counts per host), so real rows fill processes in order and all padding
    lands on the last process's tail — the global array is real rows
    first, padding last, matching mesh.row_mask."""
    import jax
    per = padded_global_rows(n_rows) // jax.process_count()
    i = jax.process_index()
    return min(i * per, n_rows), min((i + 1) * per, n_rows)


def fetch_global(x) -> np.ndarray:
    """np.ndarray of a GLOBAL (possibly row-sharded) jax.Array, safe
    under multi-process SPMD.

    ``np.asarray(x)`` on a multi-host global array either raises (rows
    living on another host are not addressable) or — worse, via
    addressable-shard paths — silently yields only THIS host's rows, so
    a host-side ``np.sum`` over it computes a per-host total that looks
    global. That is the SHD005 bug class (tmoglint flags it statically:
    docs/static_analysis.md). This helper is the documented cross-process
    fold: single-process it is a plain ``asarray``; multi-process it
    all-gathers the array so every host sees every row. Prefer reducing
    ON DEVICE (psum inside the sharded program) when you only need the
    aggregate — fetching all rows to every host is the expensive path,
    and when only THIS host's rows are needed, `fetch_local` below never
    crosses a process boundary at all.
    """
    if process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def fetch_local(x, axis: int = 0) -> np.ndarray:
    """THIS process's rows of a row-sharded global array, as one host
    block — the cheap sibling of `fetch_global` for callers that only
    need host-local rows (per-host previews, telemetry, the local half
    of a two-stage merge). Never moves data across processes: it reads
    only addressable shards, dedupes model-axis replicas by row offset,
    and concatenates in global row order. Single-process (or plain
    numpy input) it is exactly ``asarray``. Contract: the array is
    sharded (or replicated) along `axis` only — axis 0 is the engines'
    row layout; axis 1 is the fold-mask / margins layout [F, n]."""
    import jax
    if not isinstance(x, jax.Array) or process_count() == 1:
        return np.asarray(x)
    by_offset = {}
    for s in x.addressable_shards:
        start = 0
        if len(s.index) > axis and isinstance(s.index[axis], slice):
            start = int(s.index[axis].start or 0)
        by_offset.setdefault(start, s)
    blocks = [np.asarray(by_offset[k].data) for k in sorted(by_offset)]
    if not blocks:
        shape = list(x.shape)
        shape[axis] = 0
        return np.empty(tuple(shape), x.dtype)
    return blocks[0] if len(blocks) == 1 else \
        np.concatenate(blocks, axis)


def stripe_paths(paths: Sequence, index: Optional[int] = None,
                 count: Optional[int] = None) -> list:
    """This process's stripe of a deterministic path listing (readers
    pin (mtime, path) order — readers/streaming.snapshot_paths).

    CONTIGUOUS blocks, not round-robin: the concatenation of the
    stripes in process order preserves the single-process global file
    (and therefore row) order, which keeps the 2-process fit
    bit-comparable with the 1-process fit. The remainder spreads over
    the first processes so block sizes differ by at most one."""
    paths = list(paths)
    if count is None:
        count = process_count()
    if index is None:
        index = process_index()
    base, rem = divmod(len(paths), count)
    start = index * base + min(index, rem)
    stop = start + base + (1 if index < rem else 0)
    return paths[start:stop]


def host_local_rows(local: np.ndarray, mesh, n_rows_global: int,
                    pad_value: float = 0.0):
    """Global row-sharded jax.Array from this host's local block.

    `local` must be exactly this process's `process_row_range(n_rows_global)`
    slice; the block pads to the uniform per-process length with
    `pad_value` rows (weight-0 semantics downstream — give padded rows
    zero sample weight via `mesh.row_mask(padded_global_rows(n), n)`).
    Returns an array of `padded_global_rows(n_rows_global)` rows."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    padded_total = padded_global_rows(n_rows_global)
    per = padded_total // jax.process_count()
    if local.shape[0] < per:
        pad = np.full((per - local.shape[0],) + tuple(local.shape[1:]),
                      pad_value, dtype=local.dtype)
        local = np.concatenate([local, pad], axis=0)
    spec = P(BATCH_AXIS, *([None] * (local.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    global_shape = (padded_total,) + tuple(local.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local), global_shape)


class RowLayout(NamedTuple):
    """Global row layout of UNEVEN per-process blocks.

    `process_row_range` assumes the caller can slice a known global
    dataset; the file-striped ingest path cannot — each process parses
    its own files and only then knows its real row count. `row_layout`
    allgathers those counts once, and every process pads its block to
    one uniform `per_process` length (a local-device-count multiple, as
    XLA's even sharding requires). Padded rows are inert downstream via
    `local_weights` (weight 0), exactly like single-host tail padding —
    so the union of real rows, and therefore every psum-merged
    sufficient statistic, matches the single-process fit regardless of
    where the padding sits."""

    counts: Tuple[int, ...]   # real rows per process, process order
    per_process: int          # uniform padded local block length

    @property
    def n_real(self) -> int:
        return int(sum(self.counts))

    @property
    def n_padded(self) -> int:
        return self.per_process * len(self.counts)

    def local_count(self, process: Optional[int] = None) -> int:
        i = process_index() if process is None else process
        return int(self.counts[i])

    def local_weights(self, process: Optional[int] = None) -> np.ndarray:
        """1.0 for this process's real rows, 0.0 for its padding."""
        w = np.zeros((self.per_process,), np.float32)
        w[: self.local_count(process)] = 1.0
        return w


def allgather_counts(n_local: int) -> Tuple[int, ...]:
    """Every process's value of a host integer, in process order (one
    tiny device allgather; single-process: just the value)."""
    if process_count() == 1:
        return (int(n_local),)
    from jax.experimental import multihost_utils
    g = multihost_utils.process_allgather(
        np.asarray([int(n_local)], np.int32))
    return tuple(int(v) for v in np.asarray(g).reshape(-1))


def row_layout(n_local: int, mesh) -> RowLayout:
    """The pod-wide RowLayout for this process's `n_local` real rows.

    COLLECTIVE: every process must call it (it allgathers the counts).
    The uniform block length is the max padded count, rounded up to this
    host's share of the mesh batch axis."""
    pc = process_count()
    from . import podtrace
    with podtrace.collective("row_layout", procs=pc, rows=int(n_local)):
        counts = allgather_counts(n_local)
    try:
        n_batch = int(dict(mesh.shape).get(BATCH_AXIS, 1))
    except Exception:
        n_batch = 1
    local_dev = max(1, n_batch // max(1, pc))
    per = -(-max(max(counts), 1) // local_dev) * local_dev
    return RowLayout(counts=counts, per_process=per)


def host_local_block(local: np.ndarray, mesh, layout: RowLayout,
                     pad_value: Optional[float] = 0.0, axis: int = 0):
    """Global batch-sharded jax.Array from this process's (possibly
    shorter) local block, padded to `layout.per_process` along `axis`
    (the batch-sharded dim; fold masks pass axis=1).

    `pad_value=None` repeats the last real row instead of a constant —
    the tree-binning semantics of `mesh.pad_rows_to_multiple` (synthetic
    values would shift quantile bins; duplicates barely do)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    local = np.asarray(local)
    n = local.shape[axis]
    if n > layout.per_process:
        raise ValueError(f"local block of {n} rows exceeds the layout's "
                         f"per-process length {layout.per_process}")
    if n < layout.per_process:
        pad_n = layout.per_process - n
        if pad_value is None and n > 0:
            pad = np.repeat(np.take(local, [n - 1], axis=axis),
                            pad_n, axis=axis)
        else:
            shape = list(local.shape)
            shape[axis] = pad_n
            pad = np.full(shape, 0.0 if pad_value is None else pad_value,
                          local.dtype)
        local = np.concatenate([local, pad], axis=axis)
    spec = [None] * local.ndim
    spec[axis] = BATCH_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    gshape = list(local.shape)
    gshape[axis] = layout.n_padded
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local), tuple(gshape))


def replicated_global(x, mesh):
    """Fully-replicated global array from an identical host value on
    every process. `jax.device_put` refuses shardings with
    non-addressable devices, so the multi-process path goes through
    make_array_from_process_local_data; single-process it is a plain
    replicated device_put. COLLECTIVE in the sense that every process
    must supply the same value (scalars, regs/alphas grids, fold
    counts) — divergent values would silently diverge the programs."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.asarray(x)
    sharding = NamedSharding(mesh, P())
    if process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(
        sharding, x, tuple(x.shape))
