"""Multi-host (DCN) scale-out entry points.

The reference delegates cross-machine execution to Spark: the driver ships
closures to executors, readers produce per-partition rows, reduceByKey
shuffles over the cluster network (SURVEY §2.9). The TPU-native analogue
is JAX multi-process SPMD: every host runs this same program, owns a slice
of the global row axis, and XLA inserts the collectives (psum over ICI
within a slice, DCN across slices) wherever a sharded reduction appears —
the Gram matrices, gradient histograms and metric sums of the sweep
kernels need no code changes.

This module holds the process-level plumbing that Spark's driver/executor
split used to provide:

- `initialize()`         — jax.distributed bring-up (coordinator + rank
                           from args or JAX_COORDINATOR_ADDRESS /
                           JAX_NUM_PROCESSES / JAX_PROCESS_ID env vars);
- `global_mesh()`        — a Mesh over ALL processes' devices;
- `padded_global_rows(n)`— the device-count row multiple arrays pad to;
- `process_row_range(n)` — which REAL rows of a global dataset this host
                           loads (the reader-partition analogue: each host
                           reads only its slice; padding is all-tail);
- `host_local_rows(...)` — assemble a GLOBAL row-sharded jax.Array from
                           this host's local rows (jax.make_array_from_
                           process_local_data); padded rows carry
                           pad_value and are masked by `mesh.row_mask`
                           exactly like the single-host sweep padding
                           (zero weight = inert in every reduction).

Single-process use degrades to the local mesh: every helper works
unchanged with one process, which is how the unit tests cover it.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from .mesh import BATCH_AXIS, make_mesh


_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up jax.distributed; single-process calls are safe no-ops.

    Arguments fall back to JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID. An explicit coordinator with an unknown process count
    raises (silently degrading a requested distributed run to one process
    would compute per-host-only results). Only a REAL bring-up latches:
    an early no-arg call does not block a later configured one."""
    global _initialized
    if _initialized:
        return
    explicit = coordinator_address is not None
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
    if not coordinator_address:
        return  # single-process; a later configured call may still init
    if num_processes <= 0:
        raise ValueError(
            "initialize: coordinator_address given but num_processes "
            "unknown — pass it or set JAX_NUM_PROCESSES")
    if num_processes == 1 and not explicit:
        return
    import jax
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def process_count() -> int:
    import jax
    return jax.process_count()


def global_mesh(n_model: int = 1):
    """(batch, model) Mesh over every device of every process.

    The batch axis spans hosts: row-sharded arrays then reduce over DCN
    between slices exactly where the reference's Spark shuffle sat."""
    import jax

    n_dev = len(jax.devices())
    if n_dev % n_model:
        raise ValueError(f"{n_dev} devices not divisible by "
                         f"model axis {n_model}")
    return make_mesh(n_batch=n_dev // n_model, n_model=n_model)


def padded_global_rows(n_rows: int) -> int:
    """Global row counts pad up to a device-count multiple (row-sharded
    dims must divide the batch axis; mesh.row_mask masks the tail)."""
    import jax
    nd = len(jax.devices())
    return -(-n_rows // nd) * nd


def process_row_range(n_rows: int) -> Tuple[int, int]:
    """[start, stop) of the REAL rows this process loads.

    The padded row space splits uniformly across processes (equal device
    counts per host), so real rows fill processes in order and all padding
    lands on the last process's tail — the global array is real rows
    first, padding last, matching mesh.row_mask."""
    import jax
    per = padded_global_rows(n_rows) // jax.process_count()
    i = jax.process_index()
    return min(i * per, n_rows), min((i + 1) * per, n_rows)


def fetch_global(x) -> np.ndarray:
    """np.ndarray of a GLOBAL (possibly row-sharded) jax.Array, safe
    under multi-process SPMD.

    ``np.asarray(x)`` on a multi-host global array either raises (rows
    living on another host are not addressable) or — worse, via
    addressable-shard paths — silently yields only THIS host's rows, so
    a host-side ``np.sum`` over it computes a per-host total that looks
    global. That is the SHD005 bug class (tmoglint flags it statically:
    docs/static_analysis.md). This helper is the documented cross-process
    fold: single-process it is a plain ``asarray``; multi-process it
    all-gathers the array so every host sees every row. Prefer reducing
    ON DEVICE (psum inside the sharded program) when you only need the
    aggregate — fetching all rows to every host is the expensive path.
    """
    import jax
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def host_local_rows(local: np.ndarray, mesh, n_rows_global: int,
                    pad_value: float = 0.0):
    """Global row-sharded jax.Array from this host's local block.

    `local` must be exactly this process's `process_row_range(n_rows_global)`
    slice; the block pads to the uniform per-process length with
    `pad_value` rows (weight-0 semantics downstream — give padded rows
    zero sample weight via `mesh.row_mask(padded_global_rows(n), n)`).
    Returns an array of `padded_global_rows(n_rows_global)` rows."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    padded_total = padded_global_rows(n_rows_global)
    per = padded_total // jax.process_count()
    if local.shape[0] < per:
        pad = np.full((per - local.shape[0],) + tuple(local.shape[1:]),
                      pad_value, dtype=local.dtype)
        local = np.concatenate([local, pad], axis=0)
    spec = P(BATCH_AXIS, *([None] * (local.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    global_shape = (padded_total,) + tuple(local.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local), global_shape)
