"""Device mesh + sharding substrate.

The reference delegates distribution to Spark (partitioned RDDs + shuffle,
SURVEY §2.9). Here the equivalent is a named `jax.sharding.Mesh` with GSPMD
sharding annotations: feature-matrix rows ride the ``batch`` axis (Spark
partitions), CV-fold and hyperparameter-grid replication ride ``model``
(thread-pool parallelism of OpValidator.scala:318), and XLA inserts the
all-reduce/all-gather collectives over ICI/DCN that replace shuffle + Rabit.

All kernels in ops/ and models/ are written mesh-oblivious (pure jnp) and get
distribution purely through input shardings — single-chip and pod runs use
identical program text.
"""
from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"
MODEL_AXIS = "model"

_active_mesh: Optional[Mesh] = None


def shard_vary(tree, axis_name):
    """Under shard_map's varying-manual-axes tracking a scan carry becomes
    batch-varying inside the body; the initial zeros must carry the same
    type. pcast is the current spelling; pvary the deprecated one on older
    jax. Shared by every sharded streaming kernel (GLM sweep, stats
    engine) so the version shims live in one place."""
    if axis_name is None:
        return tree
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(tree, axis_name, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(tree, axis_name)
    return tree


def build_shard_map(core, mesh, in_specs, out_specs):
    """shard_map with the version shims every sharded streaming route
    needs: import location (jax >= 0.8 top-level), and replication
    checking off — jax 0.4.x shard_map has no replication rule for
    `while` (accumulator psums make every carry replicated by
    construction); jax >= 0.6 renamed the knob check_rep -> check_vma.

    check_rep=False also means NOTHING at runtime verifies a replicated
    out_spec was actually psum-merged — and at 1 device per shard (every
    CI mesh) a forgotten psum is the identity. That contract is enforced
    statically instead: tmoglint SHD001-SHD005 resolve every
    build_shard_map/shard_map call site, bind the P(...) axis names, and
    prove each replicated out_spec reduced through the body's dataflow
    (docs/static_analysis.md)."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    import inspect as _inspect
    sig = _inspect.signature(shard_map)
    if "check_rep" in sig.parameters:
        extra = {"check_rep": False}
    elif "check_vma" in sig.parameters:
        extra = {"check_vma": False}
    else:
        extra = {}
    return shard_map(core, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, **extra)


def mesh_batch_count(mesh) -> int:
    """Devices on the batch axis (1 for None / degenerate meshes) — the
    single predicate sweep drivers use to decide whether a mesh context
    warrants the row-sharded fused route (models/trees)."""
    if mesh is None:
        return 1
    try:
        return int(dict(mesh.shape).get(BATCH_AXIS, 1))
    except Exception:
        return 1


def mesh_process_count(mesh) -> int:
    """Distinct processes owning the mesh's devices (1 for None / local
    meshes). The predicate the engine drivers use to pick the multi-host
    data landing (make_array_from_process_local_data) over the
    single-host one (device_put of the full array)."""
    if mesh is None:
        return 1
    try:
        return len({d.process_index
                    for d in np.asarray(mesh.devices).ravel()})
    except Exception:
        return 1


def mesh_is_multiprocess(mesh) -> bool:
    return mesh_process_count(mesh) > 1


def make_mesh(n_batch: Optional[int] = None, n_model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Create a (batch, model) mesh over available devices.

    With jax.distributed initialized, `jax.devices()` is the GLOBAL
    device list in process order, so the batch axis (the row/data axis)
    spans hosts with each process's devices contiguous along it — the
    per-host device assignment `make_array_from_process_local_data`
    needs for a host's rows to land on its own devices. The model axis
    (the lane axis of the sweep) stays within a host at n_model <=
    local device count; the 2-D (data x lane) pod mesh of
    docs/performance.md is exactly this reshape."""
    devs = list(devices if devices is not None else jax.devices())
    if n_batch is None:
        n_batch = len(devs) // n_model
    use = devs[: n_batch * n_model]
    arr = np.array(use).reshape(n_batch, n_model)
    return Mesh(arr, (BATCH_AXIS, MODEL_AXIS))


def default_mesh() -> Mesh:
    global _active_mesh
    if _active_mesh is None:
        _active_mesh = make_mesh()
    return _active_mesh


@contextmanager
def use_mesh(mesh: Mesh):
    global _active_mesh
    prev = _active_mesh
    _active_mesh = mesh
    try:
        yield mesh
    finally:
        _active_mesh = prev


def batch_sharding(mesh: Optional[Mesh] = None, ndim: int = 2) -> NamedSharding:
    """Rows sharded over the batch axis; all other dims replicated."""
    mesh = mesh or default_mesh()
    spec = P(BATCH_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P())


def sharded_along(mesh: Optional[Mesh], dim: int, ndim: int) -> NamedSharding:
    """Shard one dimension over the batch axis, others replicated (e.g.
    fold masks [F, n] shard dim=1)."""
    mesh = mesh or default_mesh()
    spec = [None] * ndim
    spec[dim] = BATCH_AXIS
    return NamedSharding(mesh, P(*spec))


def pad_rows_to_multiple(x: np.ndarray, multiple: int,
                         pad_value: Optional[float] = 0.0
                         ) -> Tuple[np.ndarray, int]:
    """Pad rows so the batch axis divides evenly across devices. Returns the
    padded array and the original row count (callers carry a weight/mask
    vector so padded rows never affect statistics). ``pad_value=None``
    repeats the LAST real row instead — for feature matrices feeding
    unweighted statistics (tree quantile binning), where synthetic values
    would shift the distribution but duplicates barely do."""
    n = x.shape[0]
    rem = n % multiple
    if rem == 0:
        return x, n
    pad = multiple - rem
    if pad_value is None:
        pad_block = np.repeat(np.asarray(x)[-1:], pad, axis=0)
    else:
        pad_block = np.full((pad,) + x.shape[1:], pad_value, dtype=x.dtype)
    return np.concatenate([x, pad_block], axis=0), n


def device_put_batch(x: np.ndarray, mesh: Optional[Mesh] = None,
                     pad: bool = True) -> Tuple[jax.Array, int]:
    """Host -> HBM with rows sharded on the batch axis.

    Returns (device array, true row count). When `pad`, rows are zero-padded
    to a multiple of the batch-axis size (XLA requires even sharding).
    """
    mesh = mesh or default_mesh()
    n_shards = mesh.shape[BATCH_AXIS]
    n = x.shape[0]
    if pad:
        x, n = pad_rows_to_multiple(np.asarray(x), n_shards)
    return jax.device_put(x, batch_sharding(mesh, ndim=x.ndim)), n


def row_mask(n_padded: int, n_true: int) -> np.ndarray:
    """1.0 for real rows, 0.0 for padding."""
    m = np.zeros((n_padded,), dtype=np.float32)
    m[:n_true] = 1.0
    return m
