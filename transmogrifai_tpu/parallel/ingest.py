"""Parallel sharded ingest: a parse-worker pool feeding the tileplane.

The tileplane (parallel/tileplane.py) overlaps H2D copy with device
compute, but its feed was still ONE Python thread parsing records
cell-by-cell — at 100M-row scale the device starves on host parse, the
exact input-pipeline bottleneck sharded-host ingest solves for pjit/TPU
training (PAPERS arxiv 2204.06514). This module parallelizes the feed
WITHOUT changing a single downstream bit:

- `ShardedSource` is a RowSource over per-file-shard chunk factories.
  N parse workers each own a striped subset of shards (worker j owns
  shards j, j+N, j+2N, ... — `FileStreamingReader._paths` already fixes
  the shard order) and decode into bounded per-shard queues;
- the consumer side of `chunks()` drains those queues IN SHARD-INDEX
  ORDER — deterministic order-preserving reassembly. The global chunk
  sequence is identical to a serial read of the shards, so the
  tileplane's fixed-tile assembly slices identical tiles and every
  float reduction (stats moments, GLM Gram/score, tree histograms)
  stays BIT-IDENTICAL to serial ingest at any worker count;
- a worker crash/exception lands on the queue of the shard it was
  parsing; reassembly reaches that shard and re-raises on the consumer
  thread — a failed pass, never a hang;
- single-shard or workers<=1 inputs degrade to a serial in-thread loop
  (today's single-producer path, same spans, no threads);
- decode is COLUMNAR: workers pull whole column blocks per chunk
  (readers/readers.csv_columnar_chunks, readers/avro.read_avro_columns)
  and convert each column with ONE vectorized `np.asarray`/`astype`
  (readers/readers.columnar_f32) instead of the per-cell dict walk;
- each worker wraps every decoded chunk in a `tile_parse` span carrying
  a per-worker `lane` attr, so parse/copy/compute overlap renders as
  separate Perfetto swimlanes (docs/observability.md) and the planner
  can derive TMOG_TILE_PREFETCH from measured span ratios.

TMOG_INGEST_WORKERS sizes the pool (env > planner > hand default 1);
the pass emits an `ingest_pass` event + IngestPass telemetry record.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import (Any, Callable, Dict, Iterable, Iterator, List,
                    Optional, Sequence, Tuple)

import numpy as np

from .tileplane import RowSource

_INGEST_WORKERS_DEFAULT = 1
#: per-shard queue depth: how many chunks a worker may decode ahead of
#: reassembly on each shard it owns (host buffering is bounded by
#: shards * ahead chunks, independent of file size)
_SHARD_QUEUE_AHEAD = 2


def ingest_workers() -> int:
    """Parse-worker pool size for sharded sources. An explicitly-set
    TMOG_INGEST_WORKERS wins (hand beats model); otherwise the
    plan-time autotuner picks from measured ingest_parse throughput —
    a cold corpus (or TMOG_PLAN=0, or any planner fault) yields the
    serial hand default 1 (docs/planning.md). Per-pass the pool is
    additionally clamped to the shard count."""
    try:
        from ..planner.plan import planned_ingest_workers
        return max(1, int(planned_ingest_workers()))
    except Exception:
        try:
            return max(1, int(os.environ.get(
                "TMOG_INGEST_WORKERS", str(_INGEST_WORKERS_DEFAULT))))
        except ValueError:
            return _INGEST_WORKERS_DEFAULT


def _put(q: "queue.Queue", item: Any, stop: threading.Event) -> bool:
    """Bounded put that observes the stop flag (the consumer may abandon
    the pass mid-stream); False = pass abandoned, caller unwinds."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _parse_worker(factories: Sequence[Callable[[], Iterable[Tuple[np.ndarray, ...]]]],
                  owned: Sequence[int], qs: Sequence["queue.Queue"],
                  stop: threading.Event, traced: bool, anchor: Any,
                  label: str, worker_idx: int,
                  parse_s: List[float], collector: Any) -> None:
    """Worker body: decode owned shards IN ORDER into their per-shard
    queues. Module-level with explicit args — all pass state lives in
    the consumer's frame, none on shared objects. An exception lands on
    the queue of the shard being parsed: reassembly drains shards in
    index order, and every shard before the failed one either ended
    cleanly or fails first, so the consumer always reaches the error
    (failed pass) instead of blocking on a sentinel that never comes.
    `parse_s[worker_idx]` is a single-writer slot, read by the consumer
    only after join."""
    si = owned[0]
    try:
        for si in owned:
            q = qs[si]
            seq = 0
            t0 = time.perf_counter()
            for chunk in factories[si]():
                chunk = tuple(np.ascontiguousarray(a) for a in chunk)
                dur = time.perf_counter() - t0
                parse_s[worker_idx] += dur
                if traced:
                    collector.trace.add_complete(
                        "tile_parse", "tile", dur, parent_span=anchor,
                        shard=si, chunk=seq, worker=worker_idx,
                        rows=int(chunk[0].shape[0]), label=label,
                        lane=f"ingest-w{worker_idx}")
                if not _put(q, ("chunk", chunk), stop):
                    return
                seq += 1
                t0 = time.perf_counter()
            if not _put(q, ("end", None), stop):
                return
    except BaseException as e:
        _put(qs[si], ("error", e), stop)


class ShardedSource(RowSource):
    """Order-preserving parallel-parse RowSource over file shards.

    `shard_factories[i]()` starts a fresh chunk iteration of shard i
    (tuples of same-leading-dim arrays, the RowSource chunk contract).
    `chunks()` yields shard 0's chunks, then shard 1's, ... — exactly a
    serial concatenated read — while up to `workers` threads decode
    ahead. Re-iterable: every `chunks()` call is a fresh pass with
    fresh threads (GLM rounds re-read disk through the same pool)."""

    def __init__(self, shard_factories: Sequence[
                     Callable[[], Iterable[Tuple[np.ndarray, ...]]]],
                 *, n_rows: Optional[int] = None,
                 workers: Optional[int] = None,
                 ahead: int = _SHARD_QUEUE_AHEAD,
                 label: str = "ingest"):
        self.shard_factories = list(shard_factories)
        self.n_rows = n_rows
        #: None = resolve ingest_workers() (env > planner > hand) per pass
        self.workers = workers
        self.ahead = max(1, int(ahead))
        self.label = label
        self._anchor: Any = None

    def set_span_anchor(self, anchor: Any) -> None:
        # caller's thread, BEFORE the pass's threads exist (run_tileplane
        # contract) — workers then receive it by argument
        # tmoglint: disable=THR001  written before pass threads start
        self._anchor = anchor

    def effective_workers(self) -> int:
        """Pool size for the next pass: requested (or planned) workers
        clamped to the shard count — a single shard has no parallelism
        to exploit and degrades to the serial path."""
        w = self.workers if self.workers is not None else ingest_workers()
        return max(1, min(int(w), len(self.shard_factories)))

    def chunks(self) -> Iterator[Tuple[np.ndarray, ...]]:
        n_workers = self.effective_workers()
        if n_workers <= 1 or len(self.shard_factories) <= 1:
            yield from self._serial_pass()
        else:
            yield from self._parallel_pass(n_workers)

    # -- serial degradation (single shard / workers=1 / tiny inputs) --------

    def _serial_pass(self) -> Iterator[Tuple[np.ndarray, ...]]:
        """The single-producer path, in-thread — same chunk sequence,
        same tile_parse spans (worker 0), so serial-vs-parallel A/B
        reads off one trace schema."""
        from ..utils.metrics import collector
        traced = bool(collector.enabled)
        anchor = self._anchor
        parse_s = 0.0
        rows = 0
        n_chunks = 0
        t_pass = time.perf_counter()
        for si, factory in enumerate(self.shard_factories):
            seq = 0
            t0 = time.perf_counter()
            for chunk in factory():
                chunk = tuple(np.ascontiguousarray(a) for a in chunk)
                dur = time.perf_counter() - t0
                parse_s += dur
                if traced:
                    collector.trace.add_complete(
                        "tile_parse", "tile", dur, parent_span=anchor,
                        shard=si, chunk=seq, worker=0,
                        rows=int(chunk[0].shape[0]), label=self.label,
                        lane="ingest-w0")
                rows += int(chunk[0].shape[0])
                n_chunks += 1
                seq += 1
                yield chunk
                t0 = time.perf_counter()
        if traced:
            collector.ingest_pass(
                label=self.label, workers=1,
                shards=len(self.shard_factories), chunks=n_chunks,
                rows=rows, parse_seconds=parse_s,
                wall_seconds=time.perf_counter() - t_pass)

    # -- the worker pool ----------------------------------------------------

    def _parallel_pass(self, n_workers: int
                       ) -> Iterator[Tuple[np.ndarray, ...]]:
        from ..utils.metrics import collector
        traced = bool(collector.enabled)
        anchor = self._anchor
        n_shards = len(self.shard_factories)
        qs = [queue.Queue(maxsize=self.ahead) for _ in range(n_shards)]
        stop = threading.Event()
        parse_s = [0.0] * n_workers
        threads = []
        for w in range(n_workers):
            th = threading.Thread(
                target=_parse_worker,
                args=(self.shard_factories, list(range(w, n_shards,
                                                       n_workers)),
                      qs, stop, traced, anchor, self.label, w, parse_s,
                      collector),
                name=f"ingest-{self.label}-w{w}", daemon=True)
            th.start()
            threads.append(th)
        rows = 0
        n_chunks = 0
        t_pass = time.perf_counter()
        try:
            for si in range(n_shards):
                # reassembly: global order = shard order = serial order
                while True:
                    kind, payload = qs[si].get()
                    if kind == "end":
                        break
                    if kind == "error":
                        raise payload
                    rows += int(payload[0].shape[0])
                    n_chunks += 1
                    yield payload
        finally:
            stop.set()
            # drain every queue so workers blocked on put observe the
            # flag (their _put loops re-check it each timeout)
            for q in qs:
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
            for th in threads:
                th.join(timeout=30.0)
            if traced:
                # parse_s read happens-after join
                collector.ingest_pass(
                    label=self.label, workers=n_workers,
                    shards=n_shards, chunks=n_chunks, rows=rows,
                    parse_seconds=sum(parse_s),
                    wall_seconds=time.perf_counter() - t_pass)

    def peek(self) -> Tuple[np.ndarray, ...]:
        """Width probe without spinning up the pool: read shard 0's
        first chunk in-thread (falls back to a full-pass probe when
        shard 0 is empty). Cached like the base peek."""
        if self._peek_cache is None:
            if self.shard_factories:
                it = iter(self.shard_factories[0]())
                try:
                    first = next(it)
                except StopIteration:
                    first = None
                finally:
                    close = getattr(it, "close", None)
                    if close is not None:
                        close()
                if first is not None:
                    self._peek_cache = tuple(
                        np.ascontiguousarray(a) for a in first)
                    return self._peek_cache
            return super().peek()
        return self._peek_cache


def sharded_reader_source(paths: Sequence[str],
                          columns_fn: Callable[[Dict[str, np.ndarray]],
                                               Tuple[np.ndarray, ...]],
                          *, columns: Optional[Sequence[str]] = None,
                          batch_records: int = 8192,
                          n_rows: Optional[int] = None,
                          workers: Optional[int] = None,
                          label: str = "ingest",
                          stripe: Optional[bool] = None) -> ShardedSource:
    """ShardedSource over CSV/Avro file shards with COLUMNAR decode.

    Each shard decodes in whole column blocks — one vectorized
    float32 conversion per column per chunk, no per-record dicts —
    and `columns_fn({name -> float32 array})` maps one chunk's columns
    to the source's chunk tuple (e.g. `lambda c: (np.stack([c["x0"],
    c["x1"]], 1), c["y"], c["w"])`): the vectorized replacement for the
    per-record `row_fn` of tileplane.reader_row_source. Format is by
    extension per shard (.avro = container decode, else CSV);
    `columns` restricts decode to the named fields (CSV header names /
    Avro record fields). Shard ORDER is the caller's `paths` order —
    pass FileStreamingReader's deterministic listing for file globs.

    `stripe` (None = auto: TMOG_MULTIHOST set AND >1 jax processes)
    keeps only THIS PROCESS's contiguous stripe of `paths`
    (multihost.stripe_paths): under multi-host SPMD every process calls
    with the SAME deterministic global listing and opens ONLY its own
    files — its parsed rows are its batch-axis block of the global row
    set. When the stripe drops files, a caller-supplied global `n_rows`
    no longer describes the local stream and is reset to None. Pass
    stripe=False when `paths` is already a per-process stripe."""
    paths = [str(p) for p in paths]
    if stripe is None:
        from .multihost import multihost_enabled
        stripe = multihost_enabled()
    if stripe:
        from . import multihost as MH
        if MH.process_count() > 1:
            mine = [str(p) for p in MH.stripe_paths(paths)]
            if len(mine) != len(paths):
                paths = mine
                n_rows = None

    def factory_for(path: str) -> Callable[[], Iterator[Tuple[np.ndarray, ...]]]:
        if path.endswith(".avro"):
            def factory() -> Iterator[Tuple[np.ndarray, ...]]:
                from ..readers.avro import read_avro_columns
                from ..readers.readers import columnar_f32
                for cols in read_avro_columns(
                        path, fields=columns,
                        batch_records=batch_records):
                    yield columns_fn(
                        {k: columnar_f32(v) for k, v in cols.items()})
        else:
            def factory() -> Iterator[Tuple[np.ndarray, ...]]:
                from ..readers.readers import csv_columnar_chunks
                for cols in csv_columnar_chunks(
                        path, columns=columns,
                        batch_records=batch_records):
                    yield columns_fn(cols)
        return factory

    return ShardedSource([factory_for(p) for p in paths], n_rows=n_rows,
                         workers=workers, label=label)
