"""Pod flight recorder: per-rank fit tracing merged into one timeline.

PR 18 put the whole fit pipeline on a multi-process (data x lane) mesh,
but the pod stayed a black box: when the launcher reaped a timeout it
only knew "the stragglers are wedged in a collective" — no rank
timeline, no psum-wait vs compute split, no liveness signal. This
module is that signal path, in three layers:

1. **Per-rank recording** — when ``TMOG_PODTRACE`` is on and
   ``TMOG_PODTRACE_DIR`` names an artifact root, every rank records its
   own TraceTree/EventLog into ``<dir>/rank-<k>/`` (started from
   `multihost.initialize`, saved from `multihost.finalize`). The engine
   call sites bracket each round's **compute**, **collective entry ->
   exit** (the psum/allgather barrier wall, measured as monotonic deltas
   around each cross-host reduction) and **ingest stripe** walls with
   the `pod_round` / `compute` / `collective` / `ingest` context
   managers below. On the fused mesh path the compute and the psum live
   in ONE jitted program, so the bracketed collective window = program
   call + result fetch: a victim rank's collective wall inflates while
   it waits for a straggler, and the straggler itself shows large
   *derived compute* (round wall minus collective wall) — which is
   exactly the attribution the skew table reads.

2. **Heartbeats** — each bracket transition appends one JSON line
   (round, phase, monotonic, wall ts) to ``rank-<k>/heartbeat.jsonl``
   via a single O_APPEND write (atomic on POSIX; a torn final line is
   ignored by readers). `launch_local_pod`'s reaper reads the tails to
   name the wedged rank, round and collective in its timeout error
   (`straggler_table`) instead of the generic wedged message.

3. **Post-hoc merge** — `merge_pod` joins N rank dirs into one Chrome
   trace with rank swimlanes. Rank clocks are NOT synchronized, so the
   merge uses durations only, aligned on shared round boundaries: round
   r of every rank starts at the same merged timestamp and the merged
   round width is the slowest rank's width. Per round it computes the
   straggler rank, the max/median derived-compute ratio and each rank's
   collective-wait share; an MFU pass attributes analytic FLOPs/bytes
   (the planner's priors) to the measured spans and names the top
   sinks (`mfu_table`); `harvest_pod` feeds the same spans into the
   per-backend planner corpus keyed by process count — the feedback
   flywheel ROADMAP item 4 names, now fed by every pod run.

Surfaces: ``trace-report --pod <dir>`` (merged timeline + skew table,
exit 1 on undercoverage or broken round alignment), ``bench.py
--multihost`` (skew/collective-wait block), ci.sh's pod stage (asserts
an injected straggler is detected and named).

Telemetry must never break bring-up or a fit: every recorder entry
point is a no-op unless active, and `start`/`finish` swallow their own
failures.
"""
from __future__ import annotations

import contextlib
import glob as _glob
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "enabled", "active", "start", "finish", "beat", "pod_round",
    "compute", "collective", "ingest", "note_collective",
    "read_heartbeat", "straggler_table", "rank_dirs", "merge_pod",
    "harvest_pod", "pod_report", "pod_report_rc", "COVERAGE_MIN",
    "STRAGGLER_RATIO", "HEARTBEAT_NAME", "METRICS_NAME", "META_NAME",
]

HEARTBEAT_NAME = "heartbeat.jsonl"
METRICS_NAME = "metrics.json"
META_NAME = "meta.json"

#: per-round interval-union coverage floor `trace-report --pod` enforces
#: (the acceptance bar: compute + collective + ingest spans must explain
#: at least this share of each rank's round wall)
COVERAGE_MIN = 0.75

#: max/median derived-compute ratio above which a round names a straggler
STRAGGLER_RATIO = 1.5

#: span kinds the recorder emits (merge keys on these)
POD_KINDS = ("pod_round", "pod_compute", "pod_collective", "pod_ingest")

#: span kinds that count toward per-round coverage: the explicit pod
#: brackets plus the tileplane/kernel spans the engines already emit
#: (a streamed stats pass inside a round is covered by its tile spans,
#: not by a redundant pod_compute wrapper)
_COVER_KINDS = ("pod_compute", "pod_collective", "pod_ingest", "tile",
                "kernel")


def _env_on(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "off", "no")


def enabled() -> bool:
    """TMOG_PODTRACE: master switch for per-rank pod recording
    (launch_local_pod's `trace_dir` kwarg sets it for every child)."""
    return _env_on("TMOG_PODTRACE")


def _heartbeat_interval_s() -> float:
    """TMOG_PODTRACE_HEARTBEAT_S: min seconds between non-forced beats
    (phase transitions always beat — the rate limit only throttles
    repeats of the same phase)."""
    try:
        return max(float(os.environ.get("TMOG_PODTRACE_HEARTBEAT_S",
                                        "0.5")), 0.0)
    except ValueError:
        return 0.5


def _span_budget() -> int:
    """TMOG_PODTRACE_SPAN_BUDGET: pod spans recorded per rank before
    span bookkeeping stops (heartbeats continue — liveness outlives the
    bounded trace, same shape as TMOG_SERVE_SPAN_BUDGET)."""
    try:
        return max(int(os.environ.get("TMOG_PODTRACE_SPAN_BUDGET",
                                      "20000")), 0)
    except ValueError:
        return 20000


def _debug_sleep_ms() -> float:
    """TMOG_PODTRACE_DEBUG_SLEEP_MS: chaos hook — the rank it is set on
    sleeps this long inside every pod_round, inside an explicit
    pod_compute span (site=debug_sleep), so the skew table must flag it
    as the straggler. 0 = disabled; launch_local_pod's `debug_sleep_ms`
    kwarg sets it on one rank only."""
    try:
        return max(float(os.environ.get("TMOG_PODTRACE_DEBUG_SLEEP_MS",
                                        "0")), 0.0)
    except ValueError:
        return 0.0


class _Recorder:
    """Process-local recorder state. One per rank process; the lock
    serializes beats (the tileplane producer thread and the host
    dispatch thread both cross bracket boundaries) — tmoglint THR001."""

    def __init__(self) -> None:
        self.active = False
        self.rank = 0
        self.dir: Optional[str] = None
        self.hb_fd: Optional[int] = None
        self.owns_collector = False
        self.round: Optional[int] = None
        self.phase = "init"
        self.last_beat = 0.0
        self.spans = 0
        self.lock = threading.RLock()


_rec = _Recorder()


def active() -> bool:
    return _rec.active


def start(process_id: Optional[int] = None,
          processes: Optional[int] = None) -> Optional[str]:
    """Begin per-rank recording (idempotent; returns the rank dir or
    None). Called from `multihost.initialize()` after bring-up; no-op
    unless TMOG_PODTRACE is on and TMOG_PODTRACE_DIR names a root.
    Failures are swallowed: the flight recorder must never break the
    pod it is observing."""
    with _rec.lock:
        if _rec.active or not enabled():
            return _rec.dir
        root = os.environ.get("TMOG_PODTRACE_DIR", "").strip()
        if not root:
            return None
        try:
            if process_id is None:
                process_id = int(os.environ.get("TMOG_PROC_ID", "0") or 0)
            rank_dir = os.path.join(root, f"rank-{int(process_id)}")
            os.makedirs(rank_dir, exist_ok=True)
            from ..utils.metrics import collector
            if not collector.collecting:
                collector.enable(f"pod-rank{int(process_id)}")
                _rec.owns_collector = True
            collector.attach_event_log(
                os.path.join(rank_dir, "events.jsonl"))
            backend = "cpu"
            jmod = sys.modules.get("jax")
            if jmod is not None:
                try:
                    backend = str(jmod.default_backend())
                except Exception:
                    pass
            meta = {"rank": int(process_id), "pid": os.getpid(),
                    "backend": backend, "ts": round(time.time(), 3)}
            if processes is not None:
                meta["processes"] = int(processes)
            with open(os.path.join(rank_dir, META_NAME), "w",
                      encoding="utf-8") as fh:
                json.dump(meta, fh)
            _rec.hb_fd = os.open(
                os.path.join(rank_dir, HEARTBEAT_NAME),
                os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
            _rec.rank = int(process_id)
            _rec.dir = rank_dir
            _rec.round = None
            _rec.phase = "init"
            _rec.spans = 0
            _rec.active = True
        except Exception:
            _rec.active = False
            return None
    beat("start", force=True)
    return _rec.dir


def finish() -> None:
    """Save this rank's artifacts and stop recording (idempotent).
    Called from `multihost.finalize()` — i.e. while every peer is still
    alive, so a rank killed mid-run simply leaves a torn dir, which
    `merge_pod` degrades to a partial report."""
    with _rec.lock:
        if not _rec.active:
            return
        _rec.active = False
        rank_dir, fd = _rec.dir, _rec.hb_fd
        owns = _rec.owns_collector
        _rec.hb_fd = None
        _rec.owns_collector = False
    try:
        _write_beat(fd, _rec.round, "finish")
    except Exception:
        pass
    try:
        from ..utils.metrics import collector
        if rank_dir is not None:
            # a joined run (caller owns the collector) gets a snapshot
            # save; an owned run closes out — either way metrics.json
            # carries the span tree merge_pod reads
            collector.save(os.path.join(rank_dir, METRICS_NAME),
                           close=owns)
    except Exception:
        pass
    if fd is not None:
        try:
            os.close(fd)
        except OSError:
            pass


def _write_beat(fd: Optional[int], rnd: Optional[int],
                phase: str) -> None:
    if fd is None:
        return
    rec = {"round": rnd, "phase": phase,
           "mono": round(time.perf_counter(), 6),
           "ts": round(time.time(), 6)}
    # ONE os.write of one full line on an O_APPEND fd: atomic on POSIX,
    # so a concurrent reader sees whole lines or a torn tail it ignores
    os.write(fd, (json.dumps(rec) + "\n").encode("utf-8"))


def beat(phase: str, rnd: Optional[int] = None,
         force: bool = False) -> None:
    """Append one heartbeat line (rate-limited unless the phase changed
    or `force`). The launcher's reaper reads the tail to name a wedged
    rank's last (round, phase)."""
    with _rec.lock:
        if not _rec.active:
            return
        if rnd is not None:
            _rec.round = int(rnd)
        now = time.perf_counter()
        if not (force or phase != _rec.phase
                or now - _rec.last_beat >= _heartbeat_interval_s()):
            return
        _rec.phase = phase
        _rec.last_beat = now
        fd, cur = _rec.hb_fd, _rec.round
    try:
        _write_beat(fd, cur, phase)
    except OSError:
        pass  # full disk must not kill the run it is monitoring


def _budget_ok() -> bool:
    with _rec.lock:
        if not _rec.active:
            return False
        _rec.spans += 1
        return _rec.spans <= _span_budget()


@contextlib.contextmanager
def _span(name: str, kind: str, **attrs: Any) -> Iterator[Any]:
    if not _budget_ok():
        yield None
        return
    from ..utils.metrics import collector
    with collector.trace_span(name, kind, **attrs) as sp:
        yield sp


@contextlib.contextmanager
def pod_round(index: Any, **attrs: Any) -> Iterator[Any]:
    """Bracket one engine round (the shared alignment boundary the
    merge keys on: every rank runs the same round indexes). Fires the
    debug-sleep chaos hook inside an explicit pod_compute span so the
    injected straggler's wall is attributed, not mysterious."""
    if not _rec.active:
        yield None
        return
    idx = int(index)
    beat("round", rnd=idx, force=True)
    with _span(f"pod_round[{idx}]", "pod_round", round=idx,
               **attrs) as sp:
        ms = _debug_sleep_ms()
        if ms > 0:
            with _span("pod_compute[debug_sleep]", "pod_compute",
                       site="debug_sleep", sleep_ms=ms):
                time.sleep(ms / 1000.0)
        try:
            yield sp
        finally:
            beat("round_end", force=True)


@contextlib.contextmanager
def compute(site: str, **attrs: Any) -> Iterator[Any]:
    """Bracket host/device compute attributed to `site`."""
    if not _rec.active:
        yield None
        return
    beat(f"compute:{site}")
    with _span(f"pod_compute[{site}]", "pod_compute", site=site,
               **attrs) as sp:
        yield sp


@contextlib.contextmanager
def collective(site: str, **attrs: Any) -> Iterator[Any]:
    """Bracket one cross-host reduction, entry -> exit. The entry beat
    is forced: "last seen entering collective X of round N" is exactly
    what the reaper needs to name a wedge. On the fused mesh path the
    window is program call + fetch (the psum is inside the jitted
    program) — see the module docstring for how skew reads that."""
    if not _rec.active:
        yield None
        return
    beat(f"collective:{site}", force=True)
    try:
        with _span(f"pod_collective[{site}]", "pod_collective",
                   site=site, **attrs) as sp:
            yield sp
    finally:
        beat(f"post:{site}", force=True)


@contextlib.contextmanager
def ingest(site: str, **attrs: Any) -> Iterator[Any]:
    """Bracket one ingest stripe wall (parse + landing of this rank's
    rows)."""
    if not _rec.active:
        yield None
        return
    beat(f"ingest:{site}")
    with _span(f"pod_ingest[{site}]", "pod_ingest", site=site,
               **attrs) as sp:
        yield sp


def note_collective(site: str, dur: float, **attrs: Any) -> None:
    """Record an ALREADY-measured collective wall (e.g. the tileplane
    tile merge, whose blocking device wait is timed by the consumer's
    own block_until_ready window) without re-timing it."""
    if not _rec.active or not _budget_ok():
        return
    try:
        from ..utils.metrics import collector
        if collector.collecting:
            collector.trace.add_complete(
                f"pod_collective[{site}]", "pod_collective",
                max(float(dur), 0.0), site=site, **attrs)
    except Exception:
        pass


# -- heartbeat reading (launcher side) ---------------------------------------

def read_heartbeat(rank_dir: str) -> Optional[Dict[str, Any]]:
    """Last COMPLETE heartbeat record of one rank dir, or None. The
    atomic-append contract: only newline-terminated lines count, so a
    writer killed mid-append (or racing this reader) yields the
    previous beat, never a torn one."""
    path = os.path.join(rank_dir, HEARTBEAT_NAME)
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError:
        return None
    nl = raw.rfind(b"\n")
    if nl < 0:
        return None
    for line in reversed(raw[:nl].split(b"\n")):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(rec, dict):
            return rec
    return None


def rank_dirs(pod_dir: str) -> List[Tuple[int, str]]:
    """(rank, path) for every ``rank-<k>/`` under `pod_dir`, rank
    order."""
    out: List[Tuple[int, str]] = []
    for p in _glob.glob(os.path.join(pod_dir, "rank-*")):
        if not os.path.isdir(p):
            continue
        tail = os.path.basename(p)[len("rank-"):]
        if tail.isdigit():
            out.append((int(tail), p))
    return sorted(out)


def straggler_table(pod_dir: str,
                    rcs: Optional[List[Optional[int]]] = None
                    ) -> Tuple[str, List[int]]:
    """(table text, likely straggler ranks) from the per-rank heartbeat
    tails — what the launcher appends to its timeout / dead-coordinator
    error so the operator learns WHICH rank wedged, in which round, in
    which collective, without opening a single artifact.

    Straggler heuristic: a wedged pod is N-1 victims parked inside a
    collective ("collective:<site>" phase, beats stop at entry) plus
    the rank that never arrived — so ranks whose last phase is NOT a
    collective entry are the suspects; among them (or among all, when
    every rank reads "collective:") the oldest beat names the wedge."""
    dirs = rank_dirs(pod_dir)
    if not dirs:
        return ("(no podtrace heartbeats under %s)" % pod_dir, [])
    now = time.time()
    rows: List[Tuple[int, Optional[int], Optional[float],
                     Optional[int], str]] = []
    for rank, path in dirs:
        hb = read_heartbeat(path)
        rc = None
        if rcs is not None and rank < len(rcs):
            rc = rcs[rank]
        if hb is None:
            rows.append((rank, rc, None, None, "(no heartbeat)"))
            continue
        age = max(now - float(hb.get("ts") or now), 0.0)
        rnd = hb.get("round")
        rows.append((rank, rc, age,
                     int(rnd) if isinstance(rnd, int) else None,
                     str(hb.get("phase") or "?")))
    live = [r for r in rows if r[1] is None and r[2] is not None]
    pool = [r for r in live
            if not r[4].startswith("collective:")] or live
    pool = sorted(pool, key=lambda r: -(r[2] or 0.0))
    stragglers = [r[0] for r in pool[:1]]
    lines = ["rank  rc    beat_age_s  round  phase"]
    for rank, rc, age, rnd, phase in rows:
        lines.append(
            f"{rank:<4}  {str(rc):<4}  "
            f"{('%.1f' % age) if age is not None else '?':<10}  "
            f"{str(rnd) if rnd is not None else '?':<5}  {phase}")
    if stragglers:
        r = next(x for x in rows if x[0] == stragglers[0])
        lines.append(
            f"likely straggler: rank {r[0]} (round "
            f"{r[3] if r[3] is not None else '?'}, phase {r[4]}, "
            f"beat {('%.1f' % r[2]) if r[2] is not None else '?'}s ago)")
    return "\n".join(lines), stragglers


# -- post-hoc merge ----------------------------------------------------------

def _load_rank(rank: int, path: str) -> Dict[str, Any]:
    """One rank's artifacts; a killed-mid-write rank yields torn=True
    and empty spans (the partial-report contract), never a raise."""
    out: Dict[str, Any] = {"rank": rank, "path": path, "spans": [],
                           "meta": {}, "torn": False}
    try:
        with open(os.path.join(path, META_NAME), encoding="utf-8") as fh:
            meta = json.load(fh)
        if isinstance(meta, dict):
            out["meta"] = meta
    except (OSError, ValueError):
        pass
    try:
        with open(os.path.join(path, METRICS_NAME),
                  encoding="utf-8") as fh:
            doc = json.load(fh)
        spans = doc.get("spans") if isinstance(doc, dict) else None
        if not isinstance(spans, list):
            raise ValueError("no spans")
        out["spans"] = [s for s in spans if isinstance(s, dict)]
        out["doc"] = doc
    except (OSError, ValueError):
        out["torn"] = True
    return out


def _span_window(s: Dict[str, Any]) -> Optional[Tuple[float, float]]:
    t0, t1 = s.get("t_start"), s.get("t_end")
    if not isinstance(t0, (int, float)) or not isinstance(
            t1, (int, float)) or isinstance(t0, bool):
        return None
    return (float(t0), float(t1))


def _rank_rounds(spans: List[Dict[str, Any]]
                 ) -> Dict[int, Tuple[float, float]]:
    """round index -> (t_start, t_end) on this rank's own clock (first
    occurrence wins: a replayed index cannot stretch the window)."""
    out: Dict[int, Tuple[float, float]] = {}
    for s in spans:
        if s.get("kind") != "pod_round":
            continue
        rnd = (s.get("attrs") or {}).get("round")
        w = _span_window(s)
        if isinstance(rnd, int) and w is not None and rnd not in out:
            out[rnd] = w
    return out


def _union_seconds(ivals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [t0, t1] intervals (overlapping
    brackets — a tile span inside a pod_compute — must not double
    count toward coverage)."""
    total = 0.0
    end = None
    for t0, t1 in sorted(ivals):
        if end is None or t0 > end:
            total += max(t1 - t0, 0.0)
            end = t1
        elif t1 > end:
            total += t1 - end
            end = t1
    return total


def _median(vals: List[float]) -> float:
    if not vals:
        return 0.0
    v = sorted(vals)
    n = len(v)
    return v[n // 2] if n % 2 else 0.5 * (v[n // 2 - 1] + v[n // 2])


# analytic FLOPs/bytes priors per collective/compute site, from the
# attrs the instrumentation sites stamp (rows/feat/lanes/iters). These
# are the planner's closed-form work models, reused so the MFU table's
# numerator and the calibration corpus agree on what "work" means.
def _analytic_cost(name: str, attrs: Dict[str, Any]
                   ) -> Tuple[float, float]:
    """(flops, bytes) attributed to one measured span; (0, 0) when the
    shape attrs are absent (the span still ranks by wall)."""
    def num(*keys: str, default: float = 0.0) -> float:
        for k in keys:
            v = attrs.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return float(v)
        return default

    rows = num("rows", "n_rows")
    feat = num("feat", "cols")
    lanes = num("lanes", default=1.0)
    iters = num("iters", "n_iter", default=1.0)
    site = str(attrs.get("site") or name)
    if "glm_round" in site:
        # streamed IRLS round: per iter, eta = X @ B (2*r*f*l), working
        # response + weights (~6*r*l), gram/rhs accumulation
        # (~3*r*f*l) — call it 5*r*f*l*iters with X re-read per iter
        return (5.0 * rows * feat * lanes * iters,
                4.0 * rows * feat * iters)
    if "gram" in site:
        # one-shot X^T X (+ X^T y per lane): r*f*(f+l) MACs
        return (2.0 * rows * feat * (feat + lanes),
                4.0 * rows * feat)
    if "tree" in site:
        depth = num("depth", default=6.0)
        folds = num("folds", default=1.0)
        return (2.0 * rows * feat * depth * max(folds, 1.0),
                4.0 * rows * feat)
    if "stats" in site or "tile" in site:
        cols = feat or num("cols")
        return (8.0 * rows * cols, 4.0 * rows * cols)
    return (0.0, 0.0)


def merge_pod(pod_dir: str, out: Optional[str] = None,
              coverage_min: float = COVERAGE_MIN) -> Dict[str, Any]:
    """Join every ``rank-<k>/`` under `pod_dir` into one report dict +
    merged Chrome trace (written to `out`, default
    ``<pod_dir>/pod_trace.json``).

    Rank clocks are unsynchronized, so only DURATIONS are merged:
    round r starts at one shared merged timestamp for every rank and
    advances by the slowest rank's round wall. Returns::

        {"ranks": [...per-rank summaries...],
         "rounds": [...per-round skew rows...],
         "skew": {straggler_rank, flagged, max_ratio, ...},
         "mfu_table": [...top sinks...],
         "coverage_min_seen": float | None,
         "problems": [...strings...],
         "trace_path": out, "synthetic_rounds": bool}

    A torn rank dir (killed mid-write) degrades to a partial report; a
    rank whose round-index chain differs from its peers is a "broken
    round alignment" problem (exit 1 via `pod_report_rc`)."""
    dirs = rank_dirs(pod_dir)
    ranks = [_load_rank(rank, path) for rank, path in dirs]
    problems: List[str] = []
    for r in ranks:
        if r["torn"]:
            problems.append(
                f"rank {r['rank']}: torn artifact dir (no readable "
                f"{METRICS_NAME}) — partial report")

    live = [r for r in ranks if not r["torn"]]
    per_rank_rounds = {r["rank"]: _rank_rounds(r["spans"]) for r in live}

    # round alignment: every live rank must have run the same rounds
    synthetic = all(not rr for rr in per_rank_rounds.values())
    if synthetic:
        for r in live:
            windows = [w for s in r["spans"]
                       if s.get("kind") in _COVER_KINDS
                       for w in [_span_window(s)] if w is not None]
            if windows:
                per_rank_rounds[r["rank"]] = {
                    0: (min(w[0] for w in windows),
                        max(w[1] for w in windows))}
    else:
        idx_sets = {rank: frozenset(rr)
                    for rank, rr in per_rank_rounds.items() if rr}
        if len(set(idx_sets.values())) > 1:
            detail = "; ".join(
                f"rank {k}: rounds {sorted(v)[:8]}"
                for k, v in sorted(idx_sets.items()))
            problems.append(f"broken round alignment — {detail}")

    all_rounds = sorted({i for rr in per_rank_rounds.values()
                         for i in rr})

    # per (rank, round): wall, collective wall, coverage
    per_cell: Dict[Tuple[int, int], Dict[str, float]] = {}
    for r in live:
        rr = per_rank_rounds.get(r["rank"], {})
        for idx, (r0, r1) in rr.items():
            wall = max(r1 - r0, 0.0)
            coll_ivals: List[Tuple[float, float]] = []
            cover: List[Tuple[float, float]] = []
            for s in r["spans"]:
                kind = s.get("kind")
                if kind == "pod_round":
                    continue
                w = _span_window(s)
                if w is None or w[0] < r0 - 1e-6 or w[1] > r1 + 1e-6:
                    continue
                if kind == "pod_collective":
                    # UNION, not sum: a nested collective bracket (e.g.
                    # row_layout inside a wider window) must not double
                    # count toward the rank's wait share
                    coll_ivals.append(w)
                if kind in _COVER_KINDS:
                    cover.append(w)
            coll = _union_seconds(coll_ivals)
            per_cell[(r["rank"], idx)] = {
                "wall": wall, "collective": coll,
                "compute": max(wall - coll, 0.0),
                "coverage": (_union_seconds(cover) / wall
                             if wall > 0 else 1.0)}

    # skew per round
    round_rows: List[Dict[str, Any]] = []
    flag_counts: Dict[int, int] = {}
    coverage_min_seen: Optional[float] = None
    for idx in all_rounds:
        cells = {r["rank"]: per_cell[(r["rank"], idx)]
                 for r in live if (r["rank"], idx) in per_cell}
        if not cells:
            continue
        comp = {k: c["compute"] for k, c in cells.items()}
        med = _median(list(comp.values()))
        straggler = max(comp, key=lambda k: comp[k])
        ratio = (comp[straggler] / med) if med > 0 else (
            float("inf") if comp[straggler] > 0 else 1.0)
        flagged = ratio >= STRAGGLER_RATIO
        if flagged:
            flag_counts[straggler] = flag_counts.get(straggler, 0) + 1
        for k, c in cells.items():
            cov = c["coverage"]
            if coverage_min_seen is None or cov < coverage_min_seen:
                coverage_min_seen = cov
            if not synthetic and cov < coverage_min:
                problems.append(
                    f"rank {k} round {idx}: spans cover "
                    f"{100.0 * cov:.0f}% of the round wall "
                    f"(< {100.0 * coverage_min:.0f}%)")
        round_rows.append({
            "round": idx,
            "straggler_rank": straggler,
            "flagged": flagged,
            "compute_ratio": round(min(ratio, 1e9), 3),
            "wall_s": {k: round(c["wall"], 6)
                       for k, c in cells.items()},
            "collective_s": {k: round(c["collective"], 6)
                             for k, c in cells.items()},
            "collective_share": {
                k: round(c["collective"] / c["wall"], 4)
                if c["wall"] > 0 else 0.0 for k, c in cells.items()},
        })

    # merged timeline: shared round starts, slowest rank sets the width
    t_merged: Dict[int, float] = {}
    t_cursor = 0.0
    for idx in all_rounds:
        t_merged[idx] = t_cursor
        t_cursor += max((per_cell[(r["rank"], idx)]["wall"]
                         for r in live
                         if (r["rank"], idx) in per_cell),
                        default=0.0)

    events: List[Dict[str, Any]] = []
    for r in live:
        events.append({"ph": "M", "name": "process_name",
                       "pid": r["rank"], "tid": 0,
                       "args": {"name": f"rank-{r['rank']}"}})
        rr = per_rank_rounds.get(r["rank"], {})
        for s in r["spans"]:
            w = _span_window(s)
            if w is None:
                continue
            home = next((idx for idx, (r0, r1) in rr.items()
                         if w[0] >= r0 - 1e-6 and w[1] <= r1 + 1e-6),
                        None)
            if home is None:
                continue  # outside every round: not alignable
            shift = t_merged[home] - rr[home][0]
            args = dict(s.get("attrs") or {})
            args["rank"] = r["rank"]
            args["span_id"] = s.get("span_id")
            events.append({
                "ph": "X", "name": str(s.get("name", "?")),
                "cat": str(s.get("kind", "span")),
                "ts": round((w[0] + shift) * 1e6, 3),
                "dur": round((w[1] - w[0]) * 1e6, 3),
                "pid": r["rank"], "tid": 1, "args": args})

    if out is None:
        out = os.path.join(pod_dir, "pod_trace.json")
    trace_path: Optional[str] = out
    try:
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w", encoding="utf-8") as fh:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms",
                       "otherData": {"pod_dir": pod_dir,
                                     "ranks": len(ranks),
                                     "alignment": "round-boundary, "
                                                  "durations only"}},
                      fh, indent=1)
    except OSError as e:
        problems.append(f"cannot write merged trace {out}: {e}")
        trace_path = None

    # MFU pass: analytic FLOPs/bytes per measured span, summed per site
    mfu_table = _mfu_table(live)

    # pod-level straggler: the rank flagged most often
    skew: Dict[str, Any] = {"flagged": bool(flag_counts)}
    if flag_counts:
        top = max(flag_counts, key=lambda k: flag_counts[k])
        skew["straggler_rank"] = top
        skew["flagged_rounds"] = flag_counts[top]
        skew["max_ratio"] = max(rw["compute_ratio"]
                                for rw in round_rows if rw["flagged"])

    rank_rows = []
    for r in ranks:
        cells = [per_cell[(r["rank"], i)] for i in all_rounds
                 if (r["rank"], i) in per_cell]
        wall = sum(c["wall"] for c in cells)
        coll = sum(c["collective"] for c in cells)
        rank_rows.append({
            "rank": r["rank"], "torn": r["torn"],
            "rounds": len(cells),
            "round_wall_s": round(wall, 6),
            "collective_s": round(coll, 6),
            "collective_share": round(coll / wall, 4) if wall > 0
            else 0.0,
            "min_coverage": round(min((c["coverage"] for c in cells),
                                      default=0.0), 4)})

    report = {"pod_dir": pod_dir, "ranks": rank_rows,
              "rounds": round_rows, "skew": skew,
              "mfu_table": mfu_table,
              "coverage_min_seen": (round(coverage_min_seen, 4)
                                    if coverage_min_seen is not None
                                    else None),
              "synthetic_rounds": synthetic,
              "problems": problems, "trace_path": trace_path}
    try:
        from ..utils.metrics import collector
        collector.event("podtrace_merge", pod_dir=pod_dir,
                        ranks=len(ranks), rounds=len(all_rounds),
                        problems=len(problems),
                        flagged=skew.get("flagged", False))
        if skew.get("flagged"):
            collector.event("pod_straggler",
                            rank=skew.get("straggler_rank"),
                            rounds=skew.get("flagged_rounds"),
                            max_ratio=skew.get("max_ratio"))
        if mfu_table:
            collector.event("mfu_table", sinks=mfu_table[:3])
    except Exception:
        pass
    return report


def _mfu_table(live: List[Dict[str, Any]],
               top: int = 3) -> List[Dict[str, Any]]:
    """Top measured sinks with analytic FLOPs/bytes attributed — the
    "where did the pod's wall go, and how far from the roof was it"
    table every traced fit emits. MFU needs a known FLOPs roof
    (utils.metrics.flops_roof_gflops); off-TPU the sinks still rank by
    wall with mfu omitted."""
    roof_gflops = None
    try:
        from ..utils import metrics as M
        jmod = sys.modules.get("jax")
        if jmod is not None:
            kind = jmod.devices()[0].device_kind
            roof_gflops = M.flops_roof_gflops(kind)
    except Exception:
        roof_gflops = None
    agg: Dict[str, List[float]] = {}
    total_wall = 0.0
    for r in live:
        for s in r["spans"]:
            if s.get("kind") not in ("pod_collective", "pod_compute",
                                     "pod_ingest", "kernel"):
                continue
            wall = float(s.get("duration_seconds") or 0.0)
            if wall <= 0.0:
                continue
            attrs = s.get("attrs") or {}
            flops, bts = _analytic_cost(str(s.get("name", "")), attrs)
            if not bts:
                b = attrs.get("bytes_hbm")
                if isinstance(b, (int, float)):
                    bts = float(b)
            slot = agg.setdefault(str(s.get("name", "?")),
                                  [0.0, 0.0, 0.0])
            slot[0] += wall
            slot[1] += flops
            slot[2] += bts
            total_wall += wall
    rows = []
    for name, (wall, flops, bts) in sorted(
            agg.items(), key=lambda kv: -kv[1][0]):
        row: Dict[str, Any] = {
            "span": name, "wall_s": round(wall, 6),
            "wall_share": round(wall / total_wall, 4)
            if total_wall > 0 else 0.0,
            "gflops": round(flops / 1e9, 3),
            "gbytes": round(bts / 1e9, 3)}
        if roof_gflops and wall > 0 and flops > 0:
            row["mfu"] = round(flops / wall / (roof_gflops * 1e9), 4)
        rows.append(row)
    return rows[:top]


# -- planner-corpus harvest --------------------------------------------------

def harvest_pod(pod_dir: str, corpus_path: Optional[str] = None,
                backend: Optional[str] = None) -> int:
    """Harvest every rank's measured spans into the per-backend planner
    corpus, keyed by process count twice over: the backend key carries
    the ``-pc<N>`` suffix (the SAME convention planner/plan._backend
    uses inside a pod, so these rows land in the corpus file the pod's
    own plan lookups read) and the pod span shapes carry
    ``shape["procs"]`` — pod evidence never mixes with single-process
    evidence at the same geometry. Returns the number of NEW corpus
    rows. Reuses `corpus.harvest_metrics_doc` for the kernel/tile spans
    each rank's metrics.json already carries, plus the pod span
    families (`corpus.harvest_pod_spans`)."""
    from ..planner import corpus as C
    from ..planner.plan import corpus_dir
    dirs = rank_dirs(pod_dir)
    if not dirs:
        return 0
    procs = len(dirs)
    records = []
    for rank, path in dirs:
        loaded = _load_rank(rank, path)
        if loaded["torn"]:
            continue
        b = backend or str(loaded["meta"].get("backend") or "cpu")
        if procs > 1 and not b.endswith(f"-pc{procs}"):
            b = f"{b}-pc{procs}"
        doc = loaded.get("doc") or {}
        records.extend(C.harvest_metrics_doc(doc, b, src="podtrace"))
        records.extend(C.harvest_pod_spans(loaded["spans"], b,
                                           procs=procs,
                                           src="podtrace"))
    store = C.Corpus(corpus_path or corpus_dir())
    return store.append(records)


# -- trace-report --pod ------------------------------------------------------

def _fmt(rows: List[List[str]], header: List[str]) -> List[str]:
    from ..utils.tracing import _fmt_table
    return _fmt_table(rows, header)


def pod_report(pod_dir: str, top: int = 15) -> Tuple[str, bool]:
    """(report text, ok) for a merged pod run dir."""
    report = merge_pod(pod_dir)
    lines = [f"# trace-report --pod {pod_dir}"]
    lines.append(f"\n## Ranks ({len(report['ranks'])})")
    lines.extend(_fmt(
        [[str(r["rank"]), "torn" if r["torn"] else "ok",
          str(r["rounds"]), f"{r['round_wall_s']:.4f}",
          f"{r['collective_s']:.4f}",
          f"{100.0 * r['collective_share']:.1f}%",
          f"{100.0 * r['min_coverage']:.0f}%"]
         for r in report["ranks"]],
        ["rank", "state", "rounds", "round_wall_s", "collective_s",
         "coll_share", "min_cover"]))
    if report["rounds"]:
        lines.append(f"\n## Per-round skew"
                     f" ({len(report['rounds'])} rounds"
                     + (", synthetic boundaries"
                        if report["synthetic_rounds"] else "") + ")")
        lines.extend(_fmt(
            [[str(rw["round"]), str(rw["straggler_rank"]),
              "*" if rw["flagged"] else "",
              f"{rw['compute_ratio']:.2f}",
              " ".join(f"r{k}={v:.3f}"
                       for k, v in sorted(rw["wall_s"].items())),
              " ".join(f"r{k}={100.0 * v:.0f}%"
                       for k, v in
                       sorted(rw["collective_share"].items()))]
             for rw in report["rounds"][:top]],
            ["round", "straggler", "flag", "max/med", "wall_s",
             "coll_share"]))
    skew = report["skew"]
    if skew.get("flagged"):
        lines.append(
            f"\nstraggler: rank {skew['straggler_rank']} flagged in "
            f"{skew['flagged_rounds']} round(s), max compute ratio "
            f"{skew['max_ratio']:.2f}")
    if report["mfu_table"]:
        lines.append("\n## Top sinks (analytic FLOPs/bytes)")
        lines.extend(_fmt(
            [[row["span"][:44], f"{row['wall_s']:.4f}",
              f"{100.0 * row['wall_share']:.1f}%",
              f"{row['gflops']:.2f}", f"{row['gbytes']:.3f}",
              f"{row['mfu']:.4f}" if "mfu" in row else "-"]
             for row in report["mfu_table"]],
            ["span", "wall_s", "share", "gflops", "gbytes", "mfu"]))
    if report["trace_path"]:
        lines.append(f"\nmerged trace: {report['trace_path']}")
    if report["problems"]:
        lines.append(f"\n## {len(report['problems'])} problem(s)")
        lines.extend(f"  {p}" for p in report["problems"])
    return "\n".join(lines), not report["problems"]


def pod_report_rc(pod_dir: str, top: int = 15) -> Tuple[str, int]:
    """(text, exit code), project-wide code table
    (docs/static_analysis.md "Exit codes"): 0 = clean, 1 = problems
    (undercoverage, broken round alignment, torn rank dirs), 2 = usage
    error (no ``rank-<k>/`` dirs at all — nothing to merge)."""
    if not rank_dirs(pod_dir):
        return (f"trace-report --pod: no rank-*/ dirs under {pod_dir} "
                f"(not a podtrace artifact root)", 2)
    text, ok = pod_report(pod_dir, top=top)
    return text, 0 if ok else 1
