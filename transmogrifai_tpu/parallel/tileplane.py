"""Unified double-buffered host->device row-tile pipeline.

Every streamed hot path in this repo used to own a private, fully
SYNCHRONOUS tile loop: ops/stats_engine.stream_stats dispatched one tile,
blocked on the state fetch, host-merged, then started the next H2D copy
(zero copy/compute overlap); ops/glm_sweep.sweep_glm_streamed_rounds
re-read X per round through its own loop; tree binning and bulk scoring
required a resident matrix. Large-scale JAX/TPU training gets its
throughput precisely by overlapping the input pipeline's H2D transfers
with device compute behind async dispatch (PAPERS: pjit/TPUv4 training,
arxiv 2204.06514), and external-memory gradient boosting shows tree
workloads stream well when tiles keep a fixed shape (PAPERS: XGBoost GPU,
arxiv 1806.11248).

This module is the ONE pipeline those consumers now share:

- a background PRODUCER thread slices/pads row chunks into fixed-shape
  numpy tiles (ragged tail zero-padded — the repo-wide zero-weight pad
  convention makes padded rows inert in every consumer's math) and
  `device_put`s tile k+1 while the caller's thread runs tile k's jitted
  step — classic double buffering, generalized to a DEPTH-N PREFETCH
  RING: the copy slot carries `TMOG_TILE_PREFETCH` tokens (released
  when the consumer dequeues a tile), so at most depth+1 tiles are ever
  in flight — the one computing plus up to `depth` copied-ahead. The
  hand default of 1 is exactly the old two-in-flight double buffering;
  the plan-time autotuner raises it when measured tile_parse/tile_copy
  unit costs dominate tile_compute (docs/planning.md). Depth NEVER
  changes tile sizes or boundaries, so results stay bit-identical at
  any depth;
- the feed side itself can parallelize: a RowSource may parse file
  shards on a worker pool (parallel/ingest.ShardedSource) as long as
  `chunks()` yields the same chunk sequence as a serial read — the
  fixed-tile assembly below is order-preserving, which is what keeps
  stats/GLM/tree reductions bit-identical to serial ingest;
- the CARRY (moment state, GLM accumulators) stays device-resident for
  the whole pass and is fetched ONCE at the end, not per tile;
- the consumer's jitted step DONATES the carry (donate_argnums=(0,)),
  so the accumulator updates in place; tile buffers are not
  donate-marked — they have no same-shaped output to alias (XLA would
  warn and copy) and their last host reference dies at dispatch, which
  frees them just as early;
- fixed tile shapes mean ONE executable per (consumer, tile shape): the
  RecompileTracker pins 0 recompiles from tile 2 onward;
- when tracing is enabled (utils/metrics.collector), every tile records a
  `tile_copy` span (producer thread, around device_put + ready fence) and
  a `tile_compute` span (consumer thread, around the step dispatch +
  carry fence), so copy/compute OVERLAP is measurable in the exported
  Perfetto trace rather than asserted;
- an optional shard_map lane: the producer device_puts tiles with the
  caller-supplied shardings (parallel/mesh.batch_sharding) and the
  consumer's step runs under shard_map — stats tiles psum-merge across
  the mesh batch axis exactly like the resident sharded driver (and
  under the same tmoglint SHD collective-correctness gate: the lane's
  replicated carry is only sound because each tile's cross-shard merge
  psums before folding in — see docs/static_analysis.md).

`TMOG_TILEPLANE=0` is the global kill switch: every consumer keeps its
legacy synchronous loop behind it. `TMOG_TILE_MB` sizes tiles (default
32MB of f32 rows, matching the stats engine's scan-tile budget).

Sources are RE-ITERABLE (`RowSource.chunks()` starts a fresh pass), so a
multi-pass consumer (GLM Newton rounds) re-reads disk instead of holding
X: a larger-than-HBM CSV/Avro flow runs fit -> score end-to-end without
ever materializing the matrix.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import (Any, Callable, Dict, Iterable, Iterator, List,
                    NamedTuple, Optional, Sequence, Tuple)

import numpy as np

_TILE_MB_DEFAULT = 32
_TILE_PREFETCH_DEFAULT = 1


def env_on(name: str, default: str = "1") -> bool:
    """Tri-state TMOG_* toggle parse (same falsy spellings as
    ops/glm_sweep.env_on; duplicated here rather than imported so the
    parallel/ layer never triggers the ops/ package import at module
    init)."""
    return os.environ.get(name, default).strip().lower() \
        not in ("0", "false", "off")


def tileplane_enabled() -> bool:
    """THE kill switch: TMOG_TILEPLANE=0 restores every consumer's legacy
    synchronous streamed loop."""
    return env_on("TMOG_TILEPLANE")


def tile_budget_bytes() -> int:
    """Host/device bytes per tile: the knob that sizes every consumer's
    tile. Two tiles in flight + the carry is the pipeline's whole device
    footprint. An explicitly-set TMOG_TILE_MB wins (hand beats model);
    otherwise the plan-time autotuner picks the size — a cold corpus
    (or TMOG_PLAN=0, or any planner fault) yields the same 32MB hand
    default this knob always had (docs/planning.md)."""
    try:
        from ..planner.plan import planned_tile_mb
        return planned_tile_mb() << 20
    except Exception:
        return int(os.environ.get(
            "TMOG_TILE_MB", str(_TILE_MB_DEFAULT))) << 20


def tile_prefetch_depth() -> int:
    """Copy-slot tokens in the prefetch ring: how many tiles the
    producer may run AHEAD of the consumer (device footprint is
    depth+1 tiles plus the carry). An explicitly-set TMOG_TILE_PREFETCH
    wins (hand beats model); otherwise the plan-time autotuner derives
    the depth from measured tile_parse/tile_copy/tile_compute span
    ratios — a cold corpus (or TMOG_PLAN=0, or any planner fault)
    yields the depth-1 hand default, i.e. the classic double buffering
    this pipeline always had. Depth only changes how far the feed side
    runs ahead, never tile shapes, so any depth is bit-identical."""
    try:
        from ..planner.plan import planned_tile_prefetch
        return max(1, int(planned_tile_prefetch()))
    except Exception:
        try:
            return max(1, int(os.environ.get(
                "TMOG_TILE_PREFETCH", str(_TILE_PREFETCH_DEFAULT))))
        except ValueError:
            return _TILE_PREFETCH_DEFAULT


def tile_rows_for(row_bytes: int, n_rows: Optional[int] = None,
                  multiple: int = 1) -> int:
    """Rows per tile for a given per-row byte width, clamped to [256,
    2^20], rounded UP to `multiple` (mesh batch-axis divisibility)."""
    c = tile_budget_bytes() // max(int(row_bytes), 1)
    c = max(min(c, 1 << 20), 256)
    if n_rows is not None:
        c = max(min(c, int(n_rows)), 1)
    if multiple > 1:
        c = -(-c // multiple) * multiple
    return c


# -- row sources -------------------------------------------------------------

class RowSource:
    """Re-iterable source of host row-chunks.

    `chunks()` starts a FRESH pass and yields tuples of numpy arrays that
    share a leading row dimension (chunk sizes may vary; the pipeline
    re-tiles them). Multi-pass consumers (GLM rounds) call `chunks()` once
    per data pass — for file-backed sources that is a re-read of disk,
    which is the point: X never lives in memory.
    """

    #: row count if known up front (None for tail-follow sources)
    n_rows: Optional[int] = None

    def chunks(self) -> Iterator[Tuple[np.ndarray, ...]]:
        raise NotImplementedError

    _peek_cache: Optional[Tuple[np.ndarray, ...]] = None

    def peek(self) -> Tuple[np.ndarray, ...]:
        """First chunk of a fresh pass (shape/width probe for drivers
        that need d or F before streaming). Cached: repeated probes cost
        one chunk read TOTAL, not one per caller."""
        if self._peek_cache is None:
            it = self.chunks()
            try:
                self._peek_cache = next(it)
            except StopIteration:
                raise ValueError("empty row source") from None
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
        return self._peek_cache

    def set_span_anchor(self, anchor: Any) -> None:
        """Tile-span parent hook: run_tileplane hands the span current
        at pass START here, on the caller's thread, BEFORE any pipeline
        thread starts — a source that records its own `tile_parse`
        spans from parse workers (parallel/ingest.ShardedSource)
        parents them to the same anchor as tile_copy/tile_compute.
        Default: ignore."""


class ArraySource(RowSource):
    """Chunks sliced off resident host arrays (numpy views — no copies):
    the compatibility shim that lets `stream_stats(X, y, w)`-style callers
    ride the pipeline unchanged."""

    def __init__(self, *arrays: Any, chunk_rows: Optional[int] = None):
        self.arrays = [np.asarray(a) for a in arrays]
        self.n_rows = int(self.arrays[0].shape[0])
        for a in self.arrays:
            if a.shape[0] != self.n_rows:
                raise ValueError("row-count mismatch across source arrays")
        self.chunk_rows = int(chunk_rows) if chunk_rows else None

    def chunks(self) -> Iterator[Tuple[np.ndarray, ...]]:
        c = self.chunk_rows or self.n_rows
        for s in range(0, self.n_rows, c):
            yield tuple(a[s:s + c] for a in self.arrays)


class IterSource(RowSource):
    """Chunks from a factory of fresh iterators (generators over files,
    sockets, record decoders...)."""

    def __init__(self, factory: Callable[[], Iterable[Tuple[np.ndarray, ...]]],
                 n_rows: Optional[int] = None):
        self.factory = factory
        self.n_rows = n_rows

    def chunks(self) -> Iterator[Tuple[np.ndarray, ...]]:
        for chunk in self.factory():
            yield tuple(np.asarray(a) for a in chunk)


class PaddedSource(RowSource):
    """A source padded to exactly `n_target` rows with zero rows.

    The multi-host streamed pass needs every process to emit the SAME
    number of tiles — the tile step's psum is a collective, so a process
    running out of rows one tile early would wedge the whole pod in a
    reduction its peers never join. Each process wraps its (uneven)
    local stripe in a PaddedSource sized to the pod-uniform per-process
    row count (multihost.row_layout): padded rows are zeros, so the
    zero-weight convention keeps them inert in every statistic. The
    inner source must own at least one row (its first chunk is the
    shape template for the padding)."""

    def __init__(self, inner: RowSource, n_target: int):
        self.inner = inner
        self.n_target = int(n_target)
        self.n_rows = int(n_target)

    def chunks(self) -> Iterator[Tuple[np.ndarray, ...]]:
        seen = 0
        template: Optional[Tuple[np.ndarray, ...]] = None
        for chunk in self.inner.chunks():
            if template is None:
                template = chunk
            seen += chunk[0].shape[0]
            if seen > self.n_target:
                raise ValueError(
                    f"PaddedSource: inner source produced {seen} rows, "
                    f"more than the layout's {self.n_target}")
            yield chunk
        if seen < self.n_target:
            if template is None:
                raise ValueError("PaddedSource: empty inner source — "
                                 "every process must own at least one "
                                 "row (one file of its stripe)")
            miss = self.n_target - seen
            yield tuple(np.zeros((miss,) + tuple(a.shape[1:]), a.dtype)
                        for a in template)

    def peek(self) -> Tuple[np.ndarray, ...]:
        return self.inner.peek()

    def set_span_anchor(self, anchor: Any) -> None:
        self.inner.set_span_anchor(anchor)


def reader_row_source(read_records: Callable[[], Iterable[Dict[str, Any]]],
                      row_fn: Callable[[Dict[str, Any]],
                                       Sequence[Sequence[float]]],
                      batch_records: int = 4096,
                      n_rows: Optional[int] = None) -> RowSource:
    """The chunked `row-source -> numpy tile` adapter over the record
    readers (readers/avro.read_avro_file, readers/readers.CSVReader.read,
    streaming readers): `read_records()` starts a fresh record iteration;
    `row_fn(record)` maps one record to a tuple of per-array row values
    (e.g. `(x_row [d], y, w)`). Records buffer `batch_records` at a time
    into float32 chunks — the only host buffering between disk and the
    tile assembly."""

    def factory():
        buf: List[Sequence[Any]] = []

        def flush():
            cols = list(zip(*buf))
            return tuple(np.asarray(np.stack(c) if np.ndim(c[0]) else c,
                                    dtype=np.float32) for c in cols)

        for rec in read_records():
            buf.append(tuple(row_fn(rec)))
            if len(buf) >= batch_records:
                yield flush()
                buf = []
        if buf:
            yield flush()

    return IterSource(factory, n_rows=n_rows)


# -- fixed-shape re-tiling ---------------------------------------------------

def iter_fixed_tiles(source: RowSource, tile_rows: int,
                     track: Optional["TilePlaneStats"] = None
                     ) -> Iterator[Tuple[Tuple[np.ndarray, ...], int]]:
    """Re-slice a chunk stream into fixed `[tile_rows, ...]` numpy tiles,
    zero-padding the ragged tail; yields `(tile_arrays, n_valid)`.

    Synchronous — this is the shared assembly used by the producer thread
    AND by the legacy (TMOG_TILEPLANE=0) loops, so tile content is
    bit-identical on both paths. Zero padding keeps padded rows inert
    under the repo-wide zero-weight convention (w rides the source, so
    padding w with zeros IS the mask). At most one tile of rows is owned
    here at any time (`track.peak_host_rows` proves the <= 2-tile bound
    together with the chunk in hand)."""
    pend: List[Tuple[np.ndarray, ...]] = []
    pend_rows = 0
    narr = None

    def pop_tile() -> Tuple[Tuple[np.ndarray, ...], int]:
        nonlocal pend, pend_rows
        take, have = [], 0
        while pend and have < tile_rows:
            chunk = pend.pop(0)
            r = chunk[0].shape[0]
            if have + r <= tile_rows:
                take.append(chunk)
                have += r
            else:
                cut = tile_rows - have
                take.append(tuple(a[:cut] for a in chunk))
                pend.insert(0, tuple(a[cut:] for a in chunk))
                have = tile_rows
        pend_rows -= have
        parts = list(zip(*take))
        tile = []
        for ai in range(narr):
            arr = parts[ai][0] if len(parts[ai]) == 1 \
                else np.concatenate(parts[ai], axis=0)
            arr = np.ascontiguousarray(arr, dtype=np.float32)
            if arr.shape[0] < tile_rows:
                pad = [(0, tile_rows - arr.shape[0])] \
                    + [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad)
            tile.append(arr)
        return tuple(tile), have

    for chunk in source.chunks():
        if narr is None:
            narr = len(chunk)
        pend.append(chunk)
        pend_rows += chunk[0].shape[0]
        if track is not None:
            # single-writer: only the tile assembly (producer thread)
            # writes this; readers run after the producer joined
            # tmoglint: disable=THR001  read happens-after join
            track.peak_host_rows = max(track.peak_host_rows, pend_rows)
        while pend_rows >= tile_rows:
            yield pop_tile()
    while pend_rows > 0:
        yield pop_tile()


# -- the pipeline ------------------------------------------------------------

class TilePlaneStats:
    """Per-pass pipeline telemetry (mutable; filled as the pass runs)."""

    def __init__(self, tile_rows: int, label: str, prefetch: int = 1):
        self.label = label
        self.tile_rows = int(tile_rows)
        self.prefetch_depth = int(prefetch)
        self.tiles = 0
        self.rows = 0
        #: max host rows buffered in the tile assembly at any instant —
        #: the "X never materialized" proof: <= 2 * tile_rows by
        #: construction (one tile being assembled + one chunk in hand)
        self.peak_host_rows = 0
        self.copy_seconds = 0.0
        self.compute_seconds = 0.0
        self.wall_seconds = 0.0
        self.overlapped = None  # True when traced copy/compute windows met

    def to_json(self) -> Dict[str, Any]:
        return {"label": self.label, "tiles": self.tiles, "rows": self.rows,
                "tile_rows": self.tile_rows,
                "prefetch_depth": self.prefetch_depth,
                "peak_host_rows": self.peak_host_rows,
                "copy_seconds": round(self.copy_seconds, 6),
                "compute_seconds": round(self.compute_seconds, 6),
                "wall_seconds": round(self.wall_seconds, 6),
                "overlapped": self.overlapped}


class _Stop(Exception):
    pass


_SENTINEL = object()


def _device_put_tile(tile, shardings):
    """Land one host tile on the mesh. Single-host shardings are a plain
    device_put; a sharding spanning multiple PROCESSES means `tile` holds
    only THIS process's rows of the global tile, so the global array is
    assembled via make_array_from_process_local_data — each host's rows
    land on its own devices and never cross the wire (the cross-host
    traffic is the psum in the step, not the copy). Dims sharded over the
    batch axis scale by the process count; replicated dims do not."""
    import jax

    if shardings is None:
        return tuple(jax.device_put(a) for a in tile)
    out = []
    for a, s in zip(tile, shardings):
        if getattr(s, "is_fully_addressable", True):
            out.append(jax.device_put(a, s))
        else:
            pc = len({d.process_index
                      for d in np.asarray(s.mesh.devices).ravel()})
            gshape = list(a.shape)
            for i, name in enumerate(s.spec):
                if name is not None and i < len(gshape):
                    gshape[i] = gshape[i] * pc
            out.append(jax.make_array_from_process_local_data(
                s, np.ascontiguousarray(a), tuple(gshape)))
    return tuple(out)


def _producer(source: RowSource, tile_rows: int, q: "queue.Queue",
              copy_slot: threading.Semaphore, stop: threading.Event,
              stats: TilePlaneStats, shardings: Optional[Sequence[Any]],
              traced: bool, anchor=None) -> None:
    """Producer-thread body: assemble fixed tiles, device_put tile k+1
    while the consumer computes tile k, record tile_copy spans (anchored
    to the span current at pass START — the consumer thread's transient
    stage spans open and close concurrently and must not adopt them).

    `copy_slot` (prefetch-depth tokens, each released when the consumer
    DEQUEUES a tile) gates each device_put: at most `depth` tiles are
    copied-but-unconsumed while one computes, so in-flight device tiles
    are bounded at depth+1 — the footprint contract the TMOG_TILE_MB
    sizing guidance promises (depth 1 = the classic two-in-flight
    double buffering)."""
    import jax

    from ..utils.metrics import collector
    try:
        k = 0
        for tile, n_valid in iter_fixed_tiles(source, tile_rows, stats):
            acquired = False
            while not stop.is_set():
                if copy_slot.acquire(timeout=0.1):
                    acquired = True
                    break
            if not acquired:
                raise _Stop
            t0 = time.perf_counter()
            dev = _device_put_tile(tile, shardings)
            if traced:
                # fence so the span measures the COPY, not the enqueue;
                # blocks only this producer thread — the consumer keeps
                # computing tile k-1 concurrently, which is exactly the
                # overlap the span pair exists to expose
                jax.block_until_ready(dev)
                dur = time.perf_counter() - t0
                # producer-owned field; read only after th.join()
                # tmoglint: disable=THR001  read happens-after join
                stats.copy_seconds += dur
                collector.trace.add_complete(
                    "tile_copy", "tile", dur, parent_span=anchor,
                    tile=k, rows=int(n_valid), label=stats.label,
                    bytes=int(sum(a.nbytes for a in tile)))
            while not stop.is_set():
                try:
                    q.put((dev, n_valid, k), timeout=0.1)
                    break
                except queue.Full:
                    continue
            k += 1
        q.put(_SENTINEL)
    except _Stop:
        pass
    except BaseException as e:  # surfaced on the consumer thread
        q.put(("__error__", e))


def run_tileplane(source: RowSource, step: Callable[..., Any], carry0: Any,
                  *, tile_rows: int, label: str = "tileplane",
                  first_tile: Optional[Callable[..., Any]] = None,
                  sink: Optional[Callable[[np.ndarray, int], None]] = None,
                  shardings: Optional[Sequence[Any]] = None,
                  prefetch: Optional[int] = None
                  ) -> Tuple[Any, TilePlaneStats]:
    """ONE double-buffered pass of `source` through a fixed-shape jitted
    `step`, returning the final DEVICE carry and the pass stats.

    `prefetch` is the ring depth — how many tiles the producer may copy
    ahead of the consumer (None resolves tile_prefetch_depth(): env >
    planner > hand default 1). Depth changes device footprint
    ((depth+1) tiles + carry) and overlap, never tile boundaries, so
    the carry is bit-identical at any depth.

    step(carry, *tile_arrays) -> carry, or -> (carry, out_tile) when
    `sink` is given (out tiles are fetched with a one-tile lag and handed
    to `sink(np_out, n_valid)` so the D2H fetch of tile k overlaps tile
    k+1's compute). The consumer owns the jit and its donate_argnums
    (carry + tile args), which is what keeps "one executable per
    (consumer, tile shape)" under the consumer's control. `first_tile`
    (carry, *tile_arrays) -> carry runs once on tile 0 BEFORE its step —
    e.g. the stats engine derives its Gram shift from the first tile
    there, on device, instead of a separate host pre-pass over the same
    rows."""
    from ..utils.metrics import collector

    traced = bool(collector.enabled)
    anchor = collector.trace.current() if traced else None
    depth = max(1, int(prefetch)) if prefetch else tile_prefetch_depth()
    stats = TilePlaneStats(tile_rows, label, prefetch=depth)
    # anchor handed over BEFORE any pipeline thread exists: a sharded
    # source's parse workers parent their tile_parse spans to the same
    # span the copy/compute spans use
    source.set_span_anchor(anchor)
    t_pass = time.perf_counter()
    multiproc = bool(shardings) and any(
        not getattr(s, "is_fully_addressable", True) for s in shardings)
    if not tileplane_enabled() or multiproc:
        # kill switch: the SAME pass, fully synchronous on the caller's
        # thread — no producer thread, no queue, no copy/compute overlap.
        # Multi-process shardings ALWAYS take this path: landing tile k+1
        # on the producer thread while the step's cross-process gloo
        # collectives run tile k corrupts the CPU client's heap on this
        # jaxlib — the pod pays serialized copy/compute for correctness.
        return _run_sync(source, step, carry0, tile_rows=tile_rows,
                         stats=stats, first_tile=first_tile, sink=sink,
                         shardings=shardings, traced=traced,
                         anchor=anchor, t_pass=t_pass)
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    # `depth` copy slots, each released when a tile is DEQUEUED: while
    # tile k computes, tiles k+1..k+depth may be copied ahead
    copy_slot = threading.Semaphore(depth)
    stop = threading.Event()
    th = threading.Thread(
        target=_producer, args=(source, tile_rows, q, copy_slot, stop,
                                stats, shardings, traced, anchor),
        name=f"tileplane-{label}", daemon=True)
    th.start()

    import jax

    carry = carry0
    consumer = _Consumer(step, first_tile, sink, stats, traced, anchor,
                         carry0)
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                break
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] == "__error__":
                raise item[1]
            dev, n_valid, k = item
            copy_slot.release()  # tile accepted: producer may copy k+1
            consumer.feed(dev, n_valid, k)
        consumer.flush()
    finally:
        stop.set()
        # drain so a producer blocked on put/acquire observes the flag
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        th.join(timeout=30.0)
    return consumer.carry, _finish_pass(stats, traced, t_pass)


class _Consumer:
    """Per-tile step/sink/span logic, shared verbatim by the threaded
    consumer loop and the kill-switch synchronous fallback."""

    def __init__(self, step, first_tile, sink, stats: TilePlaneStats,
                 traced: bool, anchor, carry0, multiproc: bool = False):
        self.step = step
        self.first_tile = first_tile
        self.sink = sink
        self.stats = stats
        self.traced = traced
        self.anchor = anchor
        self.carry = carry0
        self.multiproc = multiproc
        self._pending: Optional[Tuple[Any, int]] = None

    def feed(self, dev, n_valid: int, k: int) -> None:
        import jax

        from ..utils.metrics import collector
        t0 = time.perf_counter()
        if k == 0 and self.first_tile is not None:
            self.carry = self.first_tile(self.carry, *dev)
            # fence: the step below DONATES these tile buffers; the
            # first-tile hook must have consumed them first (once per
            # pass — not a per-tile sync)
            jax.block_until_ready(self.carry)
        out = self.step(self.carry, *dev)
        if self.sink is not None:
            self.carry, out_tile = out
            if self._pending is not None:
                prev, prev_n = self._pending
                self.sink(np.asarray(prev)[:prev_n], prev_n)
            self._pending = (out_tile, n_valid)
        else:
            self.carry = out
        if self.traced:
            jax.block_until_ready(self.carry)
            dur = time.perf_counter() - t0
            # consumer-owned field (caller's thread); the producer
            # never touches compute-side stats
            # tmoglint: disable=THR001  single-owner, read post-join
            self.stats.compute_seconds += dur
            collector.trace.add_complete(
                "tile_compute", "tile", dur, parent_span=self.anchor,
                tile=k, rows=int(n_valid), label=self.stats.label)
            if self.multiproc:
                # the step's cross-process psum merge is inside this
                # already-measured block window — attribute it to the
                # pod collective ledger without a second clock read
                from . import podtrace
                podtrace.note_collective(
                    "tile_merge", dur, tile=k, rows=int(n_valid),
                    label=self.stats.label)
        # tmoglint: disable=THR001  consumer-owned (see compute_seconds)
        self.stats.tiles += 1
        # tmoglint: disable=THR001  consumer-owned (see compute_seconds)
        self.stats.rows += int(n_valid)

    def flush(self) -> None:
        if self._pending is not None:
            prev, prev_n = self._pending
            self.sink(np.asarray(prev)[:prev_n], prev_n)
            self._pending = None


def _finish_pass(stats: TilePlaneStats, traced: bool,
                 t_pass: float) -> TilePlaneStats:
    from ..utils.metrics import collector

    # pass-end bookkeeping: runs on the consumer thread after the
    # producer joined (run_tileplane finally)
    # tmoglint: disable=THR001  single-owner, read post-join
    stats.wall_seconds = time.perf_counter() - t_pass
    if traced:
        # tmoglint: disable=THR001  same happens-after-join ownership
        stats.overlapped = stats.copy_seconds + stats.compute_seconds \
            > stats.wall_seconds * 1.001
        collector.event(
            "tileplane_pass", label=stats.label, tiles=stats.tiles,
            rows=stats.rows, tile_rows=stats.tile_rows,
            prefetch_depth=stats.prefetch_depth,
            peak_host_rows=stats.peak_host_rows,
            copy_seconds=round(stats.copy_seconds, 6),
            compute_seconds=round(stats.compute_seconds, 6),
            wall_seconds=round(stats.wall_seconds, 6))
    return stats


def _run_sync(source: RowSource, step, carry0, *, tile_rows: int,
              stats: TilePlaneStats, first_tile, sink, shardings,
              traced: bool, anchor, t_pass: float
              ) -> Tuple[Any, TilePlaneStats]:
    """TMOG_TILEPLANE=0 fallback: the identical pass on ONE thread —
    same tiles (shared assembly), same step/sink/span semantics, no
    background producer, no copy/compute overlap."""
    import jax

    from ..utils.metrics import collector
    multiproc = bool(shardings) and any(
        not getattr(s, "is_fully_addressable", True) for s in shardings)
    consumer = _Consumer(step, first_tile, sink, stats, traced, anchor,
                         carry0, multiproc=multiproc)
    for k, (tile, n_valid) in enumerate(
            iter_fixed_tiles(source, tile_rows, stats)):
        t0 = time.perf_counter()
        dev = _device_put_tile(tile, shardings)
        if traced:
            jax.block_until_ready(dev)
            dur = time.perf_counter() - t0
            stats.copy_seconds += dur
            collector.trace.add_complete(
                "tile_copy", "tile", dur, parent_span=anchor, tile=k,
                rows=int(n_valid), label=stats.label,
                bytes=int(sum(a.nbytes for a in tile)))
        consumer.feed(dev, n_valid, k)
    consumer.flush()
    return consumer.carry, _finish_pass(stats, traced, t_pass)


# -- generic pipelined producer/consumer (record-batch consumers) ------------

def pipelined(produce: Iterable[Any], *, label: str = "tileplane",
              depth: Optional[int] = None) -> Iterator[Any]:
    """Run `produce` (any host-side iterable — e.g. records -> fixed-size
    Dataset tiles for bulk scoring) on a background thread with a
    `depth`-deep queue, yielding its items on the caller's thread.

    The array pipeline above is for numeric tile math; this is the same
    prefetch ring for consumers whose 'tile' is a host object (the
    scoring path assembles a Dataset per record tile here while the
    device scores the previous one). Items are produced at most `depth`
    ahead (None resolves tile_prefetch_depth(); the hand default of 1
    is the old one-ahead double buffering)."""
    d = max(1, int(depth)) if depth else tile_prefetch_depth()
    q: "queue.Queue" = queue.Queue(maxsize=d)
    stop = threading.Event()

    def body():
        try:
            for item in produce:
                while not stop.is_set():
                    try:
                        q.put((None, item), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            q.put(_SENTINEL)
        except BaseException as e:
            q.put((e, None))

    th = threading.Thread(target=body, name=f"tileplane-{label}",
                          daemon=True)
    th.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            err, value = item
            if err is not None:
                raise err
            yield value
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        th.join(timeout=30.0)
