"""Tree-family predictors: decision tree, random forest, GBT, XGBoost-class.

Reference wrappers: core/.../impl/classification/{OpDecisionTreeClassifier,
OpRandomForestClassifier, OpGBTClassifier, OpXGBoostClassifier}.scala and
core/.../impl/regression/{OpDecisionTreeRegressor, OpRandomForestRegressor,
OpGBTRegressor, OpXGBoostRegressor}.scala. Param names mirror the Spark/
XGBoost params the reference grids over (DefaultSelectorParams.scala:35-56).

All training runs through ops/trees histogram kernels — quantile binning +
level-wise growth as one XLA program per ensemble (scan over trees/rounds).
The reference reached C++ (libxgboost via JNI + Rabit allreduce) for exactly
this workload; here the same histogram build is a segment-sum whose
cross-chip reduction is an XLA psum over ICI.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import trees as T
from ..stages.params import Param
from .base import PredictionModel, PredictorEstimator, stable_sigmoid


def _softmax_np(raw: np.ndarray) -> np.ndarray:
    m = raw.max(axis=1, keepdims=True)
    e = np.exp(raw - m)
    return e / e.sum(axis=1, keepdims=True)


class TreeEnsembleModel(PredictionModel):
    """Fitted tree ensemble. Serving traverses raw-value thresholds in numpy
    (the Spark-free local-scoring path); `feat`/`thresh_val`/`leaf` carry a
    leading [n_trees] axis (flattened rounds x classes for softmax boosting).

    mode: 'classify_mean'  — payload K=n_classes distributions, averaged
          'margin'         — payload K=1 logistic margins, summed + base
          'regress_mean'   — payload K=1 values, averaged
          'regress_sum'    — payload K=1 boosting steps, summed + base
          'softmax'        — n_trees = rounds*n_classes, per-class margin sum
    """

    def __init__(self, feat: np.ndarray, thresh_val: np.ndarray,
                 leaf: np.ndarray, depth: int, mode: str,
                 base: float = 0.0, n_classes: int = 2,
                 miss: Optional[np.ndarray] = None,
                 operation_name: str = "treeEnsemble",
                 uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.feat = np.asarray(feat, np.int32)
        self.thresh_val = np.asarray(thresh_val, np.float32)
        self.leaf = np.asarray(leaf, np.float32)
        # models saved before missing-direction learning default NaN left
        self.miss = (np.zeros_like(self.feat) if miss is None
                     else np.asarray(miss, np.int32))
        self.depth = int(depth)
        self.mode = mode
        self.base = float(base)
        self.n_classes = int(n_classes)

    def predict_arrays(self, X):
        X = np.asarray(X, np.float32)
        agg = T.np_predict_ensemble(self.feat, self.thresh_val, self.leaf,
                                    X, self.depth,
                                    miss=self.miss)         # [N, K] sums
        n_trees = self.feat.shape[0]
        if self.mode == "classify_mean":
            prob = agg / n_trees
            prob = np.clip(prob, 0.0, None)
            prob = prob / np.maximum(prob.sum(axis=1, keepdims=True), 1e-12)
            pred = prob.argmax(axis=1).astype(np.float32)
            return pred, agg, prob
        if self.mode == "margin":
            margin = agg[:, 0] + self.base
            p1 = stable_sigmoid(margin)
            prob = np.stack([1.0 - p1, p1], axis=1)
            raw = np.stack([-margin, margin], axis=1)
            return (p1 >= 0.5).astype(np.float32), raw, prob
        if self.mode == "regress_mean":
            return (agg[:, 0] / n_trees).astype(np.float32), None, None
        # regress_sum
        return (agg[:, 0] + self.base).astype(np.float32), None, None

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(feat=self.feat, thresh_val=self.thresh_val, leaf=self.leaf,
                 miss=self.miss, depth=self.depth, mode=self.mode,
                 base=self.base, n_classes=self.n_classes)
        return d


class SoftmaxEnsembleModel(PredictionModel):
    """Multiclass boosted ensemble: trees grouped [rounds, n_classes]."""

    def __init__(self, feat: np.ndarray, thresh_val: np.ndarray,
                 leaf: np.ndarray, depth: int, n_classes: int,
                 miss: Optional[np.ndarray] = None,
                 operation_name: str = "xgbSoftmax",
                 uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.feat = np.asarray(feat, np.int32)          # [R*C, I]
        self.thresh_val = np.asarray(thresh_val, np.float32)
        self.leaf = np.asarray(leaf, np.float32)        # [R*C, L, 1]
        self.miss = (np.zeros_like(self.feat) if miss is None
                     else np.asarray(miss, np.int32))
        self.depth = int(depth)
        self.n_classes = int(n_classes)

    def predict_arrays(self, X):
        X = np.asarray(X, np.float32)
        n = X.shape[0]
        C = self.n_classes
        margins = np.zeros((n, C), np.float32)
        for c in range(C):
            margins[:, c] = T.np_predict_ensemble(
                self.feat[c::C], self.thresh_val[c::C], self.leaf[c::C],
                X, self.depth, miss=self.miss[c::C])[:, 0]
        prob = _softmax_np(margins)
        pred = prob.argmax(axis=1).astype(np.float32)
        return pred, margins, prob

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(feat=self.feat, thresh_val=self.thresh_val, leaf=self.leaf,
                 miss=self.miss, depth=self.depth, n_classes=self.n_classes)
        return d


# -- estimator machinery ----------------------------------------------------

class _TreeEstimator(PredictorEstimator):
    """Shared: quantile-bin on device, grow, freeze raw-value thresholds."""

    supports_grid_vmap = False
    # validator fast path: folds enter as weight masks over one binned matrix
    # (Validator._validate_mask_folds) — no per-fold host slicing. Bin edges
    # then come from the full feature columns (labels never participate).
    supports_mask_folds = True

    def _bin(self, X, n_valid: int = None):
        """(binned matrix, edges, n_bins).

        Keeps X's dtype (bf16 sweeps stay bf16 — no full-size f32 copy;
        quantile_edges casts only its row sample). NaN gets the dedicated
        bin 0 and routes by each node's learned direction (Tree.miss) —
        never folded into the value bins. `n_valid`: number of REAL rows
        when the caller padded X to a mesh multiple
        (validators._device_arrays repeats the last row) — the quantile
        sketch uses only the real rows so mesh and meshless runs grow
        from IDENTICAL bin edges; padded rows still bin (real values,
        zero weight — inert in every histogram)."""
        n_bins = int(self.get_param("max_bins"))
        Xd = jnp.asarray(X)
        Xq = Xd if n_valid is None or n_valid >= Xd.shape[0] \
            else Xd[:n_valid]
        edges = T.quantile_edges(Xq, n_bins)
        Xb = T.bin_matrix(Xd, edges)
        return Xb, edges, n_bins

    # -- host (C++) route ---------------------------------------------------
    # On the CPU backend, tree fits go through native/trees.cpp: the XLA
    # kernels' dense 2^depth-node levels are the right shape for the MXU
    # but pure waste for deep trees at host scale (the reference's default
    # RF grid reaches maxDepth=12 -> 4096-node levels; measured 11.8s for
    # one warm 50-tree fit on 900 Titanic rows vs 0.04s native). Same
    # role as libxgboost's C++ behind the reference's OpXGBoost*.
    @staticmethod
    def _host_route() -> bool:
        # same truthiness convention as TMOG_NO_PALLAS (pallas_hist.py)
        if os.environ.get("TMOG_NO_HOST_TREES", "").strip().lower() \
                not in ("", "0", "false"):
            return False
        import jax as _jax
        if _jax.default_backend() != "cpu":
            return False
        from ..ops import trees_host as TH
        return TH.available()

    def _bin_host(self, X, n_valid: int = None):
        from ..ops import trees_host as TH
        n_bins = int(self.get_param("max_bins"))
        Xn = np.asarray(X, np.float32)
        Xq = Xn if n_valid is None or n_valid >= Xn.shape[0] \
            else Xn[:n_valid]
        edges = TH.quantile_edges_host(Xq, n_bins)
        return TH.bin_matrix_host(Xn, edges), edges, n_bins

    # -- mask-fold sweep protocol ------------------------------------------
    def mask_sweep_context(self, X, n_valid: int = None, mesh=None):
        """Binned context shared by every (grid, fold) fit — host-tagged
        when the native route is taken. A mesh run must stay on the
        device path even on the CPU backend (the virtual-device parity
        story: sharded and single-device sweeps go through the SAME
        kernels; the native builder's near-tie choices differ)."""
        if mesh is None and self._host_route():
            return ("host",) + self._bin_host(X, n_valid=n_valid)
        return self._bin(X, n_valid=n_valid)

    # Above this row count the fold axis stops being vmapped: XLA lays the
    # vmapped traversal's [folds, n] node-index arrays out fold-minor and
    # pads the fold axis to the 128-lane tile (5 -> 128 = 25.6x HBM; the
    # 10M-row bench config needed 20.9G and failed to compile). One fold of
    # 10M rows already saturates the MXU, so large-N folds run sequentially
    # through the SAME cached per-fold executable.
    _VMAP_FOLD_MAX_ROWS = 2_000_000
    # the fold-vmapped branch must never reach the pallas histogram path
    # (pallas_call does not sit under a batch axis here) — enforced against
    # the kernel-selection threshold, and not via `assert` (stripped by -O)
    if _VMAP_FOLD_MAX_ROWS >= T._PALLAS_MIN_ROWS:
        raise RuntimeError(
            "_VMAP_FOLD_MAX_ROWS must stay below ops.trees._PALLAS_MIN_ROWS")

    def mask_fit_scores(self, ctx, y, w, masks, n_classes: int = 2,
                        multiclass: bool = False):
        """[F, n] margins (binary/regression) or [F, n, c] class scores:
        one fit+predict per fold per grid point, entirely on device against
        the shared binned matrix. `multiclass` (the validator's problem
        type, NOT n_classes — a multiclass sweep over 2-class data must
        still return [F, n, c]) picks the score shape. Folds are vmapped
        below _VMAP_FOLD_MAX_ROWS and loop over one compiled program above
        it (see the constant's rationale). A host-tagged context (CPU
        backend + native builder) runs the per-fold loop in C++ instead."""
        if isinstance(ctx, tuple) and len(ctx) == 4 and ctx[0] == "host":
            host_ctx = ctx[1:]
            yn = np.asarray(y, np.float32)
            wn = np.asarray(w, np.float32)
            mn = np.asarray(masks, np.float32)
            return np.stack([
                self._mask_score_host(host_ctx, yn, wn * mn[f], n_classes,
                                      multiclass)
                for f in range(mn.shape[0])])
        fused = self._mask_scores_fused(ctx, y, w, masks, n_classes,
                                        multiclass)
        if fused is not None:
            return fused

        def one(m):
            return self._mask_score(ctx, y, w * m, n_classes, multiclass)
        if y.shape[0] <= self._VMAP_FOLD_MAX_ROWS:
            return jax.vmap(one)(masks)
        return jnp.stack([one(masks[f]) for f in range(masks.shape[0])])

    def _mask_scores_fused(self, ctx, y, w, masks, n_classes, multiclass):
        """All-folds-in-one-program fast path; None = not applicable
        (family hook — the GBT/XGB boosters implement it)."""
        return None

    # -- config-fused sweep (grid points batched into the fold axis) ------
    #: fit_gbt_folds args that may vary PER LANE (pure algebra scalars);
    #: every other kw must match across a fused group
    _LANE_KEYS = ("learning_rate", "reg_lambda", "min_child_weight",
                  "gamma")
    _LANE_DEFAULTS = {"learning_rate": 0.1, "reg_lambda": 1.0,
                      "min_child_weight": 0.0, "gamma": 0.0}

    def _sweep_kw(self):
        """The kw dict this family passes to fit_gbt_folds (hook)."""
        return None

    def grid_fuse_signature(self, grid):
        """Hashable structural signature: grid points with EQUAL
        signatures fit in one fold-fused device program (they differ only
        in per-lane algebra scalars). None = this grid point cannot
        fuse. Used by the validator to batch the sweep."""
        est_g = self.copy(**grid)
        kw = est_g._sweep_kw()
        if kw is None:
            return None
        items = tuple(sorted(
            (k, v) for k, v in kw.items() if k not in self._LANE_KEYS))
        # seed from the GRID-APPLIED copy: a swept seed must split the
        # group (one key drives the shared subsample/colsample draws)
        return items + (("loss", getattr(self, "_loss", "logistic")),
                        ("seed", int(est_g.get_param("seed"))
                         if est_g.has_param("seed") else 0))

    def mask_fit_scores_grid(self, ctx, y, w, masks, grids,
                             n_classes: int = 2, multiclass: bool = False,
                             mesh=None):
        """[G, F, n] margins for a GROUP of same-signature grid points in
        as few device programs as fit VMEM/HBM, or None (validator falls
        back to per-config mask_fit_scores). The lanes axis is
        (config, fold) pairs over the SHARED binned matrix: one histogram
        one-hot pass serves every config and fold, and the contraction M
        dim grows from folds*3 toward the MXU's 128 rows (the measured
        headroom in docs/performance.md's roofline table).

        `mesh` (the validator's sweep mesh, None off-mesh): when the
        batch axis spans >1 devices the group runs through
        T.fit_gbt_folds_sharded — rows shard over the mesh, per-level
        histograms psum-merge (DrJAX MapReduce shape), split algebra and
        trees replicate — instead of the old unconditional fallback to
        the sequential per-fold path. Gated by _sharded_route_ok
        (TMOG_TREE_SHARD kill switch, subsample == 1.0)."""
        if isinstance(ctx, tuple) and len(ctx) == 4 and ctx[0] == "host":
            return None   # host-tagged sweep: the C++ builder path
        regression = (getattr(self, "_regression", False)
                      or getattr(self, "_loss", "logistic") == "squared")
        if multiclass and not regression:
            return None
        if len(grids) < 2:
            return None
        kws = [self.copy(**g)._sweep_kw() for g in grids]
        if any(k is None for k in kws):
            return None
        sigs = {self.grid_fuse_signature(g) for g in grids}
        if len(sigs) != 1 or None in sigs:
            return None
        depth = kws[0]["depth"]
        from ..parallel.mesh import mesh_batch_count
        n_shards = mesh_batch_count(mesh)
        if n_shards > 1:
            if not self._sharded_route_ok(kws[0]):
                return None
        elif not self._fused_route_ok(ctx, y, masks, depth):
            return None
        from ..ops import pallas_hist
        Xb, edges, n_bins = ctx
        F = masks.shape[0]
        n = y.shape[0]
        G = len(grids)
        # chunk size from the single planner (ops/pallas_hist
        # plan_lane_chunk): the fused kernel's VMEM residents scale with
        # lane count, HBM carries 4 lane-sized f32 planes (W, g, h,
        # margins), and Mosaic's layout search explodes when the out
        # block nears the scoped-VMEM boundary (r5 session 2: 20+ min
        # compiles at a 16MB out block) — the planner gates all three,
        # INCLUDING at chunk == 1 (a single config's fold lanes that
        # clear the VMEM gate can still bust the HBM/out-block caps;
        # ADVICE round 5), where 0 falls back per-config. On a mesh the
        # lane row-planes shard, so the HBM lane budget scales with the
        # shard count (the planner's lane-shard budget).
        chunk = pallas_hist.plan_lane_chunk(
            Xb.shape[1], n_bins + 1, F, G, depth, n_shards=n_shards)
        if chunk == 0:
            return None

        sharded = n_shards > 1
        self._last_grid_route = "grid_fused_sharded" if sharded \
            else "grid_fused"
        label = "tree_sweep_grid_fused_sharded" if sharded \
            else "tree_sweep_grid_fused"
        self._plan_growth_form()
        span = "tree_shard_merge" if sharded else (
            "tree_level_scan" if T.tree_scan_enabled() else None)
        loss = "squared" if regression else "logistic"
        outs = []
        for lo in range(0, G, chunk):
            sub = kws[lo:lo + chunk]
            g_here = len(sub)
            # per-config w (scale_pos_weight may vary across the grid)
            Ws = []
            for gi in range(lo, lo + g_here):
                est_g = self.copy(**grids[gi])
                w_g = est_g._apply_spw(y, w, n_classes, multiclass) \
                    if hasattr(est_g, "_apply_spw") else w
                Ws.append(masks * w_g[None, :])
            # FOLD-MAJOR lanes (fold slow, config fast): all configs of a
            # fold sit adjacent in the batched kernel's lane axis, and
            # the 5 folds share one residency of the binned matrix per
            # program — lane = f * g_here + config
            W_lanes = jnp.stack(Ws, axis=0).transpose(1, 0, 2) \
                .reshape(g_here * F, n)                    # [F*g, n]
            lane_vec = {
                key: jnp.tile(jnp.asarray(
                    [float(k.get(key, self._LANE_DEFAULTS[key]))
                     for k in sub], jnp.float32), F)
                for key in self._LANE_KEYS}
            shared = {k: v for k, v in sub[0].items()
                      if k not in self._LANE_KEYS}
            # the signature pins one seed per group; honor the grid's
            key = self.copy(**grids[lo])._key()
            if sharded:
                def fit(W_lanes=W_lanes, key=key, shared=shared,
                        lane_vec=lane_vec):
                    return T.fit_gbt_folds_sharded(
                        Xb, y, W_lanes, key, mesh=mesh, n_bins=n_bins,
                        loss=loss, **shared, **lane_vec)
            else:
                def fit(W_lanes=W_lanes, key=key, shared=shared,
                        lane_vec=lane_vec):
                    return T.fit_gbt_folds(
                        Xb, y, W_lanes, key, n_bins=n_bins, loss=loss,
                        **shared, **lane_vec)
            _, _, margins = self._timed_fused_fit(
                label, Xb, g_here * F, depth, shared["n_rounds"], fit,
                span=span)
            outs.append(margins.reshape(F, g_here, n).transpose(1, 0, 2))
        return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]

    # (backend, label, shape signature) tuples whose fused program has
    # already run once this process — the first run's wall includes jit
    # trace + Mosaic compile (documented 20+ min at sweep shapes), so its
    # span is marked cold and readers must compare warm spans only. Keyed
    # by backend: after force_cpu re-scopes the platform, a shape warmed
    # on one backend must NOT be misclassified warm on the other (a fresh
    # backend means fresh executables, and a mislabeled cold span's
    # compile wall would pollute warm-span GB/s claims).
    _WARM_FUSED_SHAPES: set = set()

    @staticmethod
    def _plan_growth_form() -> None:
        """Plan-time scan-vs-unrolled choice for the fused fits
        (docs/planning.md): consult the measured cost model and apply
        it through ops/trees.set_tree_scan BEFORE the span label and
        jit-cache signature are read. planned_tree_scan returns None —
        the current form stays untouched, no cache clear, no behavior
        change — unless the corpus MEASURED a preference; and even
        then, a lever someone ELSE flipped stays flipped: an
        explicitly-set TMOG_TREE_SCAN and a programmatic set_tree_scan
        call (the documented runtime A/B lever) are both hand settings
        and beat the model. The guard: the planner only moves the form
        when it currently sits where the planner (or the hand default)
        left it. Any planner fault leaves the form alone."""
        try:
            from ..planner.plan import planned_tree_scan
            want = planned_tree_scan()
        except Exception:
            return
        if want is None:
            return
        cur = T.tree_scan_enabled()
        baseline = _TreeEstimator._plan_scan_applied
        if baseline is None:
            baseline = True  # ops/trees' hand default (scan on); an
            #                  env-set TMOG_TREE_SCAN returned None above
        if cur != baseline:
            return  # hand-flipped at runtime: hand beats model
        if want != cur:
            T.set_tree_scan(want)
        _TreeEstimator._plan_scan_applied = want

    #: the last growth form the PLANNER applied (None = never) — the
    #: hands-off guard above compares the live lever against this
    _plan_scan_applied = None

    @staticmethod
    def _timed_fused_fit(label, Xb, lanes, depth, n_rounds, call,
                         span=None):
        """Run one fused-sweep fit; when stage metrics are being
        collected, time it to completion and record a kernel-roofline
        span (analytic HBM bytes from the single traffic model in
        ops/pallas_hist) so BENCH_*.json can report achieved GB/s and
        %-of-roof without a hand-run roofline script. The first span per
        (backend, label, shape) carries cold=True: its wall contains the
        compile, not just the kernel, and would wildly understate
        achieved GB/s. `span` ("tree_level_scan" / "tree_shard_merge")
        additionally wraps the fit in a named trace span so a Perfetto
        view shows which growth/merge form ran and the RecompileTracker
        books the fit's compiles to it (docs/observability.md)."""
        from ..utils.metrics import collector
        if not collector.enabled:
            return call()
        import contextlib
        import time
        from ..ops import pallas_hist
        # keyed by backend AND growth form: a set_tree_scan flip clears
        # the jit caches (the executables differ), so the other form's
        # first fit recompiles and must be classified cold again
        sig = (jax.default_backend(), T.tree_scan_enabled(), label,
               Xb.shape, str(Xb.dtype), lanes, depth, n_rounds)
        cold = sig not in _TreeEstimator._WARM_FUSED_SHAPES
        cm = collector.trace_span(span, kind="tree_fused",
                                  lanes=int(lanes), depth=int(depth)) \
            if span else contextlib.nullcontext()
        t0 = time.perf_counter()
        with cm:
            out = call()
            jax.block_until_ready(out)
        collector.kernel(
            label, time.perf_counter() - t0,
            pallas_hist.fused_fit_bytes(
                Xb.shape[0], Xb.shape[1], lanes, depth, n_rounds,
                xb_itemsize=Xb.dtype.itemsize),
            cold=cold,
            # shape attrs ride into the kernel span of the trace export,
            # so a Perfetto view names the program's sweep geometry
            attrs=dict(lanes=int(lanes), depth=int(depth),
                       n_rounds=int(n_rounds), n_rows=int(Xb.shape[0])))
        _TreeEstimator._WARM_FUSED_SHAPES.add(sig)
        return out

    def _sharded_route_ok(self, kw) -> bool:
        """Gate for the mesh-sharded fused sweep (mask_fit_scores_grid
        with a >1-device batch axis). TMOG_TREE_SHARD=0 is the kill
        switch; row subsample must stay 1.0 (per-shard uniform draws are
        index-local — every shard would draw the same bits for its local
        rows, matching neither the single-device mask nor independence).
        Unlike _fused_route_ok there is no TPU/pallas requirement: on
        CPU meshes the jnp twin dispatchers run the identical call
        shape, which is what makes the route parity-testable in CI."""
        if os.environ.get("TMOG_TREE_SHARD", "").strip().lower() \
                in ("0", "false", "off"):
            return False
        return float(kw.get("subsample", 1.0)) >= 1.0

    def _fused_route_ok(self, ctx, y, masks=None, depth=None):
        """Shared gate for the fold-fused booster path: live pallas on a
        single-device TPU above the fold-vmap row limit. Mesh-sharded
        contexts keep the per-fold path HERE (single-config fits);
        the GRID sweep has its own mesh route — mask_fit_scores_grid
        dispatches to fit_gbt_folds_sharded under _sharded_route_ok.
        When the caller supplies the sweep shape (masks + tree depth),
        the fused kernel's VMEM footprint is checked too — its output
        block scales with folds x slots x F x bins, and an over-budget
        shape is a Mosaic compile failure, so those fall back to the
        sequential per-fold path."""
        from ..ops import pallas_hist
        Xb, _, n_bins = ctx
        if (jax.default_backend() != "tpu"
                or not pallas_hist.available()
                or y.shape[0] <= self._VMAP_FOLD_MAX_ROWS):
            return False
        try:
            if len(Xb.sharding.device_set) > 1:
                return False
        except AttributeError:
            pass
        if masks is not None and depth is not None:
            # fit_gbt_folds histograms with B = n_bins + 1 slots per bin axis
            if not pallas_hist.fused_hist_fits(
                    Xb.shape[1], n_bins + 1, masks.shape[0], depth):
                return False
        return True

    def _mask_score(self, ctx, y, w, n_classes, multiclass):
        raise NotImplementedError

    def _mask_score_host(self, ctx, y, w, n_classes, multiclass):
        raise NotImplementedError

    def _host_fallback(self, ctx, y, w, n_classes, multiclass):
        """Device-path retry for _mask_score_host when the native library
        vanishes mid-flight (shared by every family)."""
        Xb, edges, n_bins = ctx
        return np.asarray(self._mask_score(
            (jnp.asarray(Xb), jnp.asarray(edges), n_bins),
            jnp.asarray(y), jnp.asarray(w), n_classes, multiclass))

    def _freeze(self, trees: T.Tree, edges) -> Dict[str, np.ndarray]:
        feat = np.asarray(trees.feat)
        thresh = np.asarray(trees.thresh)
        tv = np.asarray(T.thresholds_to_values(
            jnp.asarray(feat), jnp.asarray(thresh), edges))
        leaf = np.asarray(trees.leaf)
        miss = np.asarray(trees.miss)
        # stack any leading (rounds, classes) axes into one tree axis
        feat = feat.reshape(-1, feat.shape[-1])
        tv = tv.reshape(-1, tv.shape[-1])
        leaf = leaf.reshape(-1, leaf.shape[-2], leaf.shape[-1])
        miss = miss.reshape(-1, miss.shape[-1])
        return dict(feat=feat, thresh_val=tv, leaf=leaf, miss=miss)

    def _key(self):
        return jax.random.PRNGKey(int(self.get_param("seed")))

    def _w(self, y, w):
        return (np.ones_like(y, np.float32) if w is None
                else np.asarray(w, np.float32))


def _feature_frac(strategy: str, n_feat: int, classification: bool) -> float:
    """Spark featureSubsetStrategy -> fraction (RandomForest.scala defaults)."""
    if strategy == "all":
        return 1.0
    if strategy == "auto":
        return (np.sqrt(n_feat) / n_feat) if classification else (1.0 / 3.0)
    if strategy == "sqrt":
        return np.sqrt(n_feat) / n_feat
    if strategy == "log2":
        return max(np.log2(max(n_feat, 2)) / n_feat, 1.0 / n_feat)
    if strategy == "onethird":
        return 1.0 / 3.0
    try:
        return float(strategy)
    except ValueError:
        return 1.0


class _ForestBase(_TreeEstimator):
    classification = True

    def _forest_cfg(self, n_feat: int) -> Dict[str, Any]:
        return dict(
            n_trees=int(self.get_param("num_trees")),
            subsample=float(self.get_param("subsampling_rate")),
            feature_frac=float(_feature_frac(
                str(self.get_param("feature_subset_strategy")), n_feat,
                self.classification)),
            bootstrap=True)

    def _mask_score(self, ctx, y, w, n_classes, multiclass):
        Xb, edges, n_bins = ctx
        cfg = self._forest_cfg(Xb.shape[1])
        depth = int(self.get_param("max_depth"))
        if self.classification:
            G = jax.nn.one_hot(y.astype(jnp.int32), n_classes,
                               dtype=jnp.float32) * w[:, None]
        else:
            G = (y * w)[:, None]
        trees = T.fit_forest(
            Xb, G, w, self._key(), depth=depth, n_bins=n_bins,
            min_instances=float(self.get_param("min_instances_per_node")),
            min_info_gain=float(self.get_param("min_info_gain")),
            leaf_mode="mean", **cfg)
        agg = T.predict_forest_bins(trees, Xb, depth)  # [n, K]
        if not self.classification:
            return agg[:, 0] / cfg["n_trees"]
        prob = jnp.clip(agg / cfg["n_trees"], 0.0, None)
        prob = prob / jnp.maximum(prob.sum(axis=1, keepdims=True), 1e-12)
        if multiclass:
            return prob  # [n, c] class scores (argmax = predicted class)
        p1 = jnp.clip(prob[:, 1], 1e-7, 1.0 - 1e-7)
        return jnp.log(p1 / (1.0 - p1))  # margin for the binary metrics

    def _mask_score_host(self, ctx, y, w, n_classes, multiclass):
        """Numpy/native twin of _mask_score (CPU sweeps)."""
        from ..ops import trees_host as TH
        Xb, edges, n_bins = ctx
        cfg = self._forest_cfg(Xb.shape[1])
        depth = int(self.get_param("max_depth"))
        if self.classification:
            G = np.eye(n_classes, dtype=np.float32)[y.astype(int)] \
                * w[:, None]
        else:
            G = (y * w)[:, None]
        trees = TH.fit_forest_host(
            Xb, G, w, n_trees=cfg["n_trees"], depth=depth, n_bins=n_bins,
            subsample=cfg["subsample"], feature_frac=cfg["feature_frac"],
            min_instances=float(self.get_param("min_instances_per_node")),
            min_info_gain=float(self.get_param("min_info_gain")),
            bootstrap=cfg["bootstrap"], seed=int(self.get_param("seed")))
        if trees is None:  # library vanished mid-flight: device fallback
            return self._host_fallback(ctx, y, w, n_classes, multiclass)
        agg = TH.predict_bins_host(trees, Xb, depth)
        if not self.classification:
            return agg[:, 0] / cfg["n_trees"]
        prob = np.clip(agg / cfg["n_trees"], 0.0, None)
        prob = prob / np.maximum(prob.sum(axis=1, keepdims=True), 1e-12)
        if multiclass:
            return prob
        p1 = np.clip(prob[:, 1], 1e-7, 1.0 - 1e-7)
        return np.log(p1 / (1.0 - p1))

    @classmethod
    def _declare_params(cls):
        return [
            Param("num_trees", "ensemble size", 50),
            Param("max_depth", "tree depth", 5),
            Param("max_bins", "histogram bins", 32),
            Param("min_instances_per_node", "min rows per child", 1),
            Param("min_info_gain", "min impurity decrease", 0.0),
            Param("subsampling_rate", "bootstrap rate", 1.0),
            Param("feature_subset_strategy", "auto|all|sqrt|log2|onethird",
                  "auto"),
            Param("impurity", "gini|entropy|variance (variance-equivalent "
                  "gain used)", "gini"),
            Param("seed", "rng seed", 42),
        ]

    def _fit_forest(self, X, y, w, G, leaf_mode):
        frac = _feature_frac(str(self.get_param("feature_subset_strategy")),
                             X.shape[1], self.classification)
        if self._host_route():
            from ..ops import trees_host as TH
            Xb, edges, n_bins = self._bin_host(X)
            trees = TH.fit_forest_host(
                Xb, np.asarray(G, np.float32), np.asarray(w, np.float32),
                n_trees=int(self.get_param("num_trees")),
                depth=int(self.get_param("max_depth")), n_bins=n_bins,
                subsample=float(self.get_param("subsampling_rate")),
                feature_frac=float(frac),
                min_instances=float(self.get_param("min_instances_per_node")),
                min_info_gain=float(self.get_param("min_info_gain")),
                bootstrap=True, seed=int(self.get_param("seed")))
            if trees is not None:
                return self._freeze(trees, jnp.asarray(edges))
        Xb, edges, n_bins = self._bin(X)
        trees = T.fit_forest(
            Xb, jnp.asarray(G), jnp.asarray(w), self._key(),
            n_trees=int(self.get_param("num_trees")),
            depth=int(self.get_param("max_depth")), n_bins=n_bins,
            subsample=float(self.get_param("subsampling_rate")),
            feature_frac=float(frac),
            min_instances=float(self.get_param("min_instances_per_node")),
            min_info_gain=float(self.get_param("min_info_gain")),
            leaf_mode=leaf_mode)
        return self._freeze(trees, edges)


class OpRandomForestClassifier(_ForestBase):
    """Reference OpRandomForestClassifier (impl/classification/, 159 LoC)."""

    problem_types = ("binary", "multiclass")
    classification = True

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("randomForestClassifier", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        w = self._w(y, w)
        n_classes = max(int(np.max(y)) + 1 if y.size else 2, 2)
        G = np.eye(n_classes, dtype=np.float32)[y.astype(int)] * w[:, None]
        frozen = self._fit_forest(X, y, w, G, leaf_mode="mean")
        return TreeEnsembleModel(depth=int(self.get_param("max_depth")),
                                 mode="classify_mean", n_classes=n_classes,
                                 operation_name=self.operation_name, **frozen)


class OpRandomForestRegressor(_ForestBase):
    """Reference OpRandomForestRegressor (impl/regression/, 133 LoC)."""

    problem_types = ("regression",)
    classification = False
    produces_probabilities = False

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("randomForestRegressor", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        w = self._w(y, w)
        G = (np.asarray(y, np.float32) * w)[:, None]
        frozen = self._fit_forest(X, y, w, G, leaf_mode="mean")
        return TreeEnsembleModel(depth=int(self.get_param("max_depth")),
                                 mode="regress_mean",
                                 operation_name=self.operation_name, **frozen)


def _single_tree_params():
    return [p for p in _ForestBase._declare_params()
            if p.name not in ("num_trees", "subsampling_rate",
                              "feature_subset_strategy")]


class OpDecisionTreeClassifier(OpRandomForestClassifier):
    """Reference OpDecisionTreeClassifier (120 LoC): single tree, all
    features, no bagging."""

    def _forest_cfg(self, n_feat: int) -> Dict[str, Any]:
        return dict(n_trees=1, subsample=1.0, feature_frac=1.0,
                    bootstrap=False)

    @classmethod
    def _declare_params(cls):
        return _single_tree_params()

    def __init__(self, uid: Optional[str] = None, **params):
        PredictorEstimator.__init__(self, "decisionTreeClassifier", uid=uid,
                                    **params)

    def _fit_forest(self, X, y, w, G, leaf_mode):
        if self._host_route():
            from ..ops import trees_host as TH
            Xb, edges, n_bins = self._bin_host(X)
            trees = TH.fit_forest_host(
                Xb, np.asarray(G, np.float32), np.asarray(w, np.float32),
                n_trees=1, depth=int(self.get_param("max_depth")),
                n_bins=n_bins, subsample=1.0, feature_frac=1.0,
                bootstrap=False,
                min_instances=float(self.get_param("min_instances_per_node")),
                min_info_gain=float(self.get_param("min_info_gain")),
                seed=int(self.get_param("seed")))
            if trees is not None:
                return self._freeze(trees, jnp.asarray(edges))
        Xb, edges, n_bins = self._bin(X)
        trees = T.fit_forest(
            Xb, jnp.asarray(G), jnp.asarray(w), self._key(),
            n_trees=1, depth=int(self.get_param("max_depth")), n_bins=n_bins,
            subsample=1.0, feature_frac=1.0, bootstrap=False,
            min_instances=float(self.get_param("min_instances_per_node")),
            min_info_gain=float(self.get_param("min_info_gain")),
            leaf_mode=leaf_mode)
        return self._freeze(trees, edges)


class OpDecisionTreeRegressor(OpRandomForestRegressor):
    """Reference OpDecisionTreeRegressor (119 LoC)."""

    _fit_forest = OpDecisionTreeClassifier._fit_forest
    _forest_cfg = OpDecisionTreeClassifier._forest_cfg

    @classmethod
    def _declare_params(cls):
        return _single_tree_params()

    def __init__(self, uid: Optional[str] = None, **params):
        PredictorEstimator.__init__(self, "decisionTreeRegressor", uid=uid,
                                    **params)


class _GBTBase(_TreeEstimator):
    @classmethod
    def _declare_params(cls):
        return [
            Param("max_iter", "boosting rounds", 20),
            Param("max_depth", "tree depth", 5),
            Param("max_bins", "histogram bins", 32),
            Param("step_size", "learning rate", 0.1),
            Param("min_instances_per_node", "min rows per child", 1),
            Param("min_info_gain", "min gain to split", 0.0),
            Param("subsampling_rate", "row subsample per round", 1.0),
            Param("seed", "rng seed", 42),
        ]

    _loss = "logistic"  # subclass override; used by the mask-fold sweep

    def _gbt_kw(self):
        return dict(
            n_rounds=int(self.get_param("max_iter")),
            depth=int(self.get_param("max_depth")),
            learning_rate=float(self.get_param("step_size")),
            min_instances=float(self.get_param("min_instances_per_node")),
            min_info_gain=float(self.get_param("min_info_gain")),
            subsample=float(self.get_param("subsampling_rate")))

    _sweep_kw = _gbt_kw  # config-fused sweep hook

    def _fit_gbt(self, X, y, w, loss):
        kw = self._gbt_kw()
        if self._host_route():
            from ..ops import trees_host as TH
            Xb, edges, n_bins = self._bin_host(X)
            out = TH.fit_gbt_host(Xb, np.asarray(y, np.float32),
                                  np.asarray(w, np.float32), n_bins=n_bins,
                                  seed=int(self.get_param("seed")),
                                  loss=loss, **kw)
            if out is not None:
                trees, base = out
                return self._freeze(trees, jnp.asarray(edges)), float(base)
        Xb, edges, n_bins = self._bin(X)
        trees, base = T.fit_gbt(
            Xb, jnp.asarray(y, jnp.float32), jnp.asarray(w), self._key(),
            n_bins=n_bins, loss=loss, **kw)
        return self._freeze(trees, edges), float(base)

    def _mask_score(self, ctx, y, w, n_classes, multiclass):
        Xb, edges, n_bins = ctx
        kw = self._gbt_kw()
        trees, base = T.fit_gbt(Xb, y, w, self._key(), n_bins=n_bins,
                                loss=self._loss, **kw)
        return base + T.predict_forest_bins(trees, Xb, kw["depth"])[:, 0]

    def _mask_scores_fused(self, ctx, y, w, masks, n_classes, multiclass):
        kw = self._gbt_kw()
        if not self._fused_route_ok(ctx, y, masks, kw["depth"]):
            return None
        Xb, edges, n_bins = ctx
        self._plan_growth_form()
        _, _, margins = self._timed_fused_fit(
            "tree_sweep_fold_fused", Xb, masks.shape[0], kw["depth"],
            kw["n_rounds"],
            lambda: T.fit_gbt_folds(
                Xb, y, masks * w[None, :], self._key(), n_bins=n_bins,
                loss=self._loss, **kw),
            span="tree_level_scan" if T.tree_scan_enabled() else None)
        return margins

    def _mask_score_host(self, ctx, y, w, n_classes, multiclass):
        from ..ops import trees_host as TH
        Xb, edges, n_bins = ctx
        kw = self._gbt_kw()
        out = TH.fit_gbt_host(Xb, y, w, n_bins=n_bins,
                              seed=int(self.get_param("seed")),
                              loss=self._loss, **kw)
        if out is None:
            return self._host_fallback(ctx, y, w, n_classes, multiclass)
        trees, base = out
        return base + TH.predict_bins_host(trees, Xb, kw["depth"])[:, 0]


class OpGBTClassifier(_GBTBase):
    """Reference OpGBTClassifier (147 LoC). Binary only — matching Spark's
    GBTClassifier; multiclass boosting lives in OpXGBoostClassifier."""

    problem_types = ("binary",)

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("gbtClassifier", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        frozen, base = self._fit_gbt(X, y, self._w(y, w), loss="logistic")
        return TreeEnsembleModel(depth=int(self.get_param("max_depth")),
                                 mode="margin", base=base,
                                 operation_name=self.operation_name, **frozen)


class OpGBTRegressor(_GBTBase):
    """Reference OpGBTRegressor (145 LoC)."""

    problem_types = ("regression",)
    produces_probabilities = False
    _loss = "squared"

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("gbtRegressor", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        frozen, base = self._fit_gbt(X, y, self._w(y, w), loss="squared")
        return TreeEnsembleModel(depth=int(self.get_param("max_depth")),
                                 mode="regress_sum", base=base,
                                 operation_name=self.operation_name, **frozen)


class _XGBBase(_TreeEstimator):
    @classmethod
    def _declare_params(cls):
        # the real-ML tail of the reference's 41 setters
        # (OpXGBoostClassifier.scala): alpha/scale_pos_weight/
        # max_delta_step/colsample_bylevel/base_score change fitted
        # models; the remaining setters are JNI/tracker plumbing with no
        # TPU referent
        return [
            Param("num_round", "boosting rounds", 100),
            Param("eta", "learning rate", 0.3),
            Param("max_depth", "tree depth", 6),
            Param("max_bins", "histogram bins", 256),
            Param("min_child_weight", "min hessian per child", 1.0),
            Param("reg_lambda", "L2 on leaves", 1.0),
            Param("alpha", "L1 on leaf weights (soft-threshold)", 0.0),
            Param("gamma", "complexity penalty per split", 0.0),
            Param("subsample", "row subsample per round", 1.0),
            Param("colsample_bytree", "feature subsample per tree", 1.0),
            Param("colsample_bylevel", "feature subsample per level", 1.0),
            Param("scale_pos_weight", "positive-class weight multiplier "
                  "(binary; xgboost imbalance control)", 1.0),
            Param("max_delta_step", "cap on each leaf's raw newton step "
                  "(imbalanced-logistic stabilizer)", 0.0),
            Param("base_score", "initial prediction (None = weighted "
                  "label mean, a better-calibrated prior than xgboost's "
                  "fixed 0.5)", None),
            Param("seed", "rng seed", 42),
        ]

    def _common(self):
        base_score = self.get_param("base_score")
        return dict(
            n_rounds=int(self.get_param("num_round")),
            depth=int(self.get_param("max_depth")),
            learning_rate=float(self.get_param("eta")),
            reg_lambda=float(self.get_param("reg_lambda")),
            min_child_weight=float(self.get_param("min_child_weight")),
            gamma=float(self.get_param("gamma")),
            subsample=float(self.get_param("subsample")),
            feature_frac=float(self.get_param("colsample_bytree")),
            alpha=float(self.get_param("alpha")),
            max_delta_step=float(self.get_param("max_delta_step")),
            colsample_bylevel=float(self.get_param("colsample_bylevel")),
            base_score=None if base_score is None else float(base_score))

    _sweep_kw = _common  # config-fused sweep hook

    _HOST_UNSUPPORTED = ("alpha", "max_delta_step", "colsample_bylevel",
                         "base_score")

    def _split_host_kw(self, kw):
        """(host-safe kw, True if the host/native builder can run them).

        The C++ builder implements the core surface; the round-5 tail
        lives in the XLA/pallas kernels only — non-default values force
        the device route rather than silently ignoring the params."""
        host_kw = {k: v for k, v in kw.items()
                   if k not in self._HOST_UNSUPPORTED}
        ok = (kw.get("alpha", 0.0) == 0.0
              and kw.get("max_delta_step", 0.0) == 0.0
              and kw.get("colsample_bylevel", 1.0) == 1.0
              and kw.get("base_score") is None)
        return host_kw, ok

    def _apply_spw(self, y, w, n_classes=2, multiclass=False):
        """scale_pos_weight: multiply positive-class weights — for the
        logistic objective this is exactly xgboost's g/h scaling of
        positive instances, and it reaches every route (device, fused,
        native host) because all take row weights."""
        spw = float(self.get_param("scale_pos_weight"))
        if spw == 1.0 or self._regression or multiclass or n_classes > 2:
            return w
        if isinstance(w, np.ndarray):
            yn = np.asarray(y)
            return (w * np.where(yn == 1, spw, 1.0)).astype(np.float32)
        return w * jnp.where(y == 1, spw, 1.0).astype(jnp.float32)

    def _check_multiclass_params(self, multiclass_fit: bool) -> None:
        if multiclass_fit and self.get_param("base_score") is not None:
            # softmax boosting has no scalar prior slot; dropping the
            # param silently would break the never-ignore contract
            raise ValueError(
                "base_score is only supported for binary/regression "
                "xgboost fits (softmax margins start at 0, matching "
                "xgboost multi:softprob)")

    def mask_fit_scores(self, ctx, y, w, masks, n_classes: int = 2,
                        multiclass: bool = False):
        self._check_multiclass_params(multiclass and not self._regression)
        w = self._apply_spw(y, w, n_classes, multiclass)
        if isinstance(ctx, tuple) and len(ctx) == 4 and ctx[0] == "host":
            _, host_ok = self._split_host_kw(self._common())
            if not host_ok:
                # round-5 tail params live in the XLA kernels only; untag
                # the context ONCE so the sweep converts the binned
                # matrix a single time instead of per (grid point, fold)
                import jax.numpy as jnp
                Xb, edges, n_bins = ctx[1:]
                ctx = (jnp.asarray(Xb), jnp.asarray(edges), n_bins)
        return super().mask_fit_scores(ctx, y, w, masks, n_classes,
                                       multiclass)

    _regression = False

    def _mask_score_host(self, ctx, y, w, n_classes, multiclass):
        from ..ops import trees_host as TH
        Xb, edges, n_bins = ctx
        kw = self._common()
        host_kw, host_ok = self._split_host_kw(kw)
        if not host_ok:  # round-5 param tail: XLA kernels only
            return self._host_fallback(ctx, y, w, n_classes, multiclass)
        depth = kw["depth"]
        seed = int(self.get_param("seed"))
        if self._regression or not multiclass:
            loss = "squared" if self._regression else "logistic"
            out = TH.fit_gbt_host(Xb, y, w, n_bins=n_bins, seed=seed,
                                  loss=loss, **host_kw)
            if out is None:
                return self._host_fallback(ctx, y, w, n_classes, multiclass)
            trees, base = out
            return base + TH.predict_bins_host(trees, Xb, depth)[:, 0]
        trees = TH.fit_gbt_softmax_host(
            Xb, y, w, n_bins=n_bins, n_classes=n_classes, seed=seed,
            **host_kw)
        if trees is None:
            return self._host_fallback(ctx, y, w, n_classes, multiclass)
        # per-class margin = sum over rounds of that class's trees
        margins = np.zeros((Xb.shape[0], n_classes), np.float32)
        for c in range(n_classes):
            sub = T.Tree(feat=trees.feat[:, c], thresh=trees.thresh[:, c],
                         leaf=trees.leaf[:, c], miss=trees.miss[:, c])
            margins[:, c] = TH.predict_bins_host(sub, Xb, depth)[:, 0]
        return margins

    def _mask_scores_fused(self, ctx, y, w, masks, n_classes, multiclass):
        if multiclass and not self._regression:
            return None   # softmax boosting keeps the per-fold path
        kw = self._common()
        if not self._fused_route_ok(ctx, y, masks, kw["depth"]):
            return None
        Xb, edges, n_bins = ctx
        self._plan_growth_form()
        _, _, margins = self._timed_fused_fit(
            "tree_sweep_fold_fused", Xb, masks.shape[0], kw["depth"],
            kw["n_rounds"],
            lambda: T.fit_gbt_folds(
                Xb, y, masks * w[None, :], self._key(), n_bins=n_bins,
                loss="squared" if self._regression else "logistic",
                **kw),
            span="tree_level_scan" if T.tree_scan_enabled() else None)
        return margins

    def _mask_score(self, ctx, y, w, n_classes, multiclass):
        Xb, edges, n_bins = ctx
        kw = self._common()
        depth = kw["depth"]
        if self._regression or not multiclass:
            loss = "squared" if self._regression else "logistic"
            trees, base = T.fit_gbt(Xb, y, w, self._key(), n_bins=n_bins,
                                    loss=loss, **kw)
            return base + T.predict_forest_bins(trees, Xb, depth)[:, 0]
        self._check_multiclass_params(True)
        soft_kw = {k: v for k, v in kw.items() if k != "base_score"}
        trees = T.fit_gbt_softmax(Xb, y, w, self._key(), n_bins=n_bins,
                                  n_classes=n_classes, **soft_kw)

        # trees carry leading [rounds, classes] axes with K=1 payloads;
        # per-class margin = sum over rounds (mirrors the training step)
        def per_round(carry, tree_c):
            step = jax.vmap(
                lambda t: T.predict_bins(t, Xb, depth)[:, 0])(tree_c)
            return carry + step.T, None

        init = jnp.zeros((Xb.shape[0], n_classes), jnp.float32)
        margins, _ = jax.lax.scan(per_round, init, trees)
        return margins  # [n, c]


class OpXGBoostClassifier(_XGBBase):
    """Reference OpXGBoostClassifier (375 LoC, JNI -> libxgboost): binary
    logistic or multiclass softprob, histogram algorithm."""

    problem_types = ("binary", "multiclass")

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("xgbClassifier", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        n_classes = max(int(np.max(y)) + 1 if y.size else 2, 2)
        w = self._apply_spw(y, self._w(y, w), n_classes)
        kw = self._common()
        host_kw, host_ok = self._split_host_kw(kw)
        depth = kw["depth"]
        if self._host_route() and host_ok:
            from ..ops import trees_host as TH
            Xb, edges, n_bins = self._bin_host(X)
            seed = int(self.get_param("seed"))
            yn = np.asarray(y, np.float32)
            if n_classes <= 2:
                out = TH.fit_gbt_host(Xb, yn, w, n_bins=n_bins, seed=seed,
                                      loss="logistic", **host_kw)
                if out is not None:
                    trees, base = out
                    frozen = self._freeze(trees, jnp.asarray(edges))
                    return TreeEnsembleModel(
                        depth=depth, mode="margin", base=float(base),
                        operation_name=self.operation_name, **frozen)
            else:
                trees = TH.fit_gbt_softmax_host(
                    Xb, yn, w, n_bins=n_bins, n_classes=n_classes,
                    seed=seed, **host_kw)
                if trees is not None:
                    frozen = self._freeze(trees, jnp.asarray(edges))
                    return SoftmaxEnsembleModel(
                        depth=depth, n_classes=n_classes,
                        operation_name=self.operation_name, **frozen)
        Xb, edges, n_bins = self._bin(X)
        if n_classes <= 2:
            trees, base = T.fit_gbt(
                Xb, jnp.asarray(y, jnp.float32), jnp.asarray(w), self._key(),
                n_bins=n_bins, loss="logistic", **kw)
            frozen = self._freeze(trees, edges)
            return TreeEnsembleModel(depth=depth, mode="margin",
                                     base=float(base),
                                     operation_name=self.operation_name,
                                     **frozen)
        self._check_multiclass_params(True)
        soft_kw = {k: v for k, v in kw.items() if k != "base_score"}
        trees = T.fit_gbt_softmax(
            Xb, jnp.asarray(y, jnp.float32), jnp.asarray(w), self._key(),
            n_bins=n_bins, n_classes=n_classes, **soft_kw)
        frozen = self._freeze(trees, edges)
        return SoftmaxEnsembleModel(depth=depth, n_classes=n_classes,
                                    operation_name=self.operation_name,
                                    **frozen)


class OpXGBoostRegressor(_XGBBase):
    """Reference OpXGBoostRegressor (346 LoC): squared-error objective."""

    problem_types = ("regression",)
    produces_probabilities = False
    _regression = True

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("xgbRegressor", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        w = self._w(y, w)
        kw = self._common()
        host_kw, host_ok = self._split_host_kw(kw)
        if self._host_route() and host_ok:
            from ..ops import trees_host as TH
            Xb, edges, n_bins = self._bin_host(X)
            out = TH.fit_gbt_host(Xb, np.asarray(y, np.float32), w,
                                  n_bins=n_bins,
                                  seed=int(self.get_param("seed")),
                                  loss="squared", **host_kw)
            if out is not None:
                trees, base = out
                frozen = self._freeze(trees, jnp.asarray(edges))
                return TreeEnsembleModel(
                    depth=kw["depth"], mode="regress_sum", base=float(base),
                    operation_name=self.operation_name, **frozen)
        Xb, edges, n_bins = self._bin(X)
        trees, base = T.fit_gbt(
            Xb, jnp.asarray(y, jnp.float32), jnp.asarray(w), self._key(),
            n_bins=n_bins, loss="squared", **kw)
        frozen = self._freeze(trees, edges)
        return TreeEnsembleModel(depth=kw["depth"], mode="regress_sum",
                                 base=float(base),
                                 operation_name=self.operation_name, **frozen)
