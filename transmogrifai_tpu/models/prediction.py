"""Dense columnar representation of Prediction outputs.

The reference stores Prediction as a reserved-key Map column
(features/.../types/Maps.scala:302). A map-of-doubles per row would cripple
the device path, so here a prediction column is a dense float32 block
``[n, 1 + n_raw + n_prob]`` laid out [prediction, rawPrediction_*,
probability_*] with the layout carried in the column's VectorMetadata
(named columns, so it survives row gathers and persistence). Conversion
to/from the Prediction map type happens only at API boundaries (local
scoring, row access).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..data.dataset import Column
from ..data.vector import VectorColumnMetadata, VectorMetadata
from ..types import ColumnKind, Prediction

_PRED = Prediction.PREDICTION_NAME
_RAW = Prediction.RAW_PREDICTION_NAME
_PROB = Prediction.PROBABILITY_NAME


def make_prediction_column(prediction: np.ndarray,
                           raw_prediction: Optional[np.ndarray] = None,
                           probability: Optional[np.ndarray] = None) -> Column:
    pred = np.asarray(prediction, dtype=np.float32).reshape(-1, 1)
    parts = [pred]
    names = [_PRED]
    for arr, prefix in ((raw_prediction, _RAW), (probability, _PROB)):
        if arr is None:
            continue
        a = np.asarray(arr, dtype=np.float32)
        if a.ndim == 1:
            a = a[:, None]
        parts.append(a)
        names.extend(f"{prefix}_{i}" for i in range(a.shape[1]))
    data = np.concatenate(parts, axis=1)
    md = VectorMetadata(name=_PRED, columns=[
        VectorColumnMetadata(parent_feature_name=_PRED,
                             parent_feature_type="Prediction",
                             descriptor_value=nm, index=i)
        for i, nm in enumerate(names)])
    return Column(kind=ColumnKind.VECTOR, data=data, metadata=md)


def _layout(col: Column) -> Tuple[int, int]:
    """(n_raw, n_prob) from metadata; fallback: symmetric split."""
    if col.metadata is not None and col.metadata.columns and \
            col.metadata.columns[0].descriptor_value == _PRED:
        n_raw = sum(1 for c in col.metadata.columns
                    if (c.descriptor_value or "").startswith(_RAW + "_"))
        n_prob = sum(1 for c in col.metadata.columns
                     if (c.descriptor_value or "").startswith(_PROB + "_"))
        return n_raw, n_prob
    width = col.data.shape[1]
    c = (width - 1) // 2
    return c, c


def n_classes_of(col: Column) -> int:
    n_raw, n_prob = _layout(col)
    return int(max(n_raw, n_prob))


def prediction_of(col: Column) -> np.ndarray:
    return col.data[:, 0]


def raw_prediction_of(col: Column) -> Optional[np.ndarray]:
    n_raw, _ = _layout(col)
    return col.data[:, 1:1 + n_raw] if n_raw else None


def probability_of(col: Column) -> Optional[np.ndarray]:
    n_raw, n_prob = _layout(col)
    return col.data[:, 1 + n_raw:1 + n_raw + n_prob] if n_prob else None


def positive_score_of(col: Column) -> np.ndarray:
    """Score used by binary evaluators: P(class 1) when the model is
    probabilistic, else the positive-class margin (rawPrediction_1 — how the
    reference evaluates LinearSVC), else the hard prediction."""
    prob = probability_of(col)
    if prob is not None and prob.shape[1] >= 2:
        return prob[:, 1]
    raw = raw_prediction_of(col)
    if raw is not None and raw.shape[1] >= 2:
        return raw[:, 1]
    return col.data[:, 0]


def row_prediction(col: Column, i: int) -> Prediction:
    row = col.data[i]
    n_raw, n_prob = _layout(col)
    return Prediction(
        prediction=float(row[0]),
        raw_prediction=[float(x) for x in row[1:1 + n_raw]] if n_raw else None,
        probability=[float(x) for x in row[1 + n_raw:1 + n_raw + n_prob]]
        if n_prob else None)
