"""Multilayer perceptron classifier + isotonic regression calibrator.

Reference: core/.../impl/classification/OpMultilayerPerceptronClassifier.scala
(149 LoC; Spark MLP = sigmoid hidden layers + softmax out, LBFGS) and
core/.../impl/regression/IsotonicRegressionCalibrator.scala (63 LoC).

TPU shape: the MLP trains as one jitted lax.scan of full-batch Adam steps
(matmuls on the MXU; no python loop), matching Spark's full-batch LBFGS
training regime more closely than minibatch SGD would. Isotonic regression
is the classic pool-adjacent-violators pass on host (O(n) after sort) with
a device-friendly step-function transform.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..stages.base import Estimator, Transformer
from ..stages.params import Param
from ..types import RealNN
from .base import PredictionModel, PredictorEstimator, stable_sigmoid
from .glm import SoftmaxModel


def _init_params(key, sizes: Sequence[int]):
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (sizes[i], sizes[i + 1])) * \
            jnp.sqrt(2.0 / sizes[i])
        params.append((w, jnp.zeros(sizes[i + 1])))
    return params


def _forward(params, X):
    h = X
    for w, b in params[:-1]:
        h = jax.nn.sigmoid(h @ w + b)   # Spark MLP uses sigmoid hidden units
    w, b = params[-1]
    return h @ w + b                     # logits


def _fit_mlp(X, Y, w_row, sizes, steps: int, lr: float, l2: float, seed: int):
    key = jax.random.PRNGKey(seed)
    params = _init_params(key, sizes)

    def loss_fn(params):
        logits = _forward(params, X)
        logp = jax.nn.log_softmax(logits, axis=1)
        ce = -(Y * logp).sum(axis=1)
        reg = sum((w * w).sum() for w, _ in params)
        return (w_row * ce).sum() / (w_row.sum() + 1e-12) + l2 * reg

    # full-batch Adam as a lax.scan (one XLA program)
    b1, b2, eps = 0.9, 0.999, 1e-8
    zeros = jax.tree.map(jnp.zeros_like, params)

    def step(carry, i):
        params, m, v = carry
        g = jax.grad(loss_fn)(params)
        m = jax.tree.map(lambda a, b_: b1 * a + (1 - b1) * b_, m, g)
        v = jax.tree.map(lambda a, b_: b2 * a + (1 - b2) * b_ * b_, v, g)
        t = i + 1.0
        mhat = jax.tree.map(lambda a: a / (1 - b1 ** t), m)
        vhat = jax.tree.map(lambda a: a / (1 - b2 ** t), v)
        params = jax.tree.map(
            lambda p, mh, vh: p - lr * mh / (jnp.sqrt(vh) + eps),
            params, mhat, vhat)
        return (params, m, v), None

    (params, _, _), _ = jax.lax.scan(
        step, (params, zeros, zeros), jnp.arange(steps, dtype=jnp.float32))
    return params


_fit_mlp_jit = jax.jit(_fit_mlp, static_argnames=("sizes", "steps", "seed"))


class MLPModel(PredictionModel):
    """Fitted MLP: list of (W, b) layers, sigmoid hidden + softmax out."""

    def __init__(self, weights: List[np.ndarray], biases: List[np.ndarray],
                 operation_name: str = "mlp", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.weights = [np.asarray(w, np.float32) for w in weights]
        self.biases = [np.asarray(b, np.float32) for b in biases]

    def predict_arrays(self, X):
        h = np.asarray(X, np.float32)
        for w, b in zip(self.weights[:-1], self.biases[:-1]):
            h = stable_sigmoid(h @ w + b)
        logits = h @ self.weights[-1] + self.biases[-1]
        m = logits.max(axis=1, keepdims=True)
        e = np.exp(logits - m)
        prob = e / e.sum(axis=1, keepdims=True)
        return prob.argmax(axis=1).astype(np.float32), logits, prob

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(weights=self.weights, biases=self.biases)
        return d


class OpMultilayerPerceptronClassifier(PredictorEstimator):
    """Reference OpMultilayerPerceptronClassifier (149 LoC)."""

    problem_types = ("binary", "multiclass")

    @classmethod
    def _declare_params(cls):
        return [
            Param("hidden_layers", "hidden layer sizes", [10, 10]),
            Param("max_iter", "Adam steps", 200),
            Param("step_size", "learning rate", 0.05),
            Param("reg_param", "L2 strength", 1e-4),
            Param("seed", "init seed", 42),
        ]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("mlpClassifier", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        X = np.asarray(X, np.float32)
        y = np.asarray(y)
        w = np.ones(len(y), np.float32) if w is None else np.asarray(
            w, np.float32)
        n_classes = max(int(y.max()) + 1 if y.size else 2, 2)
        Y = np.eye(n_classes, dtype=np.float32)[y.astype(int)]
        hidden = [int(h) for h in self.get_param("hidden_layers")]
        sizes = tuple([X.shape[1]] + hidden + [n_classes])
        params = _fit_mlp_jit(
            jnp.asarray(X), jnp.asarray(Y), jnp.asarray(w), sizes,
            steps=int(self.get_param("max_iter")),
            lr=float(self.get_param("step_size")),
            l2=float(self.get_param("reg_param")),
            seed=int(self.get_param("seed")))
        return MLPModel([np.asarray(w_) for w_, _ in params],
                        [np.asarray(b_) for _, b_ in params],
                        operation_name=self.operation_name)


# -- isotonic regression ----------------------------------------------------

def pav_fit(x: np.ndarray, y: np.ndarray, w: Optional[np.ndarray] = None
            ) -> Tuple[np.ndarray, np.ndarray]:
    """Pool-adjacent-violators: weighted isotonic fit of y on x.

    Returns (boundaries, values): step function value[i] on x >=
    boundaries[i] (right-continuous), non-decreasing.
    """
    order = np.argsort(x, kind="stable")
    xs, ys = x[order], y[order].astype(np.float64)
    ws = (np.ones(len(y)) if w is None else w[order]).astype(np.float64)
    # blocks: (sum_w, sum_wy, x_start)
    vals: List[float] = []
    wsum: List[float] = []
    xstart: List[float] = []
    for xi, yi, wi in zip(xs, ys, ws):
        vals.append(yi * wi)
        wsum.append(wi)
        xstart.append(xi)
        while len(vals) > 1 and vals[-2] / wsum[-2] >= vals[-1] / wsum[-1]:
            v, s = vals.pop(), wsum.pop()
            xstart.pop()
            vals[-1] += v
            wsum[-1] += s
    values = np.array([v / s for v, s in zip(vals, wsum)])
    return np.asarray(xstart, np.float64), values


class IsotonicRegressionCalibrator(Estimator):
    """(RealNN label, RealNN score) -> RealNN calibrated score (reference
    IsotonicRegressionCalibrator.scala:63 wrapping Spark IsotonicRegression)."""

    input_types = (RealNN, RealNN)
    output_type = RealNN

    @classmethod
    def _declare_params(cls):
        return [Param("isotonic", "non-decreasing if true", True)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("isoCalibrator", uid=uid, **params)

    def fit_columns(self, *cols) -> Transformer:
        label = np.asarray(cols[0].data, np.float64)
        score = np.asarray(cols[1].data, np.float64)
        ok = ~(np.isnan(label) | np.isnan(score))
        x, y = score[ok], label[ok]
        if not bool(self.get_param("isotonic")):
            x = -x
        bounds, values = pav_fit(x, y)
        return IsotonicRegressionModel(
            boundaries=bounds, values=values,
            increasing=bool(self.get_param("isotonic")),
            operation_name=self.operation_name)


class IsotonicRegressionModel(Transformer):
    input_types = (RealNN, RealNN)
    output_type = RealNN

    def __init__(self, boundaries: Optional[np.ndarray] = None,
                 values: Optional[np.ndarray] = None, increasing: bool = True,
                 uid: Optional[str] = None, **params):
        self.boundaries = np.asarray(
            boundaries if boundaries is not None else [0.0], np.float64)
        self.values = np.asarray(values if values is not None else [0.0],
                                 np.float64)
        self.increasing = bool(increasing)
        super().__init__(params.pop("operation_name", "isoCalibrator"),
                         uid=uid, **params)

    def _apply(self, score: np.ndarray) -> np.ndarray:
        x = score if self.increasing else -score
        idx = np.clip(np.searchsorted(self.boundaries, x, side="right") - 1,
                      0, len(self.values) - 1)
        return self.values[idx]

    def transform_value(self, *vals):
        return RealNN(float(self._apply(np.asarray([vals[-1].value]))[0]))

    def transform_columns(self, *cols):
        from ..data.dataset import Column
        from ..types import ColumnKind
        return Column(kind=ColumnKind.FLOAT,
                      data=self._apply(np.asarray(cols[-1].data, np.float64)))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(boundaries=self.boundaries, values=self.values,
                 increasing=self.increasing)
        return d
