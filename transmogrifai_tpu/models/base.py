"""Predictor estimator/model bases.

Reference: core/.../sparkwrappers/specific/OpPredictorWrapper.scala:67 — every
model is an Estimator2(RealNN label, OPVector features) producing a
Prediction. Here the fitted model holds concrete device arrays; its transform
is pure array math (jit/vmap-able); `fit_arrays` / `predict_arrays` expose
the raw tensor path used by the model-selector sweep so no column plumbing
sits between folds.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Type

import numpy as np

from ..data.dataset import Column, Dataset
from ..stages.base import Estimator, Transformer
from ..types import OPVector, Prediction, RealNN
from .prediction import make_prediction_column, row_prediction


def stable_sigmoid(x: np.ndarray) -> np.ndarray:
    """Overflow-safe logistic: exp only ever sees non-positive arguments."""
    x = np.asarray(x)
    out = np.empty_like(
        x, dtype=x.dtype if x.dtype.kind == "f" else np.float64)
    pos = x >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-x[pos]))
    ex = np.exp(x[~pos])
    out[~pos] = ex / (1.0 + ex)
    return out


def _as_matrix(col: Column) -> np.ndarray:
    m = col.data
    if m.ndim == 1:
        m = m[:, None]
    return np.ascontiguousarray(m, dtype=np.float32)


def _as_labels(col: Column) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(col.data, dtype=np.float64), dtype=np.float32)


class PredictionModel(Transformer):
    """Fitted model: (label, features) -> Prediction column."""

    input_types = (RealNN, OPVector)
    output_type = Prediction

    def __init__(self, operation_name: str, uid: Optional[str] = None, **params):
        super().__init__(operation_name, uid=uid, **params)

    # -- tensor path -------------------------------------------------------
    def predict_arrays(self, X: np.ndarray) -> Tuple[np.ndarray,
                                                     Optional[np.ndarray],
                                                     Optional[np.ndarray]]:
        """X [n,d] -> (prediction [n], raw [n,c]|None, prob [n,c]|None)."""
        raise NotImplementedError

    # -- column path -------------------------------------------------------
    def transform_columns(self, *cols: Column) -> Column:
        vec = cols[-1]  # features are the last input
        pred, raw, prob = self.predict_arrays(_as_matrix(vec))
        return make_prediction_column(pred, raw, prob)

    def transform_value(self, *vals):
        X = np.asarray(vals[-1].value, dtype=np.float32)[None, :]
        pred, raw, prob = self.predict_arrays(X)
        col = make_prediction_column(pred, raw, prob)
        return row_prediction(col, 0)

    def transform_keyvalue(self, row: Dict[str, Any]) -> Any:
        feats = row.get(self.input_names()[-1])
        X = np.asarray(feats, dtype=np.float32)[None, :]
        pred, raw, prob = self.predict_arrays(X)
        col = make_prediction_column(pred, raw, prob)
        return row_prediction(col, 0).value


class PredictorEstimator(Estimator):
    """Unfitted model: fit(label, features) -> PredictionModel."""

    input_types = (RealNN, OPVector)
    output_type = Prediction
    # model-selector hints
    problem_types = ("binary",)   # subset of binary|multiclass|regression
    supports_grid_vmap = False    # GLMs override: grid+fold axes vmappable
    produces_probabilities = True  # margin-only models (SVC) override False

    def fit_arrays(self, X: np.ndarray, y: np.ndarray,
                   w: Optional[np.ndarray] = None) -> PredictionModel:
        raise NotImplementedError

    def fit_columns(self, *cols: Column) -> PredictionModel:
        label_col, vec_col = cols
        model = self.fit_arrays(_as_matrix(vec_col), _as_labels(label_col))
        return model
