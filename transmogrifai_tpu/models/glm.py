"""GLM-family predictors: logistic regression, linear SVC, naive Bayes,
linear regression, generalized linear regression.

Reference wrappers: core/.../impl/classification/{OpLogisticRegression,
OpLinearSVC, OpNaiveBayes}.scala, core/.../impl/regression/
{OpLinearRegression, OpGeneralizedLinearRegression}.scala. Param names mirror
the Spark params the reference grids over (DefaultSelectorParams.scala:35-56).

All fits run through ops/glm solvers — fixed-iteration jitted Newton — so the
selector can vmap them over (grid x fold).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import glm as G
from ..stages.params import Param
from .base import PredictionModel, PredictorEstimator, stable_sigmoid


# -- fitted models ---------------------------------------------------------

class LinearBinaryModel(PredictionModel):
    """Binary linear scorer: logistic (prob via sigmoid) or SVC (margin)."""

    def __init__(self, beta: np.ndarray, intercept: float,
                 probabilistic: bool = True,
                 operation_name: str = "linBin", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.beta = np.asarray(beta, np.float32)
        self.intercept = float(intercept)
        self.probabilistic = probabilistic

    def predict_arrays(self, X):
        margin = X @ self.beta + self.intercept
        raw = np.stack([-margin, margin], axis=1)
        if self.probabilistic:
            p1 = stable_sigmoid(margin)
            prob = np.stack([1.0 - p1, p1], axis=1)
            pred = (p1 >= 0.5).astype(np.float32)
        else:
            prob = None
            pred = (margin >= 0.0).astype(np.float32)
        return pred, raw, prob

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(beta=self.beta.tolist(), intercept=self.intercept,
                 probabilistic=self.probabilistic)
        return d


class SoftmaxModel(PredictionModel):
    """Multinomial logistic scorer."""

    def __init__(self, B: np.ndarray, b0: np.ndarray,
                 operation_name: str = "softmax", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.B = np.asarray(B, np.float32)
        self.b0 = np.asarray(b0, np.float32)

    def predict_arrays(self, X):
        logits = X @ self.B + self.b0[None, :]
        logits = logits - logits.max(axis=1, keepdims=True)
        e = np.exp(logits)
        prob = e / e.sum(axis=1, keepdims=True)
        pred = prob.argmax(axis=1).astype(np.float32)
        return pred, logits, prob

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(B=self.B.tolist(), b0=self.b0.tolist())
        return d


class LinearRegressionModel(PredictionModel):
    def __init__(self, beta: np.ndarray, intercept: float,
                 link: str = "identity",
                 operation_name: str = "linReg", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.beta = np.asarray(beta, np.float32)
        self.intercept = float(intercept)
        self.link = link

    def predict_arrays(self, X):
        eta = X @ self.beta + self.intercept
        pred = np.exp(eta) if self.link == "log" else eta
        return pred.astype(np.float32), None, None

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(beta=self.beta.tolist(), intercept=self.intercept, link=self.link)
        return d


class NaiveBayesModel(PredictionModel):
    def __init__(self, log_prob: np.ndarray, log_prior: np.ndarray,
                 operation_name: str = "nb", uid: Optional[str] = None):
        super().__init__(operation_name, uid=uid)
        self.log_prob = np.asarray(log_prob, np.float32)
        self.log_prior = np.asarray(log_prior, np.float32)

    def predict_arrays(self, X):
        raw = np.maximum(X, 0.0) @ self.log_prob.T + self.log_prior[None, :]
        m = raw.max(axis=1, keepdims=True)
        e = np.exp(raw - m)
        prob = e / e.sum(axis=1, keepdims=True)
        pred = raw.argmax(axis=1).astype(np.float32)
        return pred, raw, prob

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(log_prob=self.log_prob.tolist(), log_prior=self.log_prior.tolist())
        return d


# -- estimators ------------------------------------------------------------

_jit_fit_logistic = jax.jit(G.fit_logistic, static_argnames=(
    "max_iter", "fit_intercept", "standardize"))
_jit_fit_linear = jax.jit(G.fit_linear, static_argnames=(
    "max_iter", "fit_intercept", "standardize"))
_jit_fit_svc = jax.jit(G.fit_linear_svc, static_argnames=(
    "max_iter", "fit_intercept", "standardize"))
_jit_fit_softmax = jax.jit(G.fit_softmax, static_argnames=(
    "max_iter", "fit_intercept", "standardize"))
_jit_fit_glr = jax.jit(G.fit_glr, static_argnames=("family", "max_iter",
                                                   "fit_intercept"))
_jit_fit_nb = jax.jit(G.fit_naive_bayes)


def _ones_like_w(y, w):
    return np.ones_like(y, np.float32) if w is None else np.asarray(w, np.float32)


# fit_one closures are static args of the validator's jitted sweep; cache them
# per static config so repeated validate() calls hit the XLA compile cache
@functools.lru_cache(maxsize=None)
def _batched_logistic(max_iter, fit_intercept, standardize):
    def fit_one(X, y, w, reg, alpha):
        return G.fit_logistic(X, y, w, reg, alpha, max_iter=max_iter,
                              fit_intercept=fit_intercept,
                              standardize=standardize)
    return fit_one


@functools.lru_cache(maxsize=None)
def _batched_softmax(max_iter, fit_intercept, standardize, n_classes):
    """Multiclass fit_one for the vmapped sweep: same (X, y, w, reg, alpha)
    signature as the binary closure; one-hot happens inside the trace so the
    selector needs no special-casing (VERDICT r1: the multiclass sweep ran
    per-(fold x grid) host loops — reference OpValidator.scala:270 gave every
    problem type the same thread-pool treatment)."""
    def fit_one(X, y, w, reg, alpha):
        Y = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=X.dtype)
        return G.fit_softmax(X, Y, w, reg, alpha, max_iter=max_iter,
                             fit_intercept=fit_intercept,
                             standardize=standardize)
    return fit_one


@functools.lru_cache(maxsize=None)
def _batched_linear(max_iter, fit_intercept, standardize):
    def fit_one(X, y, w, reg, alpha):
        return G.fit_linear(X, y, w, reg, alpha, max_iter=max_iter,
                            fit_intercept=fit_intercept,
                            standardize=standardize)
    return fit_one


@functools.lru_cache(maxsize=None)
def _batched_svc(max_iter, fit_intercept, standardize):
    def fit_one(X, y, w, reg, _alpha):
        return G.fit_linear_svc(X, y, w, reg, max_iter=max_iter,
                                fit_intercept=fit_intercept,
                                standardize=standardize)
    return fit_one


class OpLogisticRegression(PredictorEstimator):
    """Reference OpLogisticRegression (impl/classification/, 212 LoC)."""

    problem_types = ("binary", "multiclass")
    supports_grid_vmap = True
    supports_multiclass_vmap = True
    # large binary sweeps stream ALL (fold x grid) lanes through shared
    # X passes (ops/glm_sweep.py). Parity contract: the convergence-aware
    # round driver retires each lane at its OWN delta <= tol — the same
    # stopping rule ops/glm._newton_prox_fit applies per lane — so
    # streamed coefficients match this estimator's fit_arrays within tol
    # (tests/test_glm_convergence.py pins it).
    streamed_loss = "logistic"

    @classmethod
    def _declare_params(cls):
        return [
            Param("reg_param", "regularization strength", 0.0),
            Param("elastic_net_param", "L1 ratio", 0.0),
            Param("max_iter", "Newton iterations", 50),
            Param("tol", "termination tolerance", 1e-6),
            Param("fit_intercept", "fit intercept", True),
            Param("standardization", "standardize features", True),
        ]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("logreg", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        w = _ones_like_w(y, w)
        n_classes = int(np.max(y)) + 1 if y.size else 2
        if n_classes <= 2:
            beta, b0 = _jit_fit_logistic(
                jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
                jnp.asarray(self.get_param("reg_param"), jnp.float32),
                jnp.asarray(self.get_param("elastic_net_param"), jnp.float32),
                max_iter=int(self.get_param("max_iter")),
                tol=float(self.get_param("tol")),
                fit_intercept=bool(self.get_param("fit_intercept")),
                standardize=bool(self.get_param("standardization")))
            return LinearBinaryModel(np.asarray(beta), float(b0),
                                     probabilistic=True,
                                     operation_name=self.operation_name)
        Y = np.eye(n_classes, dtype=np.float32)[y.astype(int)]
        B, b0 = _jit_fit_softmax(
            jnp.asarray(X), jnp.asarray(Y), jnp.asarray(w),
            jnp.asarray(self.get_param("reg_param"), jnp.float32),
            jnp.asarray(self.get_param("elastic_net_param"), jnp.float32),
            max_iter=min(int(self.get_param("max_iter")), 30),
            fit_intercept=bool(self.get_param("fit_intercept")),
            standardize=bool(self.get_param("standardization")))
        return SoftmaxModel(np.asarray(B), np.asarray(b0),
                            operation_name=self.operation_name)

    # vmapped grid+fold fit used by the selector; n_classes > 2 swaps in the
    # softmax solver with the SAME closure signature
    def batched_fit_fn(self, n_classes: int = 2):
        if n_classes > 2:
            fit_one = _batched_softmax(
                min(int(self.get_param("max_iter")), 30),
                bool(self.get_param("fit_intercept")),
                bool(self.get_param("standardization")), int(n_classes))
        else:
            fit_one = _batched_logistic(
                int(self.get_param("max_iter")),
                bool(self.get_param("fit_intercept")),
                bool(self.get_param("standardization")))
        return fit_one, ("reg_param", "elastic_net_param")

    def model_from_params(self, beta, b0):
        beta = np.asarray(beta)
        if beta.ndim == 2:  # softmax winner refit
            return SoftmaxModel(beta, np.asarray(b0),
                                operation_name=self.operation_name)
        return LinearBinaryModel(beta, float(b0), probabilistic=True,
                                 operation_name=self.operation_name)


class OpLinearSVC(PredictorEstimator):
    """Reference OpLinearSVC (impl/classification/, 166 LoC)."""

    problem_types = ("binary",)
    supports_grid_vmap = True
    produces_probabilities = False
    # same retirement parity contract as OpLogisticRegression; the
    # 0.5*gap^2 loss scaling keeps reg_param's effective L2 identical on
    # the streamed and per-lane routes
    streamed_loss = "squared_hinge"

    @classmethod
    def _declare_params(cls):
        return [
            Param("reg_param", "L2 strength", 0.0),
            Param("max_iter", "Newton iterations", 50),
            Param("tol", "termination tolerance", 1e-6),
            Param("fit_intercept", "fit intercept", True),
            Param("standardization", "standardize features", True),
        ]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("svc", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        w = _ones_like_w(y, w)
        beta, b0 = _jit_fit_svc(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(self.get_param("reg_param"), jnp.float32),
            max_iter=int(self.get_param("max_iter")),
            tol=float(self.get_param("tol")),
            fit_intercept=bool(self.get_param("fit_intercept")),
            standardize=bool(self.get_param("standardization")))
        return LinearBinaryModel(np.asarray(beta), float(b0),
                                 probabilistic=False,
                                 operation_name=self.operation_name)

    def batched_fit_fn(self):
        fit_one = _batched_svc(int(self.get_param("max_iter")),
                               bool(self.get_param("fit_intercept")),
                               bool(self.get_param("standardization")))
        return fit_one, ("reg_param",)

    def model_from_params(self, beta, b0) -> LinearBinaryModel:
        return LinearBinaryModel(np.asarray(beta), float(b0),
                                 probabilistic=False,
                                 operation_name=self.operation_name)


class OpNaiveBayes(PredictorEstimator):
    """Reference OpNaiveBayes (multinomial; 112 LoC)."""

    problem_types = ("binary", "multiclass")

    @classmethod
    def _declare_params(cls):
        return [Param("smoothing", "Laplace smoothing", 1.0)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("naiveBayes", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        w = _ones_like_w(y, w)
        n_classes = max(int(np.max(y)) + 1 if y.size else 2, 2)
        Y = np.eye(n_classes, dtype=np.float32)[y.astype(int)]
        log_prob, log_prior = _jit_fit_nb(
            jnp.asarray(X), jnp.asarray(Y), jnp.asarray(w),
            float(self.get_param("smoothing")))
        return NaiveBayesModel(np.asarray(log_prob), np.asarray(log_prior),
                               operation_name=self.operation_name)


class OpLinearRegression(PredictorEstimator):
    """Reference OpLinearRegression (impl/regression/, 186 LoC)."""

    problem_types = ("regression",)
    supports_grid_vmap = True
    # squared loss has curvature == 1, so the streamed route collapses to
    # the sufficient-statistics Gram fast path: ONE streaming pass builds
    # per-fold X^T W X moments, then the whole grid solves off them via
    # ops/glm.ridge_gram_solve (closed form, the per-lane Newton's fixed
    # point) and ops/glm.prox_newton_gram (the per-lane update rule
    # replayed in moment space) — the parity contract with fit_arrays
    streamed_loss = "squared"

    @classmethod
    def _declare_params(cls):
        return [
            Param("reg_param", "regularization strength", 0.0),
            Param("elastic_net_param", "L1 ratio", 0.0),
            Param("max_iter", "iterations", 50),
            Param("tol", "termination tolerance", 1e-6),
            Param("fit_intercept", "fit intercept", True),
            Param("standardization", "standardize features", True),
            Param("solver", "auto|normal|l-bfgs (ignored; Newton used)", "auto"),
        ]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("linReg", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        w = _ones_like_w(y, w)
        beta, b0 = _jit_fit_linear(
            jnp.asarray(X), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(self.get_param("reg_param"), jnp.float32),
            jnp.asarray(self.get_param("elastic_net_param"), jnp.float32),
            max_iter=int(self.get_param("max_iter")),
            tol=float(self.get_param("tol")),
            fit_intercept=bool(self.get_param("fit_intercept")),
            standardize=bool(self.get_param("standardization")))
        return LinearRegressionModel(np.asarray(beta), float(b0),
                                     operation_name=self.operation_name)

    def batched_fit_fn(self):
        fit_one = _batched_linear(int(self.get_param("max_iter")),
                                  bool(self.get_param("fit_intercept")),
                                  bool(self.get_param("standardization")))
        return fit_one, ("reg_param", "elastic_net_param")

    def model_from_params(self, beta, b0) -> LinearRegressionModel:
        return LinearRegressionModel(np.asarray(beta), float(b0),
                                     operation_name=self.operation_name)


class OpGeneralizedLinearRegression(PredictorEstimator):
    """Reference OpGeneralizedLinearRegression (198 LoC): family/link GLR."""

    problem_types = ("regression",)

    @classmethod
    def _declare_params(cls):
        return [
            Param("family", "gaussian|poisson|gamma", "gaussian",
                  lambda v: v in ("gaussian", "poisson", "gamma")),
            Param("reg_param", "L2 strength", 0.0),
            Param("max_iter", "IRLS iterations", 25),
            Param("fit_intercept", "fit intercept", True),
        ]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__("glr", uid=uid, **params)

    def fit_arrays(self, X, y, w=None):
        w = _ones_like_w(y, w)
        family = self.get_param("family")
        if family in ("poisson", "gamma"):
            y = np.maximum(y, 1e-6 if family == "gamma" else 0.0)
        beta, b0 = _jit_fit_glr(
            jnp.asarray(X), jnp.asarray(y, np.float32), jnp.asarray(w),
            jnp.asarray(self.get_param("reg_param"), jnp.float32),
            family=family,
            max_iter=int(self.get_param("max_iter")),
            fit_intercept=bool(self.get_param("fit_intercept")))
        link = "log" if family in ("poisson", "gamma") else "identity"
        return LinearRegressionModel(np.asarray(beta), float(b0), link=link,
                                     operation_name=self.operation_name)
