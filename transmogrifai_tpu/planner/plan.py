"""``plan_fit`` / ``plan_serving`` — THE per-shape route/knob choke point.

Every hot-path decision the repo used to read from a hand-set constant
or env var resolves here instead:

==========================  ===========================================
decision                    consumed by
==========================  ===========================================
glm_streamed_min_rows       validators._streamable (streamed-vs-
                            materialized GLM sweep route)
tree_scan                   models/trees fused fits (scan-vs-unrolled
                            growth form, via ops/trees.set_tree_scan)
grid_fuse                   validators' config-fused sweep gate
grid_fuse_hbm_lanes/out_mb  ops/pallas_hist.plan_lane_chunk caps
tile_mb                     parallel/tileplane.tile_budget_bytes
stats_tile_rows             ops/stats_engine.stream_tile_rows_default
score_tile_rows             readers/streaming.score_tile_rows_default
glm_bucket_floor            ops/glm_sweep.bucket_lanes (lane-retirement
                            compaction ladder)
serve_bucket_floor          serve/engine bucket ladder (plan_serving)
tile_prefetch               parallel/tileplane.tile_prefetch_depth
                            (prefetch-ring depth; derived from measured
                            tile_parse/tile_copy/tile_compute ratios)
ingest_workers              parallel/ingest.ingest_workers (sharded
                            parse-worker pool size)
==========================  ===========================================

Precedence, strictly: **an explicitly-set TMOG_* env var always wins**
(hand beats model; the override is logged once as a ``plan_override``
event), then the measured model (``TMOG_PLAN=1``, the default), then
the hand default (``TMOG_PLAN=0``, or a cold corpus — in both cases the
plan is bit-identical to today's hand plan). Decision lookups are
cached against the corpus fingerprint and never raise: any planner
fault degrades to the hand default, because a broken corpus must not
break a fit.
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, Mapping, Optional, Tuple

from .corpus import Corpus
from .model import (COMPILE_BUDGET_S, HAND_DEFAULTS, CostModel,
                    compile_ok)

_DEFAULT_CORPUS_DIR = os.path.join("~", ".cache", "transmogrifai_tpu",
                                   "plan-corpus")

#: decision name -> the env knob that hand-overrides it (decisions that
#: were bare constants before this PR have no override knob)
_ENV_FOR: Dict[str, str] = {
    "tree_scan": "TMOG_TREE_SCAN",
    "grid_fuse": "TMOG_GRID_FUSE",
    "grid_fuse_hbm_lanes": "TMOG_GRID_FUSE_HBM_LANES",
    "grid_fuse_out_mb": "TMOG_GRID_FUSE_OUT_MB",
    "tile_mb": "TMOG_TILE_MB",
    "stats_tile_rows": "TMOG_STATS_TILE_ROWS",
    "score_tile_rows": "TMOG_SCORE_TILE_ROWS",
    "tile_prefetch": "TMOG_TILE_PREFETCH",
    "ingest_workers": "TMOG_INGEST_WORKERS",
}

_lock = threading.Lock()
_model_cache: Dict[Tuple, CostModel] = {}
_decision_cache: Dict[Tuple, "PlanDecision"] = {}
_overrides_logged: set = set()
_plans_logged: set = set()


def plan_enabled() -> bool:
    """The kill switch: TMOG_PLAN=0 pins every decision to its hand
    default (env overrides still logged and honored). Parsed through
    glm_sweep.env_on — the one tri-state TMOG_* toggle parse, so the
    accepted falsy spellings cannot drift between modules."""
    from ..ops.glm_sweep import env_on
    return env_on("TMOG_PLAN")


def corpus_dir() -> str:
    """TMOG_PLAN_CORPUS_DIR, defaulting to the per-user cache dir so
    calibration and harvested bench spans persist across runs."""
    return os.path.expanduser(
        os.environ.get("TMOG_PLAN_CORPUS_DIR", "").strip()
        or _DEFAULT_CORPUS_DIR)


def _backend() -> str:
    """Corpus key for this process's measurements. Multi-process pods
    append "-pc<N>": a collective-bearing span's wall includes DCN
    waits, so pod measurements must never steer (or be steered by)
    single-process plans — the suffix keys them into their own
    corpus-<backend>.jsonl file and plan cache (docs/planning.md)."""
    try:
        import jax
        backend = jax.default_backend()
        pc = jax.process_count()
        return f"{backend}-pc{pc}" if pc > 1 else backend
    except Exception:
        return "cpu"


@dataclasses.dataclass(frozen=True)
class PlanDecision:
    """One resolved decision: value + where it came from.

    source: ``prior`` (hand default — cold corpus or default won the
    measured comparison), ``measured`` (the corpus moved it), ``env``
    (an explicitly-set TMOG_* var overrode the planner), ``off``
    (TMOG_PLAN=0). ``alternatives`` maps candidate -> predicted cost
    (None = unmeasured) for `plan explain`."""

    name: str
    value: Any
    source: str
    alternatives: Mapping[Any, Optional[float]] = \
        dataclasses.field(default_factory=dict)
    reason: str = ""


def _note_override(name: str, env_name: str, value: Any) -> None:
    """Log a hand override ONCE per knob per process (the knobs are
    read per tile / per sweep — per-read events would flood the log)."""
    with _lock:
        if env_name in _overrides_logged:
            return
        _overrides_logged.add(env_name)
    try:
        from ..utils.metrics import collector
        collector.event("plan_override", decision=name, env=env_name,
                        value=value)
    except Exception:
        pass


def _env_override(name: str) -> Optional[PlanDecision]:
    """The explicitly-set env var's value, or None when unset/unparsable
    (an unparsable override falls through to the planner rather than
    crashing the read site — matching int() call sites would have
    raised before this PR, but the planner path must not add new crash
    modes)."""
    env_name = _ENV_FOR.get(name)
    if not env_name or env_name not in os.environ:
        return None
    raw = os.environ[env_name].strip()
    default = HAND_DEFAULTS[name]
    try:
        if name == "grid_fuse":
            value: Any = raw.lower() in ("1", "true", "on")
        elif name == "tree_scan":
            value = raw.lower() not in ("0", "false", "off")
        elif isinstance(default, float):
            value = float(raw)
        else:
            value = int(raw)
    except ValueError:
        return None
    _note_override(name, env_name, value)
    return PlanDecision(name=name, value=value, source="env",
                        reason=f"{env_name} explicitly set")


def _model() -> Optional[CostModel]:
    """The cached per-(backend, corpus fingerprint) cost model; None
    when the corpus is unreadable."""
    try:
        corpus = Corpus(corpus_dir())
        backend = _backend()
        key = (backend, corpus.fingerprint())
        with _lock:
            m = _model_cache.get(key)
            if m is not None:
                return m
        m = CostModel(corpus, backend)
        with _lock:
            _model_cache.clear()  # one fingerprint is ever live
            _decision_cache.clear()
            _model_cache[key] = m
        return m
    except Exception:
        return None


def _decide(name: str, compute, cache_key: Tuple = ()) -> PlanDecision:
    """Shared resolution ladder: env override -> kill switch -> cached
    model decision -> hand default on any fault."""
    env = _env_override(name)
    if env is not None:
        return env
    default = HAND_DEFAULTS[name]
    if not plan_enabled():
        return PlanDecision(name=name, value=default, source="off",
                            reason="TMOG_PLAN=0")
    model = _model()
    if model is None:
        return PlanDecision(name=name, value=default, source="prior",
                            reason="corpus unreadable")
    key = (model.backend, name) + cache_key
    with _lock:
        hit = _decision_cache.get(key)
        if hit is not None:
            return hit
    try:
        decision = compute(model)
    except Exception as e:  # a model fault is never a fit fault
        decision = PlanDecision(name=name, value=default, source="prior",
                                reason=f"model error: {type(e).__name__}")
    with _lock:
        _decision_cache[key] = decision
    return decision


def _value_decision(name: str, family: str):
    def compute(model: CostModel) -> PlanDecision:
        value, source, alts = model.choose_value(
            name, family, HAND_DEFAULTS[name])
        return PlanDecision(name=name, value=value, source=source,
                            alternatives=alts)
    return compute


# -- shape-free knob getters (the scattered low-level consumers) -------------

def planned_tile_mb() -> int:
    """Tileplane tile size (MB) — parallel/tileplane.tile_budget_bytes."""
    return int(_decide("tile_mb",
                       _value_decision("tile_mb", "tileplane_tile")).value)


def planned_stats_tile_rows() -> int:
    """Streamed statistics tile rows — ops/stats_engine."""
    return int(_decide(
        "stats_tile_rows",
        _value_decision("stats_tile_rows", "stats_tile")).value)


def planned_score_tile_rows() -> int:
    """Bulk-scoring tile rows — readers/streaming."""
    return int(_decide(
        "score_tile_rows",
        _value_decision("score_tile_rows", "score_tile")).value)


def _compute_tile_prefetch(model: CostModel) -> PlanDecision:
    """Prefetch-ring depth: the measured knob argmin when the knob
    family carries direct A/B evidence; otherwise DERIVED from the
    measured tile-span ratios the tileplane already publishes — a feed
    side (tile_parse + tile_copy unit cost) running k x slower than the
    device step (tile_compute) needs ~ceil(k) tiles in flight before
    the consumer stops starving, clamped to the candidate range. Cold
    on both -> the depth-1 hand default (classic double buffering)."""
    import math as _math

    default = HAND_DEFAULTS["tile_prefetch"]
    value, source, alts = model.choose_value(
        "tile_prefetch", "tileplane_prefetch", default)
    if source == "measured":
        return PlanDecision(name="tile_prefetch", value=value,
                            source=source, alternatives=alts)
    ratio = model.feed_compute_ratio()
    if ratio is None:
        return PlanDecision(name="tile_prefetch", value=value,
                            source=source, alternatives=alts,
                            reason="no tile-span evidence")
    from .model import CANDIDATES
    cap = max(CANDIDATES["tile_prefetch"])
    depth = max(1, min(cap, int(_math.ceil(ratio))))
    return PlanDecision(
        name="tile_prefetch", value=depth,
        source="prior" if depth == default else "measured",
        alternatives=alts,
        reason=f"feed/compute unit-cost ratio {ratio:.2f}")


def planned_tile_prefetch() -> int:
    """Tileplane prefetch-ring depth —
    parallel/tileplane.tile_prefetch_depth."""
    return max(1, int(_decide("tile_prefetch",
                              _compute_tile_prefetch).value))


def planned_ingest_workers() -> int:
    """Sharded-ingest parse-worker pool size —
    parallel/ingest.ingest_workers. Moves off the serial hand default
    only on direct measured A/B evidence (the ingest_ab bench / a
    calibration run feeding the ingest_parse family with knob
    records)."""
    return max(1, int(_decide(
        "ingest_workers",
        _value_decision("ingest_workers", "ingest_parse")).value))


def planned_glm_bucket_floor() -> int:
    """Smallest lane bucket of the GLM retirement compaction ladder —
    ops/glm_sweep.bucket_lanes."""
    return int(_decide(
        "glm_bucket_floor",
        _value_decision("glm_bucket_floor", "glm_bucket")).value)


def _compute_out_mb(model: CostModel) -> PlanDecision:
    """Out-block cap decision: the measured argmin over KNEE-SAFE
    candidates only, so a corpus can never push the cap to a block
    size whose predicted Mosaic compile busts the budget (the 16 MB /
    20-minute r5 shape stays rejected at plan time)."""
    from .model import CANDIDATES
    safe = [c for c in CANDIDATES["grid_fuse_out_mb"]
            if compile_ok(c, model.backend)]
    if HAND_DEFAULTS["grid_fuse_out_mb"] not in safe:
        safe.append(HAND_DEFAULTS["grid_fuse_out_mb"])
    value, source, alts = model.choose_value(
        "grid_fuse_out_mb", "tree_sweep_out",
        HAND_DEFAULTS["grid_fuse_out_mb"], candidates=safe)
    return PlanDecision(name="grid_fuse_out_mb", value=value,
                        source=source, alternatives=alts,
                        reason=f"knee-safe candidates {safe}")


def _caps_decisions() -> Tuple[PlanDecision, PlanDecision]:
    return (_decide("grid_fuse_hbm_lanes",
                    _value_decision("grid_fuse_hbm_lanes",
                                    "tree_sweep_lanes")),
            _decide("grid_fuse_out_mb", _compute_out_mb))


def planned_grid_fuse_caps() -> Tuple[int, float]:
    """(HBM lane budget, out-block MB cap) for the fused-sweep chunk
    planner — ops/pallas_hist.plan_lane_chunk."""
    lanes, out_mb = _caps_decisions()
    return int(lanes.value), float(out_mb.value)


def _min_rows_decision(n_feat: int, lanes: int) -> PlanDecision:
    shape = {"feat": float(n_feat), "lanes": float(lanes)}

    def compute(model: CostModel) -> PlanDecision:
        rows, source = model.crossover_rows(
            "glm_sweep", "vmapped", "streamed", shape,
            HAND_DEFAULTS["glm_streamed_min_rows"])
        return PlanDecision(name="glm_streamed_min_rows", value=rows,
                            source=source)
    return _decide("glm_streamed_min_rows", compute,
                   cache_key=(n_feat, lanes))


def glm_streamed_min_rows(n_feat: int = 0, lanes: int = 0) -> int:
    """Row floor above which GLM sweeps take the streamed lane-batched
    route — validators._streamable's crossover."""
    return int(_min_rows_decision(n_feat, lanes).value)


def planned_tree_scan() -> Optional[bool]:
    """Scan-vs-unrolled fused tree growth, or None when the caller
    should leave the current form alone: env override set (hand wins),
    planner off, or NO measured evidence — ops/trees' set_tree_scan is
    also a programmatic hand lever (runtime A/B runs flip it without
    the env var), so only a MEASURED route preference may move the
    form; a cold-corpus prior must not reverse the lever. models/trees
    applies a non-None answer via set_tree_scan before each fused
    fit."""
    if _ENV_FOR["tree_scan"] in os.environ:
        _note_override("tree_scan", _ENV_FOR["tree_scan"],
                       os.environ[_ENV_FOR["tree_scan"]].strip())
        return None
    if not plan_enabled():
        return None

    # the decision is deliberately SHAPE-FREE (unit-cost comparison
    # over all measured records, one stable answer per corpus): a
    # per-shape answer could flip between the depth-2 and depth-6
    # configs of ONE grid sweep, and every flip clears the fused-fit
    # jit caches — recompiling mid-sweep costs more than any per-shape
    # gain the growth form could buy
    decision = _decide("tree_scan", _tree_scan_compute)
    if decision.source != "measured":
        return None
    return bool(decision.value)


def _tree_scan_compute(model: CostModel) -> PlanDecision:
    route, source, alts = model.choose_route(
        "tree_fit", ("scan", "unrolled"),
        "scan" if HAND_DEFAULTS["tree_scan"] else "unrolled", {})
    return PlanDecision(name="tree_scan", value=(route == "scan"),
                        source=source, alternatives=alts)


def grid_fuse_enabled(n_rows: int = 0, n_feat: int = 0, n_folds: int = 0,
                      n_grids: int = 0, depth: int = 0,
                      n_bins: int = 0, n_shards: int = 1) -> bool:
    """Config-fused sweep route on/off for this sweep shape —
    validators' fused-group gate. Env TMOG_GRID_FUSE wins; otherwise
    fused turns on only when measured faster AND the planned out-block
    clears the compile knee. Cold corpus -> off (today's opt-in).
    ``n_shards`` is the mesh batch-axis size: the chunk planner's lane
    budget scales with it, so the knee must judge the sharded chunk's
    block, not the single-device one."""
    return bool(_grid_fuse_decision(n_rows, n_feat, n_folds, n_grids,
                                    depth, n_bins, n_shards).value)


def _grid_fuse_decision(n_rows: int, n_feat: int, n_folds: int,
                        n_grids: int, depth: int, n_bins: int,
                        n_shards: int) -> PlanDecision:
    shape = {"rows": float(n_rows), "feat": float(n_feat),
             "lanes": float(max(n_folds, 1) * max(n_grids, 1)),
             "depth": float(depth)}

    def compute(model: CostModel) -> PlanDecision:
        out_mb = _planned_out_block_mb(n_feat, n_bins, n_folds,
                                       n_grids, depth, n_shards)
        on, source, info = model.decide_grid_fuse(shape, out_mb)
        return PlanDecision(name="grid_fuse", value=on, source=source,
                            alternatives=info.get("alternatives", {}),
                            reason=str({k: v for k, v in info.items()
                                        if k != "alternatives"}))
    return _decide("grid_fuse", compute,
                   cache_key=(n_rows, n_feat, n_folds, n_grids,
                              depth, n_bins, n_shards))


def _planned_out_block_mb(n_feat: int, n_bins: int, n_folds: int,
                          n_grids: int, depth: int,
                          n_shards: int = 1) -> float:
    """Fused out-block MB at the chunk plan_lane_chunk would pick for
    this shape — the quantity the compile knee judges. Bins are judged
    at ``n_bins + 1``, matching the fused fit's own call (the null
    bin), and the chunk at the caller's shard count — the knee is
    exponential, so judging a smaller block than the one actually
    compiled would let a shape slip past the budget."""
    if not (n_feat and n_folds and depth):
        return HAND_DEFAULTS["grid_fuse_out_mb"]
    from ..ops import pallas_hist
    bins = max(n_bins, 1) + 1
    chunk = pallas_hist.plan_lane_chunk(
        n_feat, bins, n_folds, max(n_grids, 1), depth,
        n_shards=max(int(n_shards), 1))
    if chunk <= 0:
        return HAND_DEFAULTS["grid_fuse_out_mb"]
    plan = pallas_hist.plan_fused_hist(n_feat, bins, chunk * n_folds,
                                       depth)
    return plan.out_bytes / 1e6


# -- the Plan objects --------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FitPlan:
    """Every fit-time decision for one sweep shape, with provenance."""

    backend: str
    shape: Mapping[str, float]
    decisions: Mapping[str, PlanDecision]

    def __getattr__(self, name: str) -> Any:
        d = self.decisions.get(name)
        if d is None:
            raise AttributeError(name)
        return d.value

    def to_json(self) -> Dict[str, Any]:
        return {
            "backend": self.backend,
            "shape": dict(self.shape),
            "decisions": {
                n: {"value": d.value, "source": d.source,
                    **({"reason": d.reason} if d.reason else {}),
                    **({"alternatives": {str(k): v for k, v
                                         in d.alternatives.items()}}
                       if d.alternatives else {})}
                for n, d in self.decisions.items()},
        }


@dataclasses.dataclass(frozen=True)
class ServePlan:
    """Serving-side plan: the bucket ladder + its floor decision."""

    backend: str
    max_batch: int
    buckets: Tuple[int, ...]
    decisions: Mapping[str, PlanDecision]

    def to_json(self) -> Dict[str, Any]:
        return {"backend": self.backend, "max_batch": self.max_batch,
                "buckets": list(self.buckets),
                "decisions": {
                    n: {"value": d.value, "source": d.source}
                    for n, d in self.decisions.items()}}


def _log_plan(kind: str, doc: Dict[str, Any]) -> None:
    """Emit ONE plan_chosen event per distinct plan per process (plans
    resolve per sweep/tile — re-logging identical choices would flood
    the log without adding information)."""
    import json as _json
    sig = _json.dumps(doc, sort_keys=True, default=str)
    with _lock:
        if sig in _plans_logged:
            return
        _plans_logged.add(sig)
    try:
        from ..utils.metrics import collector
        collector.event("plan_chosen", plan=kind, **doc)
    except Exception:
        pass


def plan_fit(n_rows: int, n_feat: int, *, n_folds: int = 1,
             n_grids: int = 1, depth: int = 0,
             n_bins: int = 0, n_shards: int = 1) -> FitPlan:
    """Resolve every fit-time decision for one sweep shape. Cold corpus
    (or TMOG_PLAN=0) reproduces the hand plan bit for bit; explicitly
    set TMOG_* vars override individual decisions. ``n_shards`` is the
    mesh batch-axis size — the grid-fuse knee judges the sharded
    chunk's out-block, so a mesh caller must pass it or the reported
    plan can disagree with the gate the sweep actually used."""
    lanes = max(n_folds, 1) * max(n_grids, 1)
    backend = _backend()
    hbm_lanes_dec, out_mb_dec = _caps_decisions()
    decisions: Dict[str, PlanDecision] = {}

    decisions["glm_streamed_min_rows"] = _min_rows_decision(n_feat,
                                                            lanes)
    env_scan = _ENV_FOR["tree_scan"] in os.environ
    ts = planned_tree_scan()
    decisions["tree_scan"] = PlanDecision(
        name="tree_scan",
        value=_env_override("tree_scan").value if env_scan
        else (HAND_DEFAULTS["tree_scan"] if ts is None else ts),
        source="env" if env_scan
        else ("off" if not plan_enabled()
              else ("prior" if ts is None else "measured")))
    decisions["grid_fuse"] = _grid_fuse_decision(
        n_rows, n_feat, n_folds, n_grids, depth, n_bins, n_shards)
    decisions["grid_fuse_hbm_lanes"] = hbm_lanes_dec
    decisions["grid_fuse_out_mb"] = out_mb_dec
    decisions["tile_mb"] = _decide(
        "tile_mb", _value_decision("tile_mb", "tileplane_tile"))
    decisions["stats_tile_rows"] = _decide(
        "stats_tile_rows",
        _value_decision("stats_tile_rows", "stats_tile"))
    decisions["score_tile_rows"] = _decide(
        "score_tile_rows",
        _value_decision("score_tile_rows", "score_tile"))
    decisions["glm_bucket_floor"] = _decide(
        "glm_bucket_floor",
        _value_decision("glm_bucket_floor", "glm_bucket"))
    decisions["tile_prefetch"] = _decide("tile_prefetch",
                                         _compute_tile_prefetch)
    decisions["ingest_workers"] = _decide(
        "ingest_workers",
        _value_decision("ingest_workers", "ingest_parse"))
    shape = {"rows": float(n_rows), "feat": float(n_feat),
             "folds": float(n_folds), "grids": float(n_grids),
             "depth": float(depth), "bins": float(n_bins),
             "shards": float(max(int(n_shards), 1))}
    plan = FitPlan(backend=backend, shape=shape, decisions=decisions)
    _log_plan("fit", {"backend": backend, "shape": shape,
                      "values": {n: d.value
                                 for n, d in decisions.items()},
                      "sources": {n: d.source
                                  for n, d in decisions.items()}})
    return plan


def plan_serving(max_batch: int) -> ServePlan:
    """Resolve the serving bucket ladder for a max batch size. Cold
    corpus -> exactly serve/engine.bucket_ladder's hand ladder (floor
    8); a measured corpus may move the floor rung."""
    floor_dec = _decide(
        "serve_bucket_floor",
        _value_decision("serve_bucket_floor", "serve_bucket"))
    floor = int(floor_dec.value)
    from ..serve.engine import bucket_ladder
    buckets = bucket_ladder(max_batch, floor=floor)
    backend = _backend()
    plan = ServePlan(backend=backend, max_batch=int(max_batch),
                     buckets=buckets,
                     decisions={"serve_bucket_floor": floor_dec})
    _log_plan("serving", {"backend": backend,
                          "max_batch": int(max_batch),
                          "buckets": list(buckets),
                          "sources": {"serve_bucket_floor":
                                      floor_dec.source}})
    return plan
