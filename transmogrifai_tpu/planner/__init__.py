"""Plan-time autotuning: a measured cost model that picks routes and
tile/lane/ladder knobs per shape (docs/planning.md).

The repo's hot-path route decisions were hand-set constants or env
knobs — ``TMOG_TILE_MB``, ``TMOG_GRID_FUSE`` (+ its lane/out-block
caps), ``TMOG_STATS_TILE_ROWS``/``TMOG_SCORE_TILE_ROWS``, the
``STREAMED_SWEEP_MIN_ROWS`` GLM route floor, the power-of-two bucket
ladders — while BENCH_TPU_R5 measured ~3% GLM MFU on a 197 TFLOP/s
chip: the gap is plan quality, not kernel quality. This package builds
"A Learned Performance Model for TPUs" (arxiv 2008.01040) in
miniature:

* :mod:`corpus` — a persistent, append-only JSONL calibration corpus of
  (backend, family, shape, route, knobs) -> (wall, compile wall, bytes,
  work) records, harvested from the TraceTree span artifacts every
  traced fit/bench/ci run already exports, with dedup'd merge so
  corpora from different runs and boxes compose per backend.
* :mod:`model` — the cost model: analytic roofline priors (delegating
  to the kernels' own traffic models plus a compile-cost term fit to
  the ``tpu_fuse_compile_knee`` measurements) blended with
  nearest-shape measured observations in log-shape space. A cold
  corpus yields the pure prior, and the prior reproduces today's hand
  defaults — a cold planner is a no-op, not a regression.
* :mod:`plan` — ``plan_fit(...) -> FitPlan`` / ``plan_serving(...) ->
  ServePlan``: ONE choke point for every per-shape route decision.
  Call sites in validators/trees/tileplane/glm_sweep/serve consume the
  plan; an explicitly-set ``TMOG_*`` env var always overrides the
  planner (hand wins, logged as a ``plan_override`` event).
  ``TMOG_PLAN=0`` is the kill switch; ``TMOG_PLAN_CORPUS_DIR`` points
  at the corpus.
* :mod:`calibrate` — ``python -m transmogrifai_tpu plan
  calibrate|show|explain``: a bounded micro-bench grid that seeds a
  cold corpus on the current backend in minutes, and an explainer that
  prints each decision with predicted-vs-alternative costs.
"""
from .corpus import Corpus, PlanRecord, harvest_metrics_doc
from .model import (COMPILE_BUDGET_S, HAND_DEFAULTS, CostModel,
                    compile_knee_s, compile_ok)
from .plan import (FitPlan, PlanDecision, ServePlan, corpus_dir,
                   plan_enabled, plan_fit, plan_serving)

__all__ = [
    "COMPILE_BUDGET_S", "Corpus", "CostModel", "FitPlan", "HAND_DEFAULTS",
    "PlanDecision", "PlanRecord", "ServePlan", "compile_knee_s",
    "compile_ok", "corpus_dir", "harvest_metrics_doc", "plan_enabled",
    "plan_fit", "plan_serving",
]
