"""``plan calibrate`` — a bounded micro-bench grid that seeds the corpus.

A cold corpus pins every decision to the hand defaults; calibration
buys the planner its first measured evidence on the CURRENT backend in
minutes. Each family below times a small, deterministic workload per
candidate knob value (or per route) with honest device syncs
(``block_until_ready`` before every clock read), writing warm-wall
records — and cold/compile records where the compile cost IS the
decision input (tree growth forms, the fused sweep).

The workloads are the repo's own kernels where that is cheap (the
streamed GLM round driver, the fused tree fit) and tiny shape-faithful
proxies where a real run would blow the minutes budget (the tileplane
copy/reduce loop, bucketized scoring). Every record is labeled
``src="calibrate"``; harvested hardware spans land beside them and the
model blends both.

Budget discipline: families run in priority order and each checks the
remaining wall budget before starting — a tight budget yields a
partial (still useful) corpus, never an overrun.
"""
from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .corpus import Corpus, PlanRecord
from .model import CANDIDATES
from .plan import corpus_dir as _default_corpus_dir

_SEED = 20260803


def _records_for_values(family: str, backend: str, values, measure,
                        shape: Dict[str, float], work: float
                        ) -> List[PlanRecord]:
    out = []
    for v in values:
        wall = measure(v)
        if wall is None:
            continue
        out.append(PlanRecord(
            family=family, backend=backend, knobs={"value": v},
            shape=dict(shape), wall_s=float(wall), work=float(work),
            src="calibrate"))
    return out


def _cal_tileplane_tile(backend: str, scale: float) -> List[PlanRecord]:
    """Host->device tile copy + reduce per TMOG_TILE_MB candidate over a
    fixed total byte count — the tileplane's per-tile cost shape."""
    import jax
    import jax.numpy as jnp

    row_bytes = 256 * 4                        # 1 KB/row, 64 MB total
    total_rows = max(int((1 << 16) * scale), 1024)
    rng = np.random.default_rng(_SEED)
    host = rng.normal(size=(total_rows, 256)).astype(np.float32)

    @jax.jit
    def reduce_tile(t):
        return jnp.sum(t)

    def measure(tile_mb: int) -> Optional[float]:
        tile_rows = max((int(tile_mb) << 20) // row_bytes, 256)
        # warm the program shapes first so the measured pass is copies
        # + dispatch, not compiles
        for start in range(0, total_rows, tile_rows):
            jax.block_until_ready(reduce_tile(
                jnp.asarray(host[start:start + tile_rows])))
        t0 = time.perf_counter()
        acc = []
        for start in range(0, total_rows, tile_rows):
            acc.append(reduce_tile(
                jnp.asarray(host[start:start + tile_rows])))
        jax.block_until_ready(acc)
        return time.perf_counter() - t0

    return _records_for_values(
        "tileplane_tile", backend, CANDIDATES["tile_mb"], measure,
        {"rows": float(total_rows), "feat": 256.0},
        work=float(total_rows * row_bytes))


def _cal_tile_rows(family: str, backend: str, candidates, n_feat: int,
                   total_rows: int, step_builder) -> List[PlanRecord]:
    """Shared fixed-tile-shape pass timer for the stats/score tile-row
    knobs: one jitted per-tile program per candidate shape, warmed,
    then one full measured pass over the same total row count."""
    import jax

    rng = np.random.default_rng(_SEED)
    host = rng.normal(size=(total_rows, n_feat)).astype(np.float32)

    def measure(tile_rows: int) -> Optional[float]:
        tile_rows = int(tile_rows)
        if tile_rows > total_rows:
            return None
        step = step_builder()
        import jax.numpy as jnp
        tile0 = jnp.asarray(host[:tile_rows])
        jax.block_until_ready(step(tile0))  # compile outside the clock
        t0 = time.perf_counter()
        outs = []
        for start in range(0, total_rows - tile_rows + 1, tile_rows):
            outs.append(step(jnp.asarray(host[start:start + tile_rows])))
        jax.block_until_ready(outs)
        return time.perf_counter() - t0

    return _records_for_values(
        family, backend, candidates, measure,
        {"rows": float(total_rows), "feat": float(n_feat)},
        work=float(total_rows))


def _cal_stats_tile(backend: str, scale: float) -> List[PlanRecord]:
    import jax
    import jax.numpy as jnp

    def build():
        @jax.jit
        def step(t):  # the stats engine's per-tile moment shape
            return jnp.sum(t, 0), jnp.sum(t * t, 0), jnp.sum(t > 0, 0)
        return step

    total = max(int((1 << 19) * scale), 1 << 16)
    return _cal_tile_rows("stats_tile", backend,
                          [c for c in CANDIDATES["stats_tile_rows"]],
                          16, total, build)


def _cal_score_tile(backend: str, scale: float) -> List[PlanRecord]:
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(_SEED + 1)
    wv = jnp.asarray(rng.normal(size=(64,)).astype(np.float32))

    def build():
        @jax.jit
        def step(t):  # a bulk-scoring stage program's shape
            return jax.nn.sigmoid(t @ wv[:t.shape[1]])
        return step

    total = max(int((1 << 17) * scale), 1 << 14)
    return _cal_tile_rows("score_tile", backend,
                          [c for c in CANDIDATES["score_tile_rows"]],
                          64, total, build)


def _cal_glm_routes(backend: str, scale: float) -> List[PlanRecord]:
    """The real streamed round driver vs a vmapped per-lane IRLS fit at
    two row scales — the evidence behind the streamed-vs-materialized
    crossover."""
    import jax
    import jax.numpy as jnp
    from ..ops import glm as G
    from ..ops import glm_sweep as GS

    d, folds = 16, 2
    regs = np.asarray([1e-3, 1e-2, 1e-1, 0.3], np.float32)
    alphas = np.zeros_like(regs)
    lanes = folds * len(regs)
    out: List[PlanRecord] = []
    for rows in (max(int(20_000 * scale), 2_000),
                 max(int(60_000 * scale), 6_000)):
        rng = np.random.default_rng(_SEED + rows)
        Xd = jnp.asarray(rng.normal(size=(rows, d)).astype(np.float32))
        yd = jnp.asarray(
            (rng.uniform(size=rows) < 0.5).astype(np.float32))
        masks = (rng.integers(0, folds, size=rows)[None, :]
                 != np.arange(folds)[:, None]).astype(np.float32)
        shape = {"rows": float(rows), "feat": float(d),
                 "lanes": float(lanes)}
        work = float(rows) * d * lanes

        # calibration compiles one program per measured shape ON PURPOSE
        # (the lambda closes over this shape's Xd/yd) and the warmup call
        # below keeps the compile out of the clocked window
        # tmoglint: disable=TRC001  per-shape compile IS the measurement
        vfit = jax.jit(jax.vmap(
            lambda wl, r: G.fit_logistic(Xd, yd, wl, r, 0.0,
                                         max_iter=10),
            in_axes=(0, 0)))
        w_lanes = jnp.asarray(
            np.repeat(masks, len(regs), axis=0))       # [lanes, rows]
        r_lanes = jnp.asarray(np.tile(regs, folds))
        jax.block_until_ready(vfit(w_lanes, r_lanes))  # compile
        t0 = time.perf_counter()
        jax.block_until_ready(vfit(w_lanes, r_lanes))
        out.append(PlanRecord(
            family="glm_sweep", backend=backend, route="vmapped",
            shape=shape, wall_s=time.perf_counter() - t0, work=work,
            src="calibrate"))

        def run_streamed():
            # returns host arrays: the call is device-synced by its own
            # final fetch, so the clock reads below are honest
            return GS.sweep_glm_streamed_rounds(
                Xd, yd, jnp.ones(rows, jnp.float32), jnp.asarray(masks),
                regs, alphas, loss="logistic", max_iter=10)
        B, b0, _info = run_streamed()             # compile + warm caches
        jax.block_until_ready((jnp.asarray(B), jnp.asarray(b0)))
        t0 = time.perf_counter()
        B, b0, _info = run_streamed()
        jax.block_until_ready((jnp.asarray(B), jnp.asarray(b0)))
        out.append(PlanRecord(
            family="glm_sweep", backend=backend, route="streamed",
            shape=shape, wall_s=time.perf_counter() - t0, work=work,
            src="calibrate"))
    return out


def _cal_tree_routes(backend: str, scale: float) -> List[PlanRecord]:
    """Scan-vs-unrolled growth form AND grid-fused-vs-per-config lane
    batching on the real fused fit, with compile walls recorded from
    the cold calls (the knee term's measured companion)."""
    import jax
    import jax.numpy as jnp
    from ..ops import trees as T

    rows = max(int(20_000 * scale), 2_000)
    F, bins, depth, rounds = 16, 16, 5, 2
    rng = np.random.default_rng(_SEED + 7)
    Xb = jnp.asarray(rng.integers(0, bins + 1, size=(rows, F)), jnp.int8)
    y = jnp.asarray((rng.uniform(size=rows) < 0.4), jnp.float32)
    key = jax.random.PRNGKey(0)

    def fit(lanes: int):
        W = jnp.asarray(
            (rng.integers(0, 2, size=(lanes, rows)) > 0), jnp.float32)

        def run():
            return T.fit_gbt_folds(Xb, y, W, key, n_rounds=rounds,
                                   depth=depth, n_bins=bins)
        t0 = time.perf_counter()
        jax.block_until_ready(run())
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.block_until_ready(run())
        warm = time.perf_counter() - t0
        return warm, max(cold - warm, 0.0)

    out: List[PlanRecord] = []
    prev = T.tree_scan_enabled()
    try:
        for route, scan in (("scan", True), ("unrolled", False)):
            T.set_tree_scan(scan)
            warm, compile_s = fit(lanes=4)
            shape = {"rows": float(rows), "feat": float(F),
                     "lanes": 4.0, "depth": float(depth)}
            work = float(rows) * F * 4 * depth
            out.append(PlanRecord(
                family="tree_fit", backend=backend, route=route,
                shape=shape, wall_s=warm, work=work, src="calibrate"))
            out.append(PlanRecord(
                family="tree_fit", backend=backend, route=route,
                shape=shape, compile_s=compile_s, work=work, cold=True,
                src="calibrate"))
    finally:
        T.set_tree_scan(prev)

    # grid fusion: 4 configs x 2 folds as ONE 8-lane program vs 4
    # sequential 2-lane programs (identical total work)
    warm8, compile8 = fit(lanes=8)
    t_seq = 0.0
    for _ in range(4):
        warm2, _ = fit(lanes=2)
        t_seq += warm2
    shape = {"rows": float(rows), "feat": float(F), "lanes": 8.0,
             "depth": float(depth)}
    work = float(rows) * F * 8 * depth
    out.append(PlanRecord(
        family="tree_sweep", backend=backend, route="grid_fused",
        shape=shape, wall_s=warm8, work=work, src="calibrate"))
    out.append(PlanRecord(
        family="tree_sweep", backend=backend, route="grid_fused",
        shape=shape, compile_s=compile8, work=work, cold=True,
        src="calibrate"))
    out.append(PlanRecord(
        family="tree_sweep", backend=backend, route="per_config",
        shape=shape, wall_s=t_seq, work=work, src="calibrate"))
    return out


def _expected_ladder_cost(walls: Dict[int, float], floor: int,
                          top: int, req_sizes) -> float:
    """Expected per-request wall under a power-of-two ladder with this
    floor: each request pays the smallest rung >= its size."""
    def rung(s: int) -> int:
        if s <= 1:
            return 1
        b = floor
        while b < s and b < top:
            b *= 2
        return b
    return float(np.mean([walls[rung(s)] for s in req_sizes]))


def _cal_bucket_floors(backend: str, scale: float) -> List[PlanRecord]:
    """Bucketized dispatch walls -> expected per-request cost per floor
    candidate, for BOTH power-of-two ladders (the serving bucket ladder
    and the GLM lane-retirement compaction ladder)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(_SEED + 11)
    wv = jnp.asarray(rng.normal(size=(64, 1)).astype(np.float32))
    sizes = [1, 2, 4, 8, 16, 32]

    @jax.jit
    def score(t):
        return jax.nn.sigmoid(t @ wv)

    walls: Dict[int, float] = {}
    for s in sizes:
        batch = jnp.asarray(rng.normal(size=(s, 64)).astype(np.float32))
        jax.block_until_ready(score(batch))  # compile outside the clock
        reps = 50
        t0 = time.perf_counter()
        outs = [score(batch) for _ in range(reps)]
        jax.block_until_ready(outs)
        walls[s] = (time.perf_counter() - t0) / reps

    out: List[PlanRecord] = []
    req = rng.integers(1, 9, size=256)     # serving: small requests
    for floor in CANDIDATES["serve_bucket_floor"]:
        out.append(PlanRecord(
            family="serve_bucket", backend=backend,
            knobs={"value": int(floor)},
            shape={"max_batch": 32.0},
            wall_s=_expected_ladder_cost(walls, int(floor), 32, req),
            work=1.0, src="calibrate"))
    # GLM lane retirement: active-lane counts decay geometrically
    decay = [32, 17, 9, 4, 2, 1]
    for floor in CANDIDATES["glm_bucket_floor"]:
        cost = sum(_expected_ladder_cost(walls, int(floor), 32, [a])
                   for a in decay)
        out.append(PlanRecord(
            family="glm_bucket", backend=backend,
            knobs={"value": int(floor)}, shape={"lanes": 32.0},
            wall_s=cost, work=1.0, src="calibrate"))
    return out


def _cal_grid_caps(backend: str, scale: float) -> List[PlanRecord]:
    """Measured walls for the fused-sweep chunk caps on the repo's own
    route+hist pass: lane-chunk size (family ``tree_sweep_lanes`` — the
    TMOG_GRID_FUSE_HBM_LANES candidates, one fixed lane total processed
    in candidate-sized chunks, so fewer bigger passes race more smaller
    ones) and out-block size (family ``tree_sweep_out`` — node counts
    chosen so the fused histogram block lands near each candidate MB).
    These are the records that let ``planned_grid_fuse_caps`` leave its
    priors; the out-MB argmin is still knee-filtered at plan time, so a
    fast-measured 16MB block can never bust the compile budget."""
    import jax
    import jax.numpy as jnp
    from ..ops import pallas_hist as PH

    rows = max(int(20_000 * scale), 2_000)
    F, B = 16, 17
    rng = np.random.default_rng(_SEED + 13)
    Xb_t = jnp.asarray(rng.integers(0, B, size=(F, rows)), jnp.int8)

    def pass_wall(lanes: int, n_nodes: int) -> float:
        pay = jnp.asarray(
            rng.normal(size=(2 * lanes, rows)).astype(np.float32))
        node = jnp.asarray(
            rng.integers(0, n_nodes, size=(lanes, rows))
            .astype(np.float32))
        f_lvl = jnp.asarray(
            rng.integers(0, F, size=(lanes, n_nodes)), jnp.int32)
        t_lvl = jnp.full((lanes, n_nodes), B // 2, jnp.int32)
        m_lvl = jnp.zeros((lanes, n_nodes), jnp.int32)

        def one():
            return PH.route_hist(Xb_t, pay, node, f_lvl, t_lvl, m_lvl,
                                 n_nodes=n_nodes, n_bins=B,
                                 allow_bf16=True, derive_count=True)
        jax.block_until_ready(one())  # compile outside the clock
        t0 = time.perf_counter()
        jax.block_until_ready(one())
        return time.perf_counter() - t0

    out: List[PlanRecord] = []
    # the lane pool must be at least the largest candidate or every
    # chunk degenerates to the same one-pass program and the argmin
    # would select on timer noise alone
    total_lanes = max(CANDIDATES["grid_fuse_hbm_lanes"])
    for cand in CANDIDATES["grid_fuse_hbm_lanes"]:
        chunk = min(int(cand), total_lanes)
        passes = -(-total_lanes // chunk)
        out.append(PlanRecord(
            family="tree_sweep_lanes", backend=backend,
            knobs={"value": int(cand)},
            shape={"rows": float(rows), "feat": float(F),
                   "lanes": float(total_lanes)},
            wall_s=pass_wall(chunk, 4) * passes,
            work=float(rows) * total_lanes, src="calibrate"))
    lanes = 8
    per_node_bytes = lanes * 3 * B * 4  # the fused hist block row cost
    for cand in CANDIDATES["grid_fuse_out_mb"]:
        n_nodes = max(int((float(cand) * 1e6) // per_node_bytes), 2)
        out.append(PlanRecord(
            family="tree_sweep_out", backend=backend,
            knobs={"value": float(cand)},
            shape={"rows": float(rows), "feat": float(F),
                   "lanes": float(lanes), "nodes": float(n_nodes)},
            wall_s=pass_wall(lanes, n_nodes),
            work=float(rows) * lanes, src="calibrate"))
    return out


_FAMILIES: List = [
    ("tileplane_tile", _cal_tileplane_tile),
    ("stats_tile", _cal_stats_tile),
    ("score_tile", _cal_score_tile),
    ("bucket_floors", _cal_bucket_floors),
    ("glm_routes", _cal_glm_routes),
    ("tree_routes", _cal_tree_routes),
    ("grid_caps", _cal_grid_caps),
]


def run_calibration(corpus_path: Optional[str] = None, *,
                    budget_s: float = 180.0,
                    scale: float = 1.0) -> Dict[str, Any]:
    """Run every calibration family within the wall budget and append
    the records to the corpus. Families are fault-isolated: one failing
    micro-bench logs and skips, the rest still land. Returns the
    summary the CLI prints (and emits a ``plan_calibrated`` event)."""
    import jax

    t0 = time.perf_counter()
    backend = jax.default_backend()
    corpus = Corpus(corpus_path or _default_corpus_dir())
    counts: Dict[str, int] = {}
    errors: Dict[str, str] = {}
    for name, fn in _FAMILIES:
        # each family syncs its own measurements; this clock only
        # enforces the overall budget
        # tmoglint: disable=TPU005  budget clock, not a kernel wall
        if time.perf_counter() - t0 > budget_s:
            errors[name] = "skipped: budget"
            continue
        try:
            recs = fn(backend, scale)
            counts[name] = corpus.append(recs)
        except Exception as e:  # fault-isolated by contract
            errors[name] = f"{type(e).__name__}: {str(e)[:160]}"
    summary = {"backend": backend, "corpus": corpus.path,
               "records": counts,
               "total_records": sum(counts.values()),
               # tmoglint: disable=TPU005  budget clock, not a kernel wall
               "wall_s": round(time.perf_counter() - t0, 2)}
    if errors:
        summary["errors"] = errors
    try:
        from ..utils.metrics import collector
        collector.event("plan_calibrated", backend=backend,
                        records=sum(counts.values()),
                        wall_seconds=summary["wall_s"])
    except Exception:
        pass
    return summary


# -- CLI (python -m transmogrifai_tpu plan ...) ------------------------------

def run_plan_cli(args) -> int:
    """Dispatch for the ``plan`` subcommand: calibrate | show |
    explain."""
    from . import plan as P
    path = args.corpus_dir or P.corpus_dir()
    if args.action == "calibrate":
        summary = run_calibration(path, budget_s=args.budget_s,
                                  scale=args.scale)
        print(json.dumps(summary, sort_keys=True))
        return 0
    if args.action == "show":
        print(json.dumps(Corpus(path).summary(), indent=2,
                         sort_keys=True))
        return 0
    # explain: resolve a plan for the given shape and print each
    # decision with its provenance and alternatives. The resolved path
    # OVERRIDES any pre-set TMOG_PLAN_CORPUS_DIR: an explicit
    # --corpus-dir must be the corpus the printed decisions came from
    import os
    os.environ["TMOG_PLAN_CORPUS_DIR"] = path
    fit = P.plan_fit(n_rows=args.rows, n_feat=args.feat,
                     n_folds=args.folds, n_grids=args.grids,
                     depth=args.depth, n_bins=args.bins,
                     n_shards=getattr(args, "shards", 1))
    serving = P.plan_serving(args.max_batch)
    if args.json:
        print(json.dumps({"fit": fit.to_json(),
                          "serving": serving.to_json()}, sort_keys=True))
        return 0
    print(f"plan explain  backend={fit.backend}  corpus={path}")
    print(f"shape: rows={args.rows} feat={args.feat} folds={args.folds} "
          f"grids={args.grids} depth={args.depth} bins={args.bins}")
    print(f"{'decision':<24}{'value':>12}  {'source':<9} alternatives")
    for name, d in fit.decisions.items():
        alts = ", ".join(
            f"{k}={v:.3g}" if isinstance(v, float) else f"{k}=?"
            for k, v in list(d.alternatives.items())[:6]) or "-"
        print(f"{name:<24}{str(d.value):>12}  {d.source:<9} {alts}")
    d = serving.decisions["serve_bucket_floor"]
    print(f"{'serve_bucket_floor':<24}{str(d.value):>12}  {d.source:<9} "
          f"ladder={list(serving.buckets)}")
    return 0
