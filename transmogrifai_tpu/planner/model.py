"""The plan cost model: analytic priors blended with measured evidence.

Two ingredients, in strict priority order:

* **Priors.** The hand-tuned defaults that shipped every PR so far
  (``HAND_DEFAULTS`` — the same numbers the knobs' own modules carry)
  plus the analytic cost terms the kernels already publish: the HBM
  traffic models in ``ops/pallas_hist`` / ``ops/stats_engine`` and a
  **compile-cost knee term** fit to the ``tools/tpu_fuse_compile_knee``
  measurements (r5 session 2: ~75 s Mosaic compiles at the 8 MB fused
  out-block cap, 20+ minutes at a 16 MB block). A cold corpus yields
  exactly the priors, so a cold planner reproduces today's hand plan
  bit for bit.

* **Measurements.** Corpus records blend in as nearest-shape
  observations in log-shape space: a route/knob cost at a query shape
  is the median *unit* cost (wall per work unit) of the k nearest
  measured shapes, scaled by the query's analytic work. A knob
  candidate only beats the hand default when BOTH have been measured —
  one stray observation of an alternative can never outvote an
  unmeasured default.

Decisions are per (backend): TPU evidence never informs CPU plans and
vice versa (corpora are per-backend files for the same reason).
"""
from __future__ import annotations

import math
import statistics
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from .corpus import Corpus, PlanRecord

#: Today's hand plan — one row per retired hand knob / constant, each
#: matching the owning module's shipped default (docs/planning.md maps
#: every row to its planner decision). The cold-corpus no-op guarantee
#: is an equality test against this table.
HAND_DEFAULTS: Dict[str, Any] = {
    # automl/tuning/validators.STREAMED_SWEEP_MIN_ROWS
    "glm_streamed_min_rows": 200_000,
    # ops/trees TMOG_TREE_SCAN default (scan on)
    "tree_scan": True,
    # validators TMOG_GRID_FUSE default (opt-in because of the knee)
    "grid_fuse": False,
    # ops/pallas_hist TMOG_GRID_FUSE_HBM_LANES / _OUT_MB defaults
    "grid_fuse_hbm_lanes": 64,
    "grid_fuse_out_mb": 8.0,
    # parallel/tileplane TMOG_TILE_MB default
    "tile_mb": 32,
    # ops/stats_engine TMOG_STATS_TILE_ROWS default
    "stats_tile_rows": 1 << 18,
    # readers/streaming TMOG_SCORE_TILE_ROWS default
    "score_tile_rows": 1024,
    # ops/glm_sweep._BUCKET_MIN (lane-retirement compaction ladder floor)
    "glm_bucket_floor": 8,
    # serve/engine._BUCKET_FLOOR (serving bucket ladder floor)
    "serve_bucket_floor": 8,
    # parallel/tileplane TMOG_TILE_PREFETCH default (prefetch-ring
    # depth; 1 = the classic two-in-flight double buffering)
    "tile_prefetch": 1,
    # parallel/ingest TMOG_INGEST_WORKERS default (parse-worker pool)
    "ingest_workers": 1,
}

#: candidate grids the measured argmin searches over (the default is
#: always a member, so "default measured + candidate measured" is the
#: only way a knob moves)
CANDIDATES: Dict[str, Tuple] = {
    "tile_mb": (8, 16, 32, 64, 128),
    "stats_tile_rows": (1 << 15, 1 << 16, 1 << 17, 1 << 18, 1 << 19),
    "score_tile_rows": (256, 512, 1024, 2048, 4096),
    "glm_bucket_floor": (4, 8, 16),
    "serve_bucket_floor": (2, 4, 8),
    "grid_fuse_hbm_lanes": (32, 64, 128),
    "grid_fuse_out_mb": (2.0, 4.0, 8.0, 12.0, 16.0),
    "tile_prefetch": (1, 2, 3, 4),
    "ingest_workers": (1, 2, 4, 8),
}

#: Mosaic compile budget a planned program must clear; anything past it
#: is rejected at plan time instead of discovered 20 minutes into a
#: compile (the r5 failure mode that keeps TMOG_GRID_FUSE opt-in).
COMPILE_BUDGET_S = 180.0

_KNN = 3


def compile_knee_s(out_mb: float, backend: str = "tpu") -> float:
    """Predicted whole-program compile wall (seconds) vs the fused
    out-block size in MB.

    TPU: an exponential fit through the two anchors the knee harness
    measured — ~75 s at the 8 MB TMOG_GRID_FUSE_OUT_MB default cap and
    ~21 min at the 16 MB block of r5 session 2 (Mosaic's layout search
    explodes as the out block nears the scoped-VMEM boundary) —
    ``4.3 * exp(0.356 * out_mb)``. Other backends run plain XLA with no
    Mosaic layout search: compile cost is small and near-flat in the
    out-block size."""
    mb = max(float(out_mb), 0.0)
    if backend == "tpu":
        return 4.3 * math.exp(0.356 * mb)
    return 1.0 + 0.05 * mb


def compile_ok(out_mb: float, backend: str = "tpu",
               budget_s: float = COMPILE_BUDGET_S) -> bool:
    """Does the knee term clear the compile budget at this out-block
    size? The 16 MB shape r5 measured at 20+ minutes is rejected here
    at plan time (test-pinned)."""
    return compile_knee_s(out_mb, backend) <= budget_s


def _log_distance(a: Mapping[str, float], b: Mapping[str, float]) -> float:
    """Euclidean distance in log1p-shape space over the union of keys
    (a key one side lacks reads as 0 — absent geometry is small
    geometry, keeping sparse harvest records usable)."""
    keys = set(a) | set(b)
    if not keys:
        return 0.0
    return math.sqrt(sum(
        (math.log1p(max(float(a.get(k, 0.0)), 0.0))
         - math.log1p(max(float(b.get(k, 0.0)), 0.0))) ** 2
        for k in keys))


def _default_work(shape: Mapping[str, float]) -> float:
    """Fallback analytic work proxy: rows x feat x lanes x depth over
    whatever geometry the shape names (missing axes count 1)."""
    w = 1.0
    for k in ("rows", "feat", "lanes", "depth"):
        v = float(shape.get(k, 0.0) or 0.0)
        if v > 0:
            w *= v
    return max(w, 1.0)


class CostModel:
    """Measured-cost queries over one backend's corpus slice."""

    def __init__(self, corpus: Corpus, backend: str) -> None:
        self.backend = backend
        self._records = [r for r in corpus.load(backend)
                         if r.backend == backend]

    # -- raw access ---------------------------------------------------------
    def obs(self, family: str, route: Optional[str] = None,
            knob_value: Any = None, warm: bool = True
            ) -> List[PlanRecord]:
        out = []
        for r in self._records:
            if r.family != family:
                continue
            if route is not None and r.route != route:
                continue
            if knob_value is not None \
                    and r.knobs.get("value") != knob_value:
                continue
            if warm and r.wall_s <= 0.0:
                continue
            if not warm and r.compile_s <= 0.0:
                continue
            out.append(r)
        return out

    @staticmethod
    def _unit_cost(r: PlanRecord,
                   work_fn: Callable[[Mapping[str, float]], float]
                   ) -> float:
        work = r.work if r.work > 0 else work_fn(r.shape)
        return r.wall_s / max(work, 1.0)

    def predict_wall(self, family: str, route: str,
                     shape: Mapping[str, float],
                     work_fn: Optional[Callable] = None
                     ) -> Optional[float]:
        """Predicted warm wall at ``shape``: median unit cost of the k
        nearest measured shapes x the query's analytic work. None when
        the (family, route) has no warm observations — the caller must
        then fall back to its prior."""
        work_fn = work_fn or _default_work
        recs = self.obs(family, route)
        if not recs:
            return None
        recs.sort(key=lambda r: _log_distance(r.shape, shape))
        unit = statistics.median(
            self._unit_cost(r, work_fn) for r in recs[:_KNN])
        return unit * max(work_fn(shape), 1.0)

    def predict_compile(self, family: str, route: str,
                        shape: Mapping[str, float]) -> float:
        """Predicted compile wall: the nearest cold observations when
        any exist, else 0 (the knee term is applied separately where an
        out-block size is known)."""
        recs = self.obs(family, route, warm=False)
        if not recs:
            return 0.0
        recs.sort(key=lambda r: _log_distance(r.shape, shape))
        return statistics.median(r.compile_s for r in recs[:_KNN])

    # -- decisions ----------------------------------------------------------
    def choose_value(self, name: str, family: str, default: Any,
                     candidates: Optional[Sequence] = None
                     ) -> Tuple[Any, str, Dict[Any, Optional[float]]]:
        """Measured argmin over a knob's candidate grid.

        Returns ``(value, source, alternatives)`` where alternatives
        maps candidate -> median unit cost (None = unmeasured). The
        default only loses to a candidate when BOTH are measured
        (source "measured"); a cold family keeps the default
        ("prior"). The comparison is PER HOST: absolute unit costs are
        not comparable across machines, so a candidate is judged by its
        median cost RATIO to the default on hosts that measured both —
        a merged corpus where a fast box happened to measure one
        candidate and a slow box another must not move the knob on
        hardware identity."""
        candidates = list(candidates if candidates is not None
                          else CANDIDATES.get(name, (default,)))
        if default not in candidates:
            candidates.append(default)
        alts: Dict[Any, Optional[float]] = {}
        by_host: Dict[str, Dict[Any, float]] = {}
        for cand in candidates:
            recs = self.obs(family, knob_value=cand)
            alts[cand] = (statistics.median(
                self._unit_cost(r, _default_work) for r in recs)
                if recs else None)
            hosts: Dict[str, List[float]] = {}
            for r in recs:
                hosts.setdefault(r.host, []).append(
                    self._unit_cost(r, _default_work))
            for host, costs in hosts.items():
                by_host.setdefault(host, {})[cand] = \
                    statistics.median(costs)
        ratios: Dict[Any, float] = {}
        for cand in candidates:
            if cand == default:
                continue
            rs = [cmap[cand] / max(cmap[default], 1e-12)
                  for cmap in by_host.values()
                  if cand in cmap and default in cmap]
            if rs:
                ratios[cand] = statistics.median(rs)
        winners = {c: r for c, r in ratios.items() if r < 1.0}
        if not winners:
            return default, "prior", alts
        best = min(winners, key=lambda c: winners[c])
        return best, "measured", alts

    def feed_compute_ratio(self) -> Optional[float]:
        """Median (tile_parse + tile_copy) / tile_compute unit-cost
        ratio over the harvested tileplane tile spans — how many times
        slower the FEED side (host parse + H2D copy) runs than the
        device step. The prefetch-depth decision sizes the ring from
        this: a feed k x slower than compute needs ~k tiles in flight
        before the device stops starving.

        Per host, like choose_value: absolute unit costs are not
        comparable across machines, so the ratio is formed only on
        hosts that measured the compute side, and the cross-host median
        is returned. None when no host measured tile_compute, or no
        host measured any feed-side family — cold stays cold."""
        def per_host(family: str) -> Dict[str, float]:
            hosts: Dict[str, List[float]] = {}
            for r in self.obs(family):
                hosts.setdefault(r.host, []).append(
                    self._unit_cost(r, _default_work))
            return {h: statistics.median(v) for h, v in hosts.items()}

        compute = per_host("tileplane_compute")
        parse = per_host("ingest_parse")
        copy = per_host("tileplane_copy")
        ratios = []
        for host, c in compute.items():
            if c <= 0:
                continue
            feed = parse.get(host, 0.0) + copy.get(host, 0.0)
            if feed > 0:
                ratios.append(feed / c)
        return statistics.median(ratios) if ratios else None

    def choose_route(self, family: str, routes: Sequence[str],
                     default: str, shape: Mapping[str, float],
                     work_fn: Optional[Callable] = None,
                     amortize: int = 1
                     ) -> Tuple[str, str, Dict[str, Optional[float]]]:
        """Measured argmin over route labels at a shape, charging each
        route its predicted compile wall amortized over ``amortize``
        expected reuses. Every route must be measured or the default
        holds (a route we have never run is not evidence it is slow —
        it is absence of evidence)."""
        alts: Dict[str, Optional[float]] = {}
        for route in routes:
            wall = self.predict_wall(family, route, shape, work_fn)
            if wall is None:
                alts[route] = None
                continue
            alts[route] = wall + self.predict_compile(
                family, route, shape) / max(int(amortize), 1)
        if any(v is None for v in alts.values()):
            return default, "prior", alts
        best = min(alts, key=lambda r: alts[r])  # type: ignore[arg-type]
        return best, ("prior" if best == default else "measured"), alts

    def crossover_rows(self, family: str, small_route: str,
                       big_route: str, shape: Mapping[str, float],
                       default_rows: int,
                       lo: int = 1_000, hi: int = 50_000_000
                       ) -> Tuple[int, str]:
        """Row threshold above which ``big_route`` (the higher-capacity
        kernel) beats ``small_route``, scanned over a geometric row
        grid with the rest of ``shape`` held fixed.

        Monotone by construction: the returned threshold is the
        smallest grid point from which big_route wins at EVERY larger
        grid point, so more rows can never select the smaller-capacity
        route once the threshold is crossed. The scan is bounded to the
        MEASURED row range (min observed row count to 4x the max): the
        kNN unit cost is constant beyond the nearest measurements, so
        an unbounded scan would extrapolate a flat "win" all the way
        down to the grid floor — a route can never be selected at row
        counts smaller than any shape it was actually measured at.
        Falls back to the hand default when either route is unmeasured
        or no consistent crossover exists, and clamps a measured
        threshold to [lo x 4, default x 16] so a few noisy points
        cannot push the route to an absurd extreme."""
        small_obs = self.obs(family, small_route)
        big_obs = self.obs(family, big_route)
        if not (small_obs and big_obs):
            return default_rows, "prior"
        measured = [r.shape.get("rows", 0.0)
                    for r in small_obs + big_obs
                    if r.shape.get("rows", 0.0) > 0]
        if not measured:
            return default_rows, "prior"
        r_lo = max(lo, int(min(measured)))
        r_hi = min(hi, int(max(measured)) * 4)
        grid: List[int] = []
        r = r_lo
        while r <= r_hi:
            grid.append(r)
            r *= 2
        wins = []
        for rows in grid:
            q = dict(shape)
            q["rows"] = float(rows)
            big = self.predict_wall(family, big_route, q)
            small = self.predict_wall(family, small_route, q)
            wins.append(big is not None and small is not None
                        and big <= small)
        threshold = None
        for i, rows in enumerate(grid):
            if all(wins[i:]):
                threshold = rows
                break
        if threshold is None:
            return default_rows, "prior"
        threshold = max(lo * 4, min(threshold, default_rows * 16))
        return threshold, ("prior" if threshold == default_rows
                           else "measured")

    def decide_grid_fuse(self, shape: Mapping[str, float],
                         out_mb: float) -> Tuple[bool, str, Dict]:
        """Fold x config fused sweep on/off: fused must be MEASURED
        faster than the per-config route at the nearest shape AND its
        planned out-block must clear the compile knee (predicted from
        the knee prior and any measured cold compiles, whichever is
        worse). Cold corpus -> off, exactly today's opt-in default."""
        route, source, alts = self.choose_route(
            "tree_sweep", ("grid_fused", "per_config"), "per_config",
            shape)
        knee = max(compile_knee_s(out_mb, self.backend),
                   self.predict_compile("tree_sweep", "grid_fused",
                                        shape))
        info = {"alternatives": alts, "out_mb": out_mb,
                "predicted_compile_s": round(knee, 1)}
        if source == "prior":
            return HAND_DEFAULTS["grid_fuse"], "prior", info
        if route != "grid_fused":
            return False, "measured", info
        if knee > COMPILE_BUDGET_S:
            info["rejected"] = "compile_knee"
            return False, "measured", info
        return True, "measured", info
