"""The calibration corpus: append-only JSONL of measured plan evidence.

One record = one measured observation of one kernel family at one shape
under one route/knob setting: ``(backend, family, shape, route, knobs)
-> (wall_s, compile_s, bytes_hbm, work)``. Records come from three
sources (the ``src`` field): the bounded ``plan calibrate`` micro-bench
grid, harvested TraceTree span artifacts (the kernel-roofline spans
every traced fit/bench/ci run exports since PR 4), and future hardware
bench runs — every bench run makes the planner smarter.

Storage is one ``corpus-<backend>.jsonl`` per backend under the corpus
dir (``TMOG_PLAN_CORPUS_DIR``), append-only, with content-hash dedupe
so merging corpora from different runs and boxes composes: replaying
the same bench artifact twice adds nothing, and two boxes' CPU corpora
union cleanly while their TPU corpora stay separate files. Corrupt
lines (torn tails from a killed run, hand edits) are skipped on load,
never fatal — a broken corpus must degrade the planner to its priors,
not break a fit.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Dict, Iterable, List, Mapping, Optional

RECORD_V = 1


def _hostname() -> str:
    try:
        import platform
        return platform.node() or "unknown"
    except Exception:
        return "unknown"

#: span-name -> (family, route) map for harvesting the kernel-roofline
#: spans traced runs export (utils/metrics collector.kernel). Families
#: match the calibration micro-bench families so harvested hardware
#: evidence and seeded CPU evidence feed the same decisions.
_SPAN_FAMILIES = {
    "tree_sweep_grid_fused": ("tree_sweep", "grid_fused"),
    "tree_sweep_grid_fused_sharded": ("tree_sweep", "grid_fused_sharded"),
    "tree_sweep_fold_fused": ("tree_fit", "fused"),
    "tree_sweep_per_config": ("tree_sweep", "per_config"),
    "stats_pass[fused]": ("stats_tile", "fused"),
    "stats_pass[streamed]": ("stats_tile", "streamed"),
    "stats_pass[sharded]": ("stats_tile", "sharded"),
}

#: tile-kind span names -> (family, route): the per-tile feed/compute
#: spans the tileplane and the sharded ingest engine emit. Harvested
#: AGGREGATED — per (name, label) sums over a whole pass — because one
#: traced pass emits hundreds of near-identical per-tile spans and the
#: planner only needs their unit costs; the tile_prefetch decision
#: derives its ring depth from these families' feed/compute ratio
#: (planner/model.feed_compute_ratio).
_TILE_SPAN_FAMILIES = {
    "tile_parse": ("ingest_parse", "parse"),
    "tile_copy": ("tileplane_copy", "copy"),
    "tile_compute": ("tileplane_compute", "compute"),
}


@dataclasses.dataclass(frozen=True)
class PlanRecord:
    """One measured observation. ``shape`` holds the numeric geometry
    (rows/feat/lanes/depth/...), ``knobs`` the knob values under test
    (e.g. ``{"value": 32}`` for a tile-MB candidate), ``work`` the
    normalizing unit count (bytes moved or rows processed) so walls
    compare across shapes as unit costs. ``cold`` marks a wall that
    includes jit trace + compile (only cold records inform the
    compile-cost term; warm records inform the run-cost term)."""

    family: str
    backend: str
    route: str = ""
    shape: Mapping[str, float] = dataclasses.field(default_factory=dict)
    knobs: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    wall_s: float = 0.0
    compile_s: float = 0.0
    bytes_hbm: float = 0.0
    work: float = 0.0
    cold: bool = False
    src: str = ""
    host: str = ""
    ts: float = 0.0

    def key(self) -> str:
        """Content hash for merge dedupe — everything but the timestamp
        and the source label (the same measurement replayed from the
        same artifact — or harvested twice under different src tags, as
        a traced bench run does — must not double-weight the model)."""
        doc = dataclasses.asdict(self)
        doc.pop("ts", None)
        doc.pop("src", None)
        blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def to_json(self) -> Dict[str, Any]:
        doc = dataclasses.asdict(self)
        doc["v"] = RECORD_V
        doc["shape"] = {k: float(v) for k, v in self.shape.items()}
        return doc

    @staticmethod
    def from_json(doc: Mapping[str, Any]) -> "PlanRecord":
        if not isinstance(doc, Mapping) or "family" not in doc \
                or "backend" not in doc:
            raise ValueError("not a plan record")
        return PlanRecord(
            family=str(doc["family"]), backend=str(doc["backend"]),
            route=str(doc.get("route", "")),
            shape={str(k): float(v)
                   for k, v in (doc.get("shape") or {}).items()},
            knobs=dict(doc.get("knobs") or {}),
            wall_s=float(doc.get("wall_s", 0.0)),
            compile_s=float(doc.get("compile_s", 0.0)),
            bytes_hbm=float(doc.get("bytes_hbm", 0.0)),
            work=float(doc.get("work", 0.0)),
            cold=bool(doc.get("cold", False)),
            src=str(doc.get("src", "")),
            host=str(doc.get("host", "")),
            ts=float(doc.get("ts", 0.0)))


class Corpus:
    """Per-backend JSONL record store under one directory."""

    def __init__(self, path: str) -> None:
        self.path = path

    def _file(self, backend: str) -> str:
        return os.path.join(self.path, f"corpus-{backend}.jsonl")

    def backends(self) -> List[str]:
        try:
            names = sorted(os.listdir(self.path))
        except OSError:
            return []
        return [n[len("corpus-"):-len(".jsonl")] for n in names
                if n.startswith("corpus-") and n.endswith(".jsonl")]

    def fingerprint(self) -> tuple:
        """Cheap change token (name, size, mtime per backend file) — the
        plan layer caches decisions against it, so an append or an
        external merge invalidates cached choices without re-reading the
        files on every knob lookup."""
        out = []
        for b in self.backends():
            try:
                st = os.stat(self._file(b))
                out.append((b, st.st_size, st.st_mtime_ns))
            except OSError:
                continue
        return tuple(out)

    def load(self, backend: Optional[str] = None) -> List[PlanRecord]:
        """All parseable records (one backend, or every backend file).
        Corrupt/torn/foreign lines are skipped — load never raises on
        file content."""
        out: List[PlanRecord] = []
        backends = [backend] if backend else self.backends()
        for b in backends:
            try:
                with open(self._file(b), "r", encoding="utf-8") as fh:
                    lines = fh.read().splitlines()
            except OSError:
                continue
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(PlanRecord.from_json(json.loads(line)))
                except (ValueError, TypeError, KeyError):
                    continue  # torn tail / hand edit: skip, never fatal
        return out

    def append(self, records: Iterable[PlanRecord]) -> int:
        """Append records, deduping by content hash against what is
        already stored (and within the batch). Returns the number of
        NEW records written."""
        by_backend: Dict[str, List[PlanRecord]] = {}
        for r in records:
            by_backend.setdefault(r.backend, []).append(r)
        if not by_backend:
            return 0
        os.makedirs(self.path, exist_ok=True)
        wrote = 0
        for backend, recs in by_backend.items():
            seen = {r.key() for r in self.load(backend)}
            fresh = []
            for r in recs:
                if not r.host:
                    # stamp the measuring machine: absolute unit costs
                    # are only comparable within one host, and the cost
                    # model's knob argmin groups by this field
                    r = dataclasses.replace(r, host=_hostname())
                k = r.key()
                if k in seen:
                    continue
                seen.add(k)
                if not r.ts:
                    r = dataclasses.replace(r, ts=round(time.time(), 3))
                fresh.append(r)
            if not fresh:
                continue
            with open(self._file(backend), "a", encoding="utf-8") as fh:
                for r in fresh:
                    fh.write(json.dumps(r.to_json(), sort_keys=True)
                             + "\n")
            wrote += len(fresh)
        return wrote

    def merge_from(self, other: "Corpus") -> int:
        """Fold another corpus dir in (per backend, dedup'd) — how
        corpora from different boxes/runs compose."""
        return self.append(other.load())

    def summary(self) -> Dict[str, Any]:
        """Record counts per (backend, family, route) for `plan show`."""
        counts: Dict[str, Dict[str, int]] = {}
        for r in self.load():
            fam = counts.setdefault(r.backend, {})
            key = f"{r.family}:{r.route}" if r.route else r.family
            fam[key] = fam.get(key, 0) + 1
        return {"path": self.path, "backends": counts,
                "total": sum(sum(f.values()) for f in counts.values())}


def harvest_metrics_doc(doc: Mapping[str, Any], backend: str,
                        src: str = "harvest") -> List[PlanRecord]:
    """Plan records from one saved AppMetrics JSON (the
    ``bench_stage_metrics.json`` / ``stage_metrics.json`` artifact a
    traced run writes — collector.save()).

    Reads the span tree's kernel spans (they carry the shape attrs the
    flat kernel_metrics list drops) and falls back to kernel_metrics
    when no span tree was exported. Unknown span names are skipped —
    harvesting an artifact from a newer/older repo version degrades to
    fewer records, never an error."""
    out: List[PlanRecord] = []
    spans = doc.get("spans")
    rows: List[Mapping[str, Any]] = []
    if isinstance(spans, list):
        rows = [s for s in spans if isinstance(s, dict)
                and s.get("kind") == "kernel"]
    if not rows:
        rows = [m for m in doc.get("kernel_metrics") or []
                if isinstance(m, dict)]
    for s in rows:
        name = str(s.get("name") or s.get("kernel") or "")
        fam_route = _SPAN_FAMILIES.get(name)
        if fam_route is None:
            continue
        family, route = fam_route
        attrs = s.get("attrs") or {}
        wall = float(s.get("duration_seconds")
                     or s.get("wall_seconds") or 0.0)
        if wall <= 0.0:
            continue
        cold = bool(attrs.get("cold", s.get("cold")) or False)
        shape = {}
        for k_attr, k_shape in (("n_rows", "rows"), ("rows", "rows"),
                                ("cols", "feat"), ("lanes", "lanes"),
                                ("depth", "depth"), ("tiles", "tiles"),
                                ("n_rounds", "rounds")):
            v = attrs.get(k_attr)
            if isinstance(v, (int, float)) and k_shape not in shape:
                shape[k_shape] = float(v)
        bytes_hbm = float(attrs.get("bytes_hbm", s.get("bytes_hbm"))
                          or 0.0)
        out.append(PlanRecord(
            family=family, backend=backend, route=route, shape=shape,
            wall_s=0.0 if cold else wall,
            compile_s=wall if cold else 0.0,
            bytes_hbm=bytes_hbm, work=bytes_hbm or shape.get("rows", 0.0),
            cold=cold, src=src))
    if isinstance(spans, list):
        out.extend(_harvest_tile_spans(spans, backend, src))
    return out


def _harvest_tile_spans(spans: List[Mapping[str, Any]], backend: str,
                        src: str) -> List[PlanRecord]:
    """One aggregate record per (tile-span name, pass label): summed
    wall over summed rows, i.e. the pass's unit cost for that pipeline
    stage, with the tile count in the shape. Per-tile harvesting would
    bloat the corpus by hundreds of records per traced pass while
    informing the exact same median."""
    agg: Dict[tuple, List[float]] = {}
    for s in spans:
        if not isinstance(s, dict) or s.get("kind") != "tile":
            continue
        name = str(s.get("name") or "")
        if name not in _TILE_SPAN_FAMILIES:
            continue
        wall = float(s.get("duration_seconds") or 0.0)
        if wall <= 0.0:
            continue
        attrs = s.get("attrs") or {}
        rows = attrs.get("rows")
        rows = float(rows) if isinstance(rows, (int, float)) else 0.0
        slot = agg.setdefault((name, str(attrs.get("label") or "")),
                              [0.0, 0.0, 0.0])
        slot[0] += wall
        slot[1] += rows
        slot[2] += 1.0
    out: List[PlanRecord] = []
    for (name, label), (wall, rows, tiles) in agg.items():
        if rows <= 0.0:
            continue
        family, route = _TILE_SPAN_FAMILIES[name]
        out.append(PlanRecord(
            family=family, backend=backend, route=route,
            shape={"rows": rows, "tiles": tiles},
            knobs={"label": label} if label else {},
            wall_s=wall, work=rows, src=src))
    return out


#: pod-span kind -> family: the flight-recorder span families
#: (parallel/podtrace.py) harvested per (site, round) occurrence. The
#: route is the bracket's `site` attr (glm_round, tree_fit, tile_merge,
#: stats_fetch, ...) and the shape always carries ``procs`` — pod
#: evidence is keyed per process count so a 2-process collective wall
#: never informs a single-process decision at the same geometry.
_POD_SPAN_FAMILIES = {
    "pod_collective": "pod_collective",
    "pod_compute": "pod_compute",
    "pod_ingest": "pod_ingest",
}


def harvest_pod_spans(spans: List[Mapping[str, Any]], backend: str, *,
                      procs: int, src: str = "podtrace"
                      ) -> List[PlanRecord]:
    """Plan records from one rank's pod_* spans, aggregated per
    (kind, site) over the whole fit — summed wall over summed rows, the
    same per-pass unit-cost shape _harvest_tile_spans uses, because one
    traced fit emits one bracket per engine round and the planner needs
    the fit-level cost, not per-round noise. Unknown kinds/sites skip
    silently (best-effort harvest contract)."""
    agg: Dict[tuple, List[float]] = {}
    shapes: Dict[tuple, Dict[str, float]] = {}
    for s in spans:
        if not isinstance(s, dict):
            continue
        family = _POD_SPAN_FAMILIES.get(str(s.get("kind") or ""))
        if family is None:
            continue
        wall = float(s.get("duration_seconds") or 0.0)
        if wall <= 0.0:
            continue
        attrs = s.get("attrs") or {}
        site = str(attrs.get("site") or "")
        if not site:
            continue
        slot = agg.setdefault((family, site), [0.0, 0.0, 0.0])
        slot[0] += wall
        rows = attrs.get("rows")
        slot[1] += float(rows) if isinstance(rows, (int, float)) else 0.0
        slot[2] += 1.0
        shp = shapes.setdefault((family, site), {})
        for k in ("feat", "lanes", "depth", "folds", "cols"):
            v = attrs.get(k)
            if isinstance(v, (int, float)):
                # max over occurrences: buckets shrink as lanes retire,
                # so the widest bracket names the fit's geometry
                shp[k] = max(shp.get(k, 0.0), float(v))
    out: List[PlanRecord] = []
    for (family, site), (wall, rows, count) in agg.items():
        shape = {"procs": float(int(procs)), "spans": count}
        if rows > 0.0:
            shape["rows"] = rows
        shape.update(shapes.get((family, site), {}))
        out.append(PlanRecord(
            family=family, backend=backend, route=site, shape=shape,
            wall_s=wall, work=rows or count, src=src))
    return out


def harvest_metrics_file(path: str, backend: str,
                         src: str = "harvest") -> List[PlanRecord]:
    """harvest_metrics_doc over a JSON file; unreadable/unparseable
    files yield no records (harvest is best-effort by contract)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict):
        return []
    return harvest_metrics_doc(doc, backend, src=src)
