"""Rich feature syntax: the reference's dsl implicits as Feature methods.

Reference: core/.../dsl/ (10 files, ~3,900 LoC) — `Rich{Numeric,Text,Date,
List,Map,Vector}Feature` add `feature.tokenize()`, `f1 + f2`, `.pivot()`,
`.sanityCheck()`, `.transmogrify()` to features by implicit conversion.
Python shape: the methods are installed directly on Feature at import time
(this module is imported by the package __init__), so
``fare + age``, ``name.tokenize().tf_idf()``, ``features.transmogrify()``
read the same as the Scala dsl.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Union

from .features.feature import Feature
from .types import (
    Binary, Integral, MultiPickList, OPNumeric, OPVector, PickList, Real,
    RealNN, Text, TextList,
)

Number = Union[int, float]


def _is_numeric(f: Feature) -> bool:
    return issubclass(f.feature_type, (OPNumeric, Binary))


# -- arithmetic (RichNumericFeature) ----------------------------------------

def _binary_op(self: Feature, other: Any, cls_scalar, cls_feature):
    from .transformers import math as M
    if isinstance(other, Feature):
        stage = cls_feature()
        return stage.set_input(self, other).get_output()
    stage = cls_scalar(scalar=float(other))
    return stage.set_input(self).get_output()


def _add(self, other):
    from .transformers.math import AddTransformer, ScalarAddTransformer
    return _binary_op(self, other, ScalarAddTransformer, AddTransformer)


def _sub(self, other):
    from .transformers.math import SubtractTransformer, ScalarSubtractTransformer
    return _binary_op(self, other, ScalarSubtractTransformer,
                      SubtractTransformer)


def _mul(self, other):
    from .transformers.math import MultiplyTransformer, ScalarMultiplyTransformer
    return _binary_op(self, other, ScalarMultiplyTransformer,
                      MultiplyTransformer)


def _div(self, other):
    from .transformers.math import DivideTransformer, ScalarDivideTransformer
    return _binary_op(self, other, ScalarDivideTransformer, DivideTransformer)


def _unary(self: Feature, cls, **kw):
    return cls(**kw).set_input(self).get_output()


def _abs(self):
    from .transformers.math import AbsTransformer
    return _unary(self, AbsTransformer)


def _log(self, base: float = 2.718281828459045):
    from .transformers.math import LogTransformer
    return _unary(self, LogTransformer, base=base)


def _exp(self):
    from .transformers.math import ExpTransformer
    return _unary(self, ExpTransformer)


def _sqrt(self):
    from .transformers.math import SqrtTransformer
    return _unary(self, SqrtTransformer)


def _round(self):
    from .transformers.math import RoundTransformer
    return _unary(self, RoundTransformer)


def _ceil(self):
    from .transformers.math import CeilTransformer
    return _unary(self, CeilTransformer)


def _floor(self):
    from .transformers.math import FloorTransformer
    return _unary(self, FloorTransformer)


def _power(self, p: float):
    from .transformers.math import PowerTransformer
    return _unary(self, PowerTransformer, exponent=p)


# -- misc (RichFeature) ------------------------------------------------------

def _alias(self, name: str):
    from .transformers.misc import AliasTransformer
    return _unary(self, AliasTransformer, name=name)


def _to_occur(self):
    from .transformers.misc import ToOccurTransformer
    return _unary(self, ToOccurTransformer)


def _fill_missing_with_mean(self):
    from .transformers.misc import FillMissingWithMean
    return _unary(self, FillMissingWithMean)


def _scale(self, scaling_type: str = "linear", slope: float = 1.0,
           intercept: float = 0.0):
    from .transformers.misc import ScalerTransformer
    return _unary(self, ScalerTransformer, scaling_type=scaling_type,
                  slope=slope, intercept=intercept)


def _autobucketize(self, label: Feature, max_splits: int = 15,
                   min_info_gain: float = 0.01):
    from .transformers.misc import DecisionTreeNumericBucketizer
    stage = DecisionTreeNumericBucketizer(max_splits=max_splits,
                                          min_info_gain=min_info_gain)
    return stage.set_input(label, self).get_output()


def _calibrate_percentile(self, buckets: int = 100):
    from .transformers.misc import PercentileCalibrator
    return _unary(self, PercentileCalibrator, buckets=buckets)


# -- text (RichTextFeature) --------------------------------------------------

def _tokenize(self, min_token_length: int = 1, to_lowercase: bool = True,
              filter_stopwords: bool = False):
    from .transformers.text import TextTokenizer
    return _unary(self, TextTokenizer, min_token_length=min_token_length,
                  to_lowercase=to_lowercase,
                  filter_stopwords=filter_stopwords)


def _text_len(self):
    from .transformers.text import TextLenTransformer
    return _unary(self, TextLenTransformer)


def _detect_languages(self):
    from .transformers.text import LangDetector
    return _unary(self, LangDetector)


def _detect_mime_types(self):
    from .transformers.text import MimeTypeDetector
    return _unary(self, MimeTypeDetector)


def _is_valid_phone(self, default_region: str = "US"):
    from .transformers.text import PhoneNumberParser
    return _unary(self, PhoneNumberParser, default_region=default_region)


def _email_domain(self):
    from .transformers.text import EmailToPickList
    return _unary(self, EmailToPickList)


def _index_string(self, handle_invalid: str = "keep"):
    from .transformers.text import OpStringIndexer
    return _unary(self, OpStringIndexer, handle_invalid=handle_invalid)


def _count_vectorize(self, vocab_size: int = 512, min_df: int = 1,
                     binary: bool = False):
    from .transformers.text import OpCountVectorizer
    return _unary(self, OpCountVectorizer, vocab_size=vocab_size,
                  min_df=min_df, binary=binary)


def _tf_idf(self, vocab_size: int = 512, min_df: int = 1):
    from .transformers.text import TfIdfVectorizer
    return _unary(self, TfIdfVectorizer, vocab_size=vocab_size, min_df=min_df)


def _lda(self, k: int = 10, max_iter: int = 50, seed: int = 42):
    from .transformers.topics import OpLDA
    return _unary(self, OpLDA, k=k, max_iter=max_iter, seed=seed)


def _word2vec(self, vector_size: int = 100, vocab_bins: int = 2048,
              window_size: int = 5, seed: int = 42):
    from .transformers.topics import OpWord2Vec
    return _unary(self, OpWord2Vec, vector_size=vector_size,
                  vocab_bins=vocab_bins, window_size=window_size, seed=seed)


def _recognize_entities(self):
    from .transformers.ner import NameEntityRecognizer
    return _unary(self, NameEntityRecognizer)


# -- similarity --------------------------------------------------------------

def _ngram_similarity(self, other: Feature, n: int = 3):
    from .transformers.text import NGramSimilarity
    return NGramSimilarity(n=n).set_input(self, other).get_output()


def _jaccard_similarity(self, other: Feature):
    from .transformers.text import JaccardSimilarity
    return JaccardSimilarity().set_input(self, other).get_output()


# -- generic (RichFeature) ---------------------------------------------------

def _map_values(self, fn, output_type=None, operation_name: str = "map"):
    """Apply a python function per value (RichFeature.map:61). Lambda
    stages persist only with load(..., custom_stages=...) — same closure
    caveat as the reference's lambda transformers."""
    from .stages.base import LambdaTransformer
    out_t = output_type or self.feature_type
    return LambdaTransformer(
        operation_name,
        lambda v, _f=fn, _t=out_t: _t(_f(v.value)),
        (self.feature_type,), out_t).set_input(self).get_output()


def _replace_with(self, old_value, new_value):
    """Swap one raw value for another (RichFeature.replaceWith:75)."""
    from .transformers.misc import ReplaceWithTransformer
    return ReplaceWithTransformer(old_value=old_value, new_value=new_value) \
        .set_input(self).get_output()


def _exists(self, pred, operation_name: str = "exists"):
    """Binary: predicate holds for the raw value (RichFeature.exists:176).
    Lambda-stage persistence caveat as in map_values."""
    from .stages.base import LambdaTransformer
    return LambdaTransformer(
        operation_name,
        lambda v, _p=pred: Binary(None if v.value is None else
                                  bool(_p(v.value))),
        (self.feature_type,), Binary).set_input(self).get_output()


def _filter_values(self, pred, default, operation_name: str = "filter"):
    """Keep values passing the predicate, else the default
    (RichFeature.filter:134). Lambda-stage persistence caveat applies."""
    from .stages.base import LambdaTransformer
    t = self.feature_type
    return LambdaTransformer(
        operation_name,
        lambda v, _p=pred, _d=default, _t=t: (
            v if (v.value is not None and _p(v.value)) else _t(_d)),
        (t,), t).set_input(self).get_output()


# -- text extras (RichTextFeature / Email / URL) -----------------------------

def _to_multi_pick_list(self):
    from .transformers.text import TextToMultiPickList
    return TextToMultiPickList().set_input(self).get_output()


def _is_valid_email(self):
    from .transformers.text import ValidEmailTransformer
    return ValidEmailTransformer().set_input(self).get_output()


def _email_prefix(self):
    from .transformers.text import EmailPrefixTransformer
    return EmailPrefixTransformer().set_input(self).get_output()


def _url_domain(self):
    from .transformers.text import UrlPartsTransformer
    return UrlPartsTransformer(part="domain").set_input(self).get_output()


def _url_protocol(self):
    from .transformers.text import UrlPartsTransformer
    return UrlPartsTransformer(part="protocol").set_input(self).get_output()


def _is_valid_url(self, protocols=None):
    from .transformers.text import ValidUrlTransformer
    stage = ValidUrlTransformer()
    if protocols is not None:
        stage.set_param("protocols", list(protocols))
    return stage.set_input(self).get_output()


# -- dates (RichDateFeature) -------------------------------------------------

def _to_unit_circle(self, time_period: str = "HourOfDay", **kwargs):
    """Date -> [sin, cos] of a calendar period (RichDateFeature
    .toUnitCircle:68); DateMap/DateTimeMap inputs take the per-key map
    route (RichMapFeature.toUnitCircle:716)."""
    from .types import OPMap
    if issubclass(self.feature_type, OPMap):
        return _to_unit_circle_map(self, time_period=time_period, **kwargs)
    from .transformers.misc import DateToUnitCircleTransformer
    return DateToUnitCircleTransformer(time_period=time_period) \
        .set_input(self).get_output()


def _to_unit_circle_map(self, time_period: str = "HourOfDay",
                        clean_keys: bool = False,
                        allow_listed_keys=None, block_listed_keys=None):
    """DateMap -> per-key [sin, cos] unit-circle vector (RichMapFeature
    .toUnitCircle:716 -> DateMapToUnitCircleVectorizer)."""
    from .automl.vectorizers.maps import DateMapUnitCircleVectorizer
    return DateMapUnitCircleVectorizer(
        time_period=time_period, clean_keys=clean_keys,
        allow_listed_keys=allow_listed_keys,
        block_listed_keys=block_listed_keys).set_input(self).get_output()


def _tupled(self):
    """Prediction -> (prediction RealNN, rawPrediction OPVector,
    probability OPVector) (RichMapFeature RichPredictionFeature
    .tupled:1098)."""
    from .types import OPVector, RealNN
    pred = _map_feature(self, lambda p: p.prediction, RealNN,
                        operation_name="predictionValue")
    raw = _map_feature(self, lambda p: p.raw_prediction, OPVector,
                       operation_name="rawPrediction")
    prob = _map_feature(self, lambda p: p.probability, OPVector,
                        operation_name="probability")
    return pred, raw, prob


def _to_date_list(self):
    """Date -> DateList / DateTime -> DateTimeList (RichDateFeature
    .toDateList:54)."""
    from .transformers.misc import DateToListTransformer
    return DateToListTransformer().set_input(self).get_output()


def _vectorize_dates(self, *others, **kwargs):
    """Date features -> circular-encoded vector (RichDateFeature
    .vectorize:97)."""
    from .automl.vectorizers.dates import DateVectorizer
    return DateVectorizer(**kwargs).set_input(self, *others).get_output()


# -- maps (RichMapFeature) ---------------------------------------------------

def _filter_keys(self, allow: Optional[Sequence[str]] = None,
                 block: Optional[Sequence[str]] = None):
    """Keep/drop map keys (RichMapFeature.filter:58 whiteList/blackList)."""
    from .transformers.misc import FilterMapKeys
    return FilterMapKeys(allow=allow, block=block) \
        .set_input(self).get_output()


def _vectorize_map(self, *others, **kwargs):
    """Per-key map vectorization dispatched on the map's type
    (RichMapFeature.vectorize overloads)."""
    from .automl.transmogrifier import TransmogrifierDefaults
    from .automl.vectorizers.maps import map_vectorizer_for
    stage = map_vectorizer_for(self.type_name, TransmogrifierDefaults)
    for k, v in kwargs.items():
        stage.set_param(k, v)
    return stage.set_input(self, *others).get_output()


def _is_valid_phone_map(self, default_region: str = "US"):
    """Per-key phone validity (RichMapFeature
    .isValidPhoneDefaultCountryMap)."""
    from .transformers.misc import PhoneValidityMap
    return PhoneValidityMap(default_region=default_region) \
        .set_input(self).get_output()


def _detect_mime_types_map(self):
    """Per-key MIME detection on Base64 maps (RichMapFeature
    .detectMimeTypes)."""
    from .transformers.misc import MimeTypeMap
    return MimeTypeMap().set_input(self).get_output()


def _autobucketize_map(self, label: Feature, **kwargs):
    """Label-aware bucketization of every numeric map key
    (RichMapFeature.autoBucketize:542 ->
    DecisionTreeNumericMapBucketizer)."""
    from .transformers.misc import DecisionTreeNumericMapBucketizer
    return DecisionTreeNumericMapBucketizer(**kwargs) \
        .set_input(label, self).get_output()


# -- geolocation (RichLocationFeature) ---------------------------------------

def _vectorize_geo(self, *others, **kwargs):
    """Geolocation -> mean-imputed (lat, lon, acc) block
    (RichLocationFeature.vectorize:63)."""
    from .automl.vectorizers.geo import GeolocationVectorizer
    return GeolocationVectorizer(**kwargs).set_input(self, *others) \
        .get_output()


# -- vector (RichVectorFeature) ----------------------------------------------

def _combine_with(self, *others):
    """Concatenate OPVector features (RichVectorFeature combine)."""
    from .automl.vectorizers.combiner import VectorsCombiner
    return VectorsCombiner().set_input(self, *others).get_output()


def _descale(self, scaled_source: Feature, scaler=None):
    """Invert a ScalerTransformer's scaling (RichNumericFeature
    .descale); a Prediction input descales its prediction value
    (RichPredictionFeature.descale:1113 -> PredictionDescaler)."""
    from .transformers.misc import DescalerTransformer
    from .types import Prediction, RealNN
    target = self
    if issubclass(self.feature_type, Prediction):
        target = _map_feature(self, lambda p: p.prediction, RealNN,
                              operation_name="predictionValue")
    return DescalerTransformer(scaler=scaler) \
        .set_input(target, scaled_source).get_output()


# -- vectorize / check (RichFeaturesCollection) ------------------------------

def _vectorize(self, **kwargs):
    from .automl.transmogrifier import transmogrify
    return transmogrify([self], **kwargs)


def _pivot(self, top_k: int = 20):
    from .automl.vectorizers.categorical import OneHotVectorizer
    return OneHotVectorizer(top_k=top_k).set_input(self).get_output()


def _sanity_check(self, label: Feature, **kwargs):
    from .automl.preparators import SanityChecker
    return SanityChecker(**kwargs).set_input(label, self).get_output()


# -- round-3 breadth (closing the dsl gap vs the reference's ~3,900 LoC) ----

def _bucketize(self, splits=None, num_buckets: int = 4,
               track_nulls: bool = True):
    """Fixed-split or quantile buckets (RichNumericFeature.bucketize)."""
    from .automl.vectorizers.numeric import NumericBucketizer
    given = None if splits is None else [list(splits)]
    return NumericBucketizer(splits=given, num_buckets=num_buckets,
                             track_nulls=track_nulls) \
        .set_input(self).get_output()


def _z_normalize(self):
    """Z-score scaling fit on the data (RichNumericFeature.zNormalize)."""
    from .transformers.math import ZNormalizeEstimator
    return ZNormalizeEstimator().set_input(self).get_output()


def _to_isotonic_calibrated(self, label: Feature, isotonic: bool = True):
    """Calibrate a score against a label by isotonic regression
    (RichNumericFeature.toIsotonicCalibrated)."""
    from .models.mlp import IsotonicRegressionCalibrator
    return IsotonicRegressionCalibrator(isotonic=isotonic) \
        .set_input(label, self).get_output()


def _is_substring(self, other: Feature):
    """Binary: is this text contained in `other` (RichTextFeature
    .isSubstring)."""
    from .transformers.text import SubstringTransformer
    return SubstringTransformer().set_input(self, other).get_output()


def _tokenize_regex(self, pattern: str = r"\w+", to_lowercase: bool = True,
                    min_token_length: int = 1):
    from .transformers.text import RegexTokenizer
    return _unary(self, RegexTokenizer, pattern=pattern,
                  to_lowercase=to_lowercase,
                  min_token_length=min_token_length)


def _remove_stop_words(self):
    from .transformers.text import StopWordsRemover
    return _unary(self, StopWordsRemover)


def _ngram(self, n: int = 2):
    from .transformers.text import NGramTransformer
    return _unary(self, NGramTransformer, n=n)


def _tf(self, num_features: int = 512):
    """Hashed term frequencies (RichListFeature.tf via HashingTF)."""
    from .automl.vectorizers.text import TextListHashingVectorizer
    return TextListHashingVectorizer(num_features=num_features) \
        .set_input(self).get_output()


def _drop_indices_by(self, predicate):
    """Drop vector columns whose metadata matches `predicate`
    (RichVectorFeature.dropIndicesBy)."""
    from .transformers.misc import DropIndicesByTransformer
    return DropIndicesByTransformer(predicate=predicate) \
        .set_input(self).get_output()


def _map_feature(self, fn, output_type, operation_name: str = "map"):
    """Arbitrary row-level transform (RichFeature.map): `fn` takes and
    returns FeatureType instances."""
    from .stages.base import LambdaTransformer
    stage = LambdaTransformer(operation_name, fn,
                              input_types=(self.feature_type,),
                              output_type=output_type)
    return stage.set_input(self).get_output()


def _loco_insights(self, model, top_k: int = 20):
    from .insights import RecordInsightsLOCO
    return RecordInsightsLOCO(model=model, top_k=top_k) \
        .set_input(self).get_output()


def _parse_phone(self, default_region: str = "US"):
    """Normalized E.164 text (RichTextFeature.parsePhone:464 /
    parsePhoneDefaultCountry:489)."""
    from .transformers.text import PhoneParser
    return _unary(self, PhoneParser, default_region=default_region)


def _deindexed(self, labels: Sequence[str]):
    """Index -> original string label (RichNumericFeature.deindexed:418
    via OpIndexToString). `labels` is the indexer's fitted vocabulary —
    required here because, unlike Spark, no column metadata carries it."""
    if not labels:
        raise ValueError("deindexed() needs the fitted label vocabulary "
                         "(the paired OpStringIndexer's ordering)")
    from .transformers.text import OpIndexToString
    return OpIndexToString(labels=list(labels)).set_input(self).get_output()


def _filter_not(self, pred, default, operation_name: str = "filterNot"):
    """Complement of filter_values (RichFeature.filterNot:148)."""
    return _filter_values(self, lambda v, _p=pred: not _p(v), default,
                          operation_name=operation_name)


def _collect(self, fn, default, output_type=None,
             operation_name: str = "collect"):
    """Partial map: `fn` returns None where undefined, replaced by
    `default` (RichFeature.collect:160)."""
    from .stages.base import LambdaTransformer
    out_t = output_type or self.feature_type

    def apply(v, _f=fn, _t=out_t, _d=default):
        r = None if v.value is None else _f(v.value)
        return _t(_d if r is None else r)

    return LambdaTransformer(operation_name, apply, (self.feature_type,),
                             out_t).set_input(self).get_output()


def _idf(self, min_doc_freq: int = 0):
    """Inverse-document-frequency rescaling of a count vector
    (RichVectorFeature.idf:56)."""
    from .transformers.text import OpIDF
    return _unary(self, OpIDF, min_doc_freq=min_doc_freq)


def _random_forest_vec(self, label: Feature, **params):
    """Fit a random-forest classifier on (label, vector) and emit the
    Prediction feature (RichVectorFeature.randomForest:77)."""
    from .models.trees import OpRandomForestClassifier
    return OpRandomForestClassifier(**params) \
        .set_input(label, self).get_output()


def _smart_vectorize(self, *others, **kwargs):
    """Cardinality-adaptive text vectorization (RichTextFeature
    .smartVectorize:223 -> SmartTextVectorizer); text-map inputs route
    through the key-discovering map vectorizer, whose 'smarttext' kind is
    the SmartTextMapVectorizer equivalent (RichMapFeature:280,425)."""
    from .types import OPMap
    if issubclass(self.feature_type, OPMap):
        from .automl.vectorizers.maps import MapVectorizer
        return MapVectorizer(**kwargs).set_input(self, *others).get_output()
    from .automl.vectorizers.text import SmartTextVectorizer
    return SmartTextVectorizer(**kwargs).set_input(self, *others).get_output()


def install() -> None:
    """Install the dsl methods on Feature (idempotent)."""
    ops = {
        "__add__": _add, "__radd__": _add, "__sub__": _sub,
        "__mul__": _mul, "__rmul__": _mul, "__truediv__": _div,
        "abs": _abs, "log": _log, "exp": _exp, "sqrt": _sqrt,
        "round": _round, "ceil": _ceil, "floor": _floor, "power": _power,
        "alias": _alias, "to_occur": _to_occur,
        "fill_missing_with_mean": _fill_missing_with_mean, "scale": _scale,
        "autobucketize": _autobucketize,
        "calibrate_percentile": _calibrate_percentile,
        "tokenize": _tokenize, "text_len": _text_len,
        "detect_languages": _detect_languages,
        "detect_mime_types": _detect_mime_types,
        "is_valid_phone": _is_valid_phone, "email_domain": _email_domain,
        "index_string": _index_string, "count_vectorize": _count_vectorize,
        "tf_idf": _tf_idf, "lda": _lda, "word2vec": _word2vec,
        "recognize_entities": _recognize_entities,
        "ngram_similarity": _ngram_similarity,
        "jaccard_similarity": _jaccard_similarity,
        "vectorize": _vectorize, "pivot": _pivot,
        "sanity_check": _sanity_check, "loco_insights": _loco_insights,
        "to_unit_circle": _to_unit_circle,
        "to_unit_circle_map": _to_unit_circle_map, "tupled": _tupled,
        "to_date_list": _to_date_list,
        "vectorize_dates": _vectorize_dates,
        "filter_keys": _filter_keys, "vectorize_map": _vectorize_map,
        "autobucketize_map": _autobucketize_map,
        "vectorize_geo": _vectorize_geo,
        "combine_with": _combine_with, "descale": _descale,
        "map_values": _map_values, "replace_with": _replace_with,
        "exists": _exists, "filter_values": _filter_values,
        "to_multi_pick_list": _to_multi_pick_list,
        "is_valid_email": _is_valid_email, "email_prefix": _email_prefix,
        "url_domain": _url_domain, "url_protocol": _url_protocol,
        "is_valid_url": _is_valid_url,
        "bucketize": _bucketize, "z_normalize": _z_normalize,
        "to_isotonic_calibrated": _to_isotonic_calibrated,
        "is_substring": _is_substring, "tokenize_regex": _tokenize_regex,
        "remove_stop_words": _remove_stop_words, "ngram": _ngram,
        "tf": _tf, "drop_indices_by": _drop_indices_by,
        "map": _map_feature,
        "is_valid_phone_map": _is_valid_phone_map,
        "detect_mime_types_map": _detect_mime_types_map,
        "parse_phone": _parse_phone, "deindexed": _deindexed,
        "filter_not": _filter_not, "collect": _collect, "idf": _idf,
        "random_forest": _random_forest_vec,
        "smart_vectorize": _smart_vectorize,
        "to_date_time_list": _to_date_list,  # DateTime in -> DateTimeList
        "auto_transform": _vectorize,  # RichFeaturesCollection alias
    }
    for name, fn in ops.items():
        setattr(Feature, name, fn)


def transmogrify(features: Sequence[Feature], **kwargs):
    """Module-level shortcut mirroring RichFeaturesCollection.transmogrify."""
    from .automl.transmogrifier import transmogrify as tf
    return tf(list(features), **kwargs)


def combine(features: Sequence[Feature]):
    """Concatenate OPVector features into one (RichFeaturesCollection
    .combine:76 -> VectorsCombiner)."""
    from .automl.vectorizers.combiner import VectorsCombiner
    feats = list(features)
    return VectorsCombiner().set_input(*feats).get_output()


install()
