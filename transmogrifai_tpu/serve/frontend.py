"""Dependency-light HTTP/JSON frontend + the `serve` CLI body.

Stdlib only (http.server.ThreadingHTTPServer) — the serving subsystem
adds no dependency the batch library doesn't already carry. The HTTP
layer is deliberately thin: every request body is one JSON record (or a
list for bulk), the typed errors of the admission path map to status
codes (validation -> 400, Overloaded -> 503, anything else -> 500), and
`/metrics` serves the engine's own latency histograms. Tests and
bench.py drive the same :class:`ServeFrontend` in-process through
``submit()``/``submit_many()`` — the HTTP layer is transport, not logic.

Endpoints:
  POST /score         {record} -> scores; [records] -> bulk (no queue)
  GET  /healthz       liveness + warm/bucket state (503 when draining)
  GET  /metrics       engine counters + p50/p95/p99 latency histograms
  GET  /metrics/history  ring of periodic gauge snapshots (queue depth,
                      in-flight, shed, compiles, drift verdicts) — the
                      time-series behind the counters
  GET  /requests      request-tracing payload: per-segment latency
                      histograms (the fleet merge unit) + the tail-kept
                      trace ring (observability.md "Request tracing")
  GET  /debugz        live thread names + stack frames, queue depth,
                      dispatcher heartbeat age — the "why is it stuck"
                      snapshot
  GET  /drain         flip /healthz to draining-503 (also SIGUSR1) so a
                      router/LB rotates this replica out BEFORE SIGTERM;
                      in-flight and still-arriving requests keep scoring
  GET  /drift         drift-monitor report (monitoring.md)
  GET  /drift/window  the CURRENT window's raw sufficient statistics —
                      what the fleet telemetry merger pools (fleet.md)

Request tracing: every /score request gets a RequestTrace (trace id
adopted from the router's ``X-Tmog-Trace`` header or minted), segments
stamped through parse -> queue -> batch -> device -> monitor -> respond,
tail-sampled at completion; the reply echoes the header back with this
replica's id so the router's record and this one share a trace id.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple, Union

from ..local.scoring import (InvalidFeatureError, MissingFeatureError,
                             UnknownFeatureError)
from ..utils.metrics import GaugeRing, collector
from . import reqtrace
from .batcher import MicroBatcher, Overloaded
from .engine import ServingEngine
from .reqtrace import (BatchTrace, GaugeSampler, ReqTracer, RequestTrace,
                       thread_dump)

_log = logging.getLogger("transmogrifai_tpu.serve")

Record = Dict[str, Any]

#: the typed client errors -> HTTP 400 (bad request, not a server fault)
CLIENT_ERRORS = (UnknownFeatureError, MissingFeatureError,
                 InvalidFeatureError)


class ServeFrontend:
    """In-process API the HTTP handler, tests and bench all share.

    `max_bulk` bounds ONE HTTP bulk request (HTTP 413 above it): the
    bulk lane bypasses the admission queue, so without a bound a single
    giant list could hold the engine lock for minutes while single-
    record traffic starves behind it with no shed available. In-process
    callers (bench, batch jobs) call engine.score_batch directly when
    they really mean row floods."""

    def __init__(self, engine: ServingEngine, batcher: MicroBatcher,
                 max_bulk: int = 65536,
                 tracer: Optional[ReqTracer] = None):
        self.engine = engine
        self.batcher = batcher
        self.max_bulk = int(max_bulk)
        # drain flag (GET /drain or SIGUSR1): an Event — set/is_set are
        # atomic, shared by HTTP workers and the signal path
        self._draining = threading.Event()
        #: per-replica request tracer (reqtrace) + the gauge ring behind
        #: GET /metrics/history; run_serve passes the CLI-configured
        #: tracer, in-process embedders get the env-gated default
        self.tracer = tracer if tracer is not None else ReqTracer(
            f"pid{os.getpid()}", enabled=reqtrace.env_enabled())
        self.gauges = GaugeRing()
        #: the X-Tmog-Debug-Sleep chaos hook is OFF unless the operator
        #: opted in (ci.sh injects its artificially slow request here);
        #: the cap bounds what any client can inflict
        try:
            self.debug_sleep_max_ms = float(
                os.environ.get("TMOG_DEBUG_SLEEP_MAX_MS", "0"))
        except ValueError:
            self.debug_sleep_max_ms = 0.0
        # duck-typed engine stand-ins (tests, adapters) may not accept
        # batch_trace=; probe the signature ONCE so the traced bulk path
        # degrades to untraced batch walls instead of a 500 per request
        import inspect
        try:
            self._engine_takes_batch_trace = "batch_trace" in \
                inspect.signature(engine.score_batch).parameters
        except (TypeError, ValueError):
            self._engine_takes_batch_trace = False

    def submit(self, record: Record, timeout: Optional[float] = None,
               trace: Optional[RequestTrace] = None) -> Record:
        """One record through the micro-batching queue."""
        return self.batcher.submit(record, timeout=timeout, trace=trace)

    def submit_many(self, records: List[Record],
                    trace: Optional[RequestTrace] = None) -> List[Record]:
        """Bulk scoring straight through the bucket ladder (no queue —
        a bulk caller IS a batch already)."""
        t0 = time.perf_counter()
        for r in records:
            self.engine.validate_record(r)
        if trace is None:
            return self.engine.score_batch(records)
        trace.seg("validate", time.perf_counter() - t0)
        # request-thread-owned record (reqtrace single-owner contract)
        trace.rows = len(records)  # tmoglint: disable=THR001
        if not self._engine_takes_batch_trace:
            return self.engine.score_batch(records)
        bt = BatchTrace()
        out = self.engine.score_batch(records, batch_trace=bt)
        bt.stamp(trace)
        return out

    def debug_sleep(self, headers: Any,
                    trace: Optional[RequestTrace]) -> None:
        """Honor the X-Tmog-Debug-Sleep header (bounded by
        TMOG_DEBUG_SLEEP_MAX_MS, default 0 = hook disabled): the
        injected latency is its own trace segment, so a deliberately
        slow request still covers its e2e wall."""
        if self.debug_sleep_max_ms <= 0:
            return
        raw = headers.get(reqtrace.DEBUG_SLEEP_HEADER)
        if not raw:
            return
        try:
            ms = min(float(raw), self.debug_sleep_max_ms)
        except ValueError:
            return
        if ms <= 0:
            return
        time.sleep(ms / 1e3)
        if trace is not None:
            trace.seg("debug_sleep", ms / 1e3)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self) -> Dict[str, Any]:
        """Flip /healthz to draining-503 while the engine keeps scoring
        everything in flight (and anything that still arrives): the
        router — or any external load balancer probing /healthz — takes
        the replica out of rotation, traffic bleeds off, and only THEN
        does the operator send SIGTERM. Before this endpoint the only
        drain was SIGTERM itself, which gave an LB no advance notice.
        Idempotent; there is deliberately no un-drain (a drained replica
        restarts, re-proving the compile-free-start contract)."""
        if not self._draining.is_set():
            self._draining.set()
            collector.event("serve_drain",
                            queue_len=self.batcher.queue_len)
            _log.info("serve: draining — /healthz now 503, in-flight "
                      "requests finishing")
        return self.healthz()

    def drift_window(self) -> Optional[Dict[str, Any]]:
        """The ``GET /drift/window`` payload: the current window's RAW
        sufficient statistics (monitor/window.ServeMonitor.window_state)
        — histogram mass, null counts, prediction sketch, row count.
        This is the merge unit of fleet-level drift (fleet/telemetry):
        the fleet sums these across replicas and runs ONE DriftPolicy
        verdict on the pooled window. None when monitoring is off."""
        mon = self.engine.monitor
        if mon is None:
            return None
        return mon.window_state()

    def healthz(self) -> Dict[str, Any]:
        status = "ok" if self.engine.warm else "warming"
        out = {"warm": self.engine.warm,
               "buckets": list(self.engine.buckets),
               "queue_len": self.batcher.queue_len,
               "closed": self.batcher.closed}
        mon = self.engine.monitor
        if mon is not None:
            out["drift_alerting"] = mon.alerting
            if not mon.healthy() and not self.engine.monitor_disabled:
                # the optional hard health gate (docs/monitoring.md):
                # with --monitor-health-gate, an alerting window degrades
                # /healthz (HTTP 503) until a clean window closes or the
                # verdict expires idle — a load balancer can rotate a
                # replica off a rotten feed. A self-disabled monitor
                # (observation faults) cannot refresh its verdict, so
                # its stale alert must not hold the gate
                status = "degraded"
        if self._draining.is_set():
            # draining wins over every other verdict: the whole point is
            # that probes stop selecting this replica
            status = "draining"
        out["draining"] = self._draining.is_set()
        out["status"] = status
        return out

    def drift(self) -> Optional[Dict[str, Any]]:
        """The ``GET /drift`` payload; None when monitoring is off."""
        mon = self.engine.monitor
        if mon is None:
            return None
        rep = mon.report()
        rep["disabled"] = self.engine.monitor_disabled
        return rep

    def metrics(self) -> Dict[str, Any]:
        return self.engine.metrics()

    def requests(self) -> Dict[str, Any]:
        """The ``GET /requests`` payload: this replica's per-segment
        histograms + tail-kept traces (observability.md)."""
        return self.tracer.requests_payload()

    def history(self) -> Dict[str, Any]:
        """The ``GET /metrics/history`` payload: the gauge ring."""
        return {"replica": self.tracer.replica_id,
                "interval_hint_s": None,
                "gauges": self.gauges.to_json()}

    def sample_gauges(self) -> Dict[str, Any]:
        """One gauge snapshot (GaugeSampler's read): queue depth +
        in-flight + the engine's counter gauges incl. drift verdicts."""
        out = {"queue_depth": self.batcher.queue_len,
               "in_flight": self.tracer.in_flight,
               "draining": self.draining}
        out.update(self.engine.gauge_state())
        return out

    def debugz(self) -> Dict[str, Any]:
        """The "why is it stuck" snapshot: every live thread's name +
        innermost stack frames (sys._current_frames), queue depth, and
        the lock-ish health bits — batcher thread alive, dispatcher
        heartbeat age (a big age with a deep queue = the dispatcher is
        wedged inside a batch)."""
        return {"threads": thread_dump(),
                "queue_len": self.batcher.queue_len,
                "batcher_alive": self.batcher.alive,
                "batcher_closed": self.batcher.closed,
                "dispatcher_beat_age_s": round(self.batcher.beat_age(),
                                               4),
                "in_flight": self.tracer.in_flight,
                "warm": self.engine.warm,
                "draining": self.draining}


class _Handler(BaseHTTPRequestHandler):
    server_version = "transmogrifai-tpu-serve"
    frontend: ServeFrontend  # attached by make_http_server

    def log_message(self, fmt: str, *args: Any) -> None:
        _log.debug("http: " + fmt, *args)

    def _reply(self, code: int, payload: Any,
               trace_header: Optional[str] = None) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if trace_header:
            # hop-context echo: the caller (router or client) learns the
            # serving replica id without parsing the body
            self.send_header(reqtrace.TRACE_HEADER, trace_header)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        fe = self.server.frontend  # type: ignore[attr-defined]
        if self.path == "/healthz":
            h = fe.healthz()
            self._reply(503 if h["status"] in ("degraded", "draining")
                        else 200, h)
        elif self.path == "/metrics":
            self._reply(200, fe.metrics())
        elif self.path == "/metrics/history":
            self._reply(200, fe.history())
        elif self.path == "/requests":
            self._reply(200, fe.requests())
        elif self.path == "/debugz":
            self._reply(200, fe.debugz())
        elif self.path == "/drain":
            self._reply(200, fe.drain())
        elif self.path == "/drift/window":
            w = fe.drift_window()
            if w is None:
                self._reply(404, {"error": "drift monitoring not "
                                           "enabled"})
            else:
                self._reply(200, w)
        elif self.path == "/drift":
            d = fe.drift()
            if d is None:
                self._reply(404, {"error": "drift monitoring not enabled "
                                           "(no monitor.json profile, or "
                                           "--monitor off)"})
            else:
                self._reply(200, d)
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self) -> None:  # noqa: N802
        fe = self.server.frontend  # type: ignore[attr-defined]
        if self.path == "/drain":
            # REST-proper alias of GET /drain (kept on GET too for curl
            # ergonomics and the documented LB-rotation contract)
            self._reply(200, fe.drain())
            return
        if self.path != "/score":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        # request trace: id adopted from the router's header (or the
        # client's), minted here otherwise; None when tracing is off —
        # every stamp below is behind one None check
        rt = fe.tracer.start(self.headers.get(reqtrace.TRACE_HEADER))
        t0 = time.perf_counter()
        code, payload = self._score_body(fe, rt, t0)
        t1 = time.perf_counter()
        header = (reqtrace.format_trace_header(
            rt.trace_id, replica=fe.tracer.replica_id)
            if rt is not None else None)
        try:
            self._reply(code, payload, trace_header=header)
        except OSError:
            # the client hung up (e.g. the router's timeout fired while
            # we were scoring) — exactly a trace worth keeping
            if rt is not None:
                rt.error_type = rt.error_type or "ClientDisconnect"
            raise
        finally:
            # tail sampling happens HERE, after the response left (or
            # failed to): finish must run on EVERY exit or in_flight
            # leaks and the interesting trace is dropped
            if rt is not None:
                rt.seg("respond", time.perf_counter() - t1)
                fe.tracer.finish(rt, time.perf_counter() - t0,
                                 status=code)

    def _score_body(self, fe: "ServeFrontend",
                    rt: Optional[RequestTrace],
                    t0: float) -> Tuple[int, Any]:
        """(status, payload) of one /score request; trace segments and
        the error/shed markers the tail sampler keys on are stamped on
        `rt` along the way."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
            doc: Union[Record, List[Record]] = json.loads(
                self.rfile.read(length) or b"null")
            if rt is not None:
                rt.seg("parse", time.perf_counter() - t0)
            fe.debug_sleep(self.headers, rt)
            if isinstance(doc, list):
                if len(doc) > fe.max_bulk:
                    return 413, {
                        "error": f"bulk request of {len(doc)} records "
                                 f"exceeds max_bulk={fe.max_bulk}; "
                                 f"split into smaller requests"}
                return 200, fe.submit_many(doc, trace=rt)
            elif isinstance(doc, dict):
                return 200, fe.submit(doc, trace=rt)
            else:
                return 400, {"error": "body must be a JSON record "
                                      "object or a list of records"}
        except json.JSONDecodeError as e:
            if rt is not None:
                # handler-thread-owned record (reqtrace contract)
                rt.error_type = "JSONDecodeError"  # tmoglint: disable=THR001
            return 400, {"error": f"invalid JSON: {e}"}
        except CLIENT_ERRORS as e:
            if rt is not None:
                rt.error_type = type(e).__name__
            return 400, {"error": str(e),
                         "error_type": type(e).__name__}
        except Overloaded as e:
            if rt is not None:
                rt.shed = True
            return 503, {"error": str(e), "error_type": "Overloaded"}
        except TimeoutError as e:
            if rt is not None:
                rt.error_type = "TimeoutError"
            return 504, {"error": str(e)}
        except Exception as e:  # pragma: no cover - systemic faults
            _log.exception("serve: request failed")
            if rt is not None:
                rt.error_type = type(e).__name__
            return 500, {"error": f"{type(e).__name__}: {e}"}


def make_http_server(frontend: ServeFrontend, host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    """ThreadingHTTPServer bound to (host, port); port 0 picks an
    ephemeral port (server.server_address[1] has the real one)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.frontend = frontend  # type: ignore[attr-defined]
    return httpd


# -- the `serve` CLI body -----------------------------------------------------

def run_serve(args: Any) -> int:
    """Body of ``python -m transmogrifai_tpu serve`` (cli.py parses).

    --prewarm-only: compile every bucket, populate the persistent
    compilation cache, write the serve.json manifest next to the model,
    print one summary JSON line and exit — the deploy-time prewarm whose
    cache entries make the NEXT process start compile-free.
    """
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s")

    from ..workflow.workflow import WorkflowModel

    model = WorkflowModel.load(args.model_dir)
    metrics_loc = getattr(args, "metrics_location", None)
    if metrics_loc:
        os.makedirs(metrics_loc, exist_ok=True)
        collector.enable("serve")
        collector.attach_event_log(os.path.join(metrics_loc,
                                                "events.jsonl"))

    buckets = None
    if getattr(args, "buckets", None):
        buckets = [int(b) for b in str(args.buckets).split(",") if b]
    example = None
    if getattr(args, "example", None):
        with open(args.example) as f:
            example = json.load(f)

    # drift monitor (docs/monitoring.md): --monitor auto (default) turns
    # it on exactly when the model artifact carries a monitor.json
    # reference profile; `on` demands one; `off` disables
    monitor = None
    mon_mode = getattr(args, "monitor", "auto")
    if mon_mode != "off":
        from ..monitor.profile import ReferenceProfile
        from ..monitor.window import ServeMonitor
        from ..workflow.io import load_monitor_profile
        doc = load_monitor_profile(args.model_dir)
        if doc is not None:
            try:
                monitor = ServeMonitor(
                    ReferenceProfile.from_json(doc),
                    window_rows=int(getattr(args, "monitor_window_rows",
                                            4096)),
                    window_seconds=float(getattr(args,
                                                 "monitor_window_seconds",
                                                 60.0)),
                    health_gate=bool(getattr(args, "monitor_health_gate",
                                             False)))
            except Exception:
                # a structurally corrupt profile (valid JSON, broken
                # schema) must not block startup under auto — same
                # contract as load_monitor_profile's decode guard; an
                # explicit `on` fails loudly below
                _log.exception("serve: monitor.json under %s is "
                               "unusable", args.model_dir)
                if mon_mode == "on":
                    return 2
                monitor = None
        if monitor is not None:
            _log.info("serve: drift monitoring ON (%d numeric + %d "
                      "hashed features, window %d rows / %.0fs%s)",
                      len(monitor.numeric_names),
                      len(monitor.hashed_names), monitor.window_rows,
                      monitor.window_seconds,
                      ", health gate" if monitor.health_gate else "")
        elif mon_mode == "on" and doc is None:
            _log.error("serve: --monitor on but %s has no monitor.json "
                       "(save the model from a fitted session)",
                       args.model_dir)
            return 2
        elif doc is None:
            _log.info("serve: no monitor.json next to the model — drift "
                      "monitoring off")

    engine = ServingEngine(
        model, max_batch=args.max_batch, buckets=buckets, example=example,
        single_record=getattr(args, "single_record", "bucket"),
        monitor=monitor)
    if engine.manifest_mismatch and getattr(args, "strict_manifest",
                                            False):
        # the fleet contract (docs/fleet.md): a stale serve.json means
        # the prewarm would compile instead of cache-hit — under
        # --strict-manifest (every fleet replica) that is a refusal to
        # join, not a warning
        _log.error("serve: --strict-manifest and the serve.json "
                   "manifest is stale: %s",
                   "; ".join(engine.manifest_mismatch))
        return 2
    if monitor is not None and engine.monitor is None and mon_mode == "on":
        # the engine refused the monitor (profile/model feature
        # mismatch — e.g. a retrained model served with a stale
        # monitor.json). Under auto that degrades to unmonitored with a
        # warning; under an explicit `on` the operator DEMANDED
        # monitoring, so running without it must be a startup failure
        _log.error("serve: --monitor on but the profile does not match "
                   "this model's features (stale monitor.json? re-save "
                   "the model)")
        return 2
    summary = engine.prewarm()

    def _save_artifacts() -> None:
        if not metrics_loc:
            return
        collector.save(os.path.join(metrics_loc,
                                    "serve_stage_metrics.json"))
        collector.save_chrome_trace(os.path.join(metrics_loc,
                                                 "serve_trace.json"))
        collector.detach_event_log()
        collector.disable()

    if getattr(args, "prewarm_only", False):
        manifest = engine.write_manifest()
        summary["manifest"] = manifest
        _save_artifacts()
        print(json.dumps({"prewarm": summary}, default=str))
        return 0

    batcher = MicroBatcher(engine, max_wait_ms=args.max_wait_ms,
                           max_queue=args.max_queue)
    # request tracing (docs/observability.md "Request tracing"):
    # --replica-id is the fleet-assigned identity echoed in the
    # X-Tmog-Trace reply header and stamped on every kept trace
    replica_id = getattr(args, "replica_id", None) or f"pid{os.getpid()}"
    rt_enabled = (getattr(args, "request_trace", "on") != "off"
                  and reqtrace.env_enabled())
    tracer = ReqTracer(replica_id, enabled=rt_enabled,
                       sample_rate=getattr(args, "trace_sample", None))
    frontend = ServeFrontend(engine, batcher, tracer=tracer)
    gauge_sampler = GaugeSampler(frontend.sample_gauges,
                                 ring=frontend.gauges).start()
    httpd = make_http_server(frontend, host=args.host, port=args.port)
    host, port = httpd.server_address[:2]
    _log.info("serving %s on http://%s:%s (buckets %s, max_wait %.1fms, "
              "queue %d, replica %s, request tracing %s)",
              args.model_dir, host, port, list(engine.buckets),
              args.max_wait_ms, args.max_queue, replica_id,
              "on" if rt_enabled else "OFF")

    def _graceful(signum: int, frame: Any) -> None:
        _log.info("signal %s: draining and shutting down", signum)
        # shutdown() blocks until serve_forever returns — must not run on
        # the signal-interrupted main thread itself
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    def _drain_signal(signum: int, frame: Any) -> None:
        frontend.drain()  # /healthz -> 503; serving continues

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
        if hasattr(signal, "SIGUSR1"):
            # the signal twin of GET /drain: rotate out of the LB first,
            # SIGTERM later (docs/serving.md "Drain before stop")
            signal.signal(signal.SIGUSR1, _drain_signal)
    except ValueError:  # not on the main thread (tests drive in-process)
        pass

    try:
        httpd.serve_forever(poll_interval=0.1)
    finally:
        httpd.server_close()
        gauge_sampler.stop()
        batcher.shutdown(drain=True)
        engine.finish_monitor()  # close the partial drift window
        _save_artifacts()
        _log.info("serve: drained; %d request(s), %d batch(es), "
                  "%d shed, %d post-warmup compile(s)",
                  engine.n_requests, engine.n_batches, engine.n_shed,
                  engine.post_warmup_compiles)
    return 0
