"""Async micro-batching admission queue for the serving engine.

Requests enter a BOUNDED queue; a dispatcher thread gathers them into the
largest batch that fills within ``max_wait_ms`` (or up to ``max_batch``,
whichever first) and drives one :meth:`ServingEngine.score_batch` call —
the engine pads the gathered batch up to its bucket ladder. The tradeoff
is explicit: waiting longer fills bigger buckets (throughput), waiting
less bounds the queue-wait term of tail latency; both ends are visible in
the engine's ``serve_queue_wait`` histogram.

Backpressure is load-shedding, not unbounded buffering: a full queue
raises the typed :class:`Overloaded` (HTTP 503 at the frontend) instead
of growing the queue until every request times out. Validation runs at
submit time (``engine.validate_record`` — the typed 400 errors of
local/scoring), so a malformed record is rejected before admission and
can never poison a batch that other requests share.

Shutdown is a graceful drain by default: new submissions are refused,
everything already admitted is scored, then the dispatcher exits.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from .reqtrace import BatchTrace, RequestTrace

Record = Dict[str, Any]


class Overloaded(RuntimeError):
    """Typed load-shed: the admission queue is full. Clients should back
    off and retry; the frontend maps this to HTTP 503."""

    def __init__(self, queue_len: int, max_queue: int):
        self.queue_len = queue_len
        self.max_queue = max_queue
        super().__init__(f"serving queue full ({queue_len}/{max_queue}); "
                         f"request shed")


class _Pending:
    __slots__ = ("record", "t_enq", "done", "result", "error", "trace")

    def __init__(self, record: Record,
                 trace: Optional[RequestTrace] = None):
        self.record = record
        self.t_enq = time.perf_counter()
        self.done = threading.Event()
        self.result: Optional[Record] = None
        self.error: Optional[BaseException] = None
        #: per-request trace record (reqtrace, docs/observability.md):
        #: the dispatcher stamps queue/batch/device segments onto it
        self.trace = trace


class MicroBatcher:
    """Bounded queue + dispatcher thread in front of a ServingEngine."""

    def __init__(self, engine: Any, *, max_batch: Optional[int] = None,
                 max_wait_ms: float = 5.0, max_queue: int = 1024):
        self.engine = engine
        # clamped to the engine's top bucket: a gathered batch must map
        # onto one prewarmed rung (the engine would chunk a bigger list,
        # but pick_bucket on the whole batch is the latency contract)
        self.max_batch = min(int(max_batch or engine.max_batch),
                             int(engine.max_batch))
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self.max_queue = int(max_queue)
        self._q: "collections.deque[_Pending]" = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        #: dispatcher heartbeat (written under _cond each loop pass):
        #: /debugz serves its age — a wedged dispatcher shows up as a
        #: beat that stopped advancing while the queue grows
        self._beat = time.perf_counter()
        self._thread = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._thread.start()

    # -- client side -------------------------------------------------------
    def submit(self, record: Record,
               timeout: Optional[float] = None,
               trace: Optional[RequestTrace] = None) -> Record:
        """Validate, enqueue, block for the scored result.

        Raises the typed validation errors (unknown/missing/invalid
        feature — reject before admission), :class:`Overloaded` on a full
        queue, TimeoutError when `timeout` expires first, RuntimeError
        after shutdown. `trace` (reqtrace) rides the pending slot; the
        dispatcher stamps queue wait + the batch's shared walls onto it."""
        self.engine.validate_record(record)
        p = _Pending(record, trace)
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is shut down")
            if len(self._q) >= self.max_queue:
                qlen = len(self._q)
                self.engine.note_shed(qlen)
                raise Overloaded(qlen, self.max_queue)
            self._q.append(p)
            self._cond.notify_all()
        if not p.done.wait(timeout):
            # withdraw from the queue so an abandoned request is neither
            # scored nor counted, and stops holding queue capacity
            # against live traffic; if it already left the queue it is
            # mid-dispatch — give the race one more look, then discard
            with self._cond:
                try:
                    self._q.remove(p)
                    withdrawn = True
                except ValueError:
                    withdrawn = False
                # reclaim the trace record before raising: past this
                # point the CALLER finishes it, and a mid-dispatch
                # stamp would break the reqtrace single-owner handoff.
                # The dispatcher captures p.trace ONCE per pending, so
                # after this detach at most a stamp already in progress
                # lands — attribute/list ops are CPython-atomic, the
                # record stays structurally sound and can at worst miss
                # the late batch segments of a request that timed out
                # anyway
                p.trace = None
            if withdrawn or not p.done.is_set():
                raise TimeoutError(f"no result within {timeout}s "
                                   f"(queue depth {len(self._q)})")
        if p.error is not None:
            raise p.error
        return p.result  # type: ignore[return-value]

    @property
    def queue_len(self) -> int:
        return len(self._q)

    @property
    def alive(self) -> bool:
        """Dispatcher thread liveness (the /debugz health bit)."""
        return self._thread.is_alive()

    def beat_age(self) -> float:
        """Seconds since the dispatcher last passed the top of its loop
        — near zero on a healthy batcher (it wakes at least every 100ms
        idle); a growing age with a non-empty queue means the dispatcher
        is stuck inside a batch (device hang, lock convoy)."""
        with self._cond:
            return max(time.perf_counter() - self._beat, 0.0)

    # -- dispatcher --------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cond:
                self._beat = time.perf_counter()
                while not self._q and not self._closed:
                    self._cond.wait(0.1)
                    if not self._q and not self._closed:
                        break  # idle beat: tick the monitor off-lock
                if not self._q:
                    if self._closed:
                        return  # closed AND drained
                    batch = None  # idle: no work gathered this beat
                else:
                    batch = [self._q.popleft()]
                    deadline = time.perf_counter() + self.max_wait_s
                    while len(batch) < self.max_batch:
                        if self._q:
                            batch.append(self._q.popleft())
                            continue
                        now = time.perf_counter()
                        if self._closed or now >= deadline:
                            break
                        self._cond.wait(min(deadline - now, 0.05))
            if batch is None:
                # drift-monitor heartbeat (docs/monitoring.md): a
                # `window_seconds` boundary must close even when no
                # traffic arrives to trigger it — the dispatcher is the
                # natural idle thread, and the tick runs OUTSIDE the
                # queue condition so submissions never wait on it
                tick = getattr(self.engine, "monitor_tick", None)
                if tick is not None:
                    tick()
                continue
            self._dispatch(batch)

    def _dispatch(self, batch: List[_Pending]) -> None:
        t_d = time.perf_counter()
        for p in batch:
            self.engine.observe_queue_wait(t_d - p.t_enq)
        # one BatchTrace per traced dispatch: the engine fills the
        # shared assemble/device/monitor walls, every traced rider gets
        # them stamped below (an untraced batch allocates nothing)
        bt = (BatchTrace()
              if any(p.trace is not None for p in batch) else None)
        try:
            bucket = self.engine.pick_bucket(len(batch))
            records = [p.record for p in batch]
            # keyword only when tracing: duck-typed engine stands-ins
            # (tests, adapters) keep their plain score_batch signature
            results = (self.engine.score_batch(records) if bt is None
                       else self.engine.score_batch(records,
                                                    batch_trace=bt))
        except BaseException as e:
            # submit-time validation already rejected record-level
            # problems, so a failure here is systemic — every waiter of
            # THIS batch gets the typed cause instead of hanging
            for p in batch:
                tr = p.trace  # ONE read: a timeout may null it out
                if tr is not None:
                    tr.seg("queue", t_d - p.t_enq)
                p.error = e
                p.done.set()
            return
        t_end = time.perf_counter()
        for p, r in zip(batch, results):
            tr = p.trace  # ONE read: a timed-out submit reclaims it
            if tr is not None:
                tr.seg("queue", t_d - p.t_enq)
                if bt is not None:
                    bt.stamp(tr)
            p.result = r
            p.done.set()
            self.engine.observe_request(t_end - p.t_enq, bucket)

    # -- lifecycle ---------------------------------------------------------
    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting; drain=True scores everything already queued
        before the dispatcher exits, drain=False fails queued requests
        with RuntimeError immediately."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._q:
                    p = self._q.popleft()
                    p.error = RuntimeError("batcher shut down before "
                                           "this request was scored")
                    p.done.set()
            self._cond.notify_all()
        self._thread.join(timeout)

    @property
    def closed(self) -> bool:
        return self._closed
