# tmoglint: disable-file=THR001  single-owner record structs: a
# RequestTrace/BatchTrace is owned by one thread at a time, handed off
# through the batcher's done Event (happens-before; see "Ownership
# model" below); all genuinely shared state in this file is lock-guarded
"""Per-request distributed tracing across the serving fleet.

The fleet serves one request through four hops — router → replica
frontend → micro-batcher → engine (+ monitor) — and merged histograms
cannot say WHERE a p99 spike lives: queue wait, batch padding, device
wall, or monitor observe. This module is the request-level layer that
can (docs/observability.md "Request tracing"):

- the router MINTS a trace id and propagates it to the serving replica
  via the ``X-Tmog-Trace`` HTTP header; the replica echoes the header
  back stamped with its replica id, so one id names the whole chain;
- every hop stamps monotonic SEGMENT durations onto a flat, slotted
  :class:`RequestTrace` record — one ``perf_counter`` read + one list
  append per mark, NO span-tree nodes on the hot path (the PR 7 span
  budget contract holds under unbounded traffic). Durations only cross
  the process boundary, never absolute timestamps: two hosts' clocks
  are not comparable, two durations are;
- TAIL-BASED sampling decides at COMPLETION, when the request's fate is
  known: errors, sheds, retries, shadow-mirror drops and anything past
  the live latency-SLO quantile are always kept; the rest keep with
  probability ``TMOG_TRACE_SAMPLE``. Kept traces land as
  ``request_trace`` events on events.jsonl, in the bounded kept ring
  (``GET /requests``), and — when span collection is on — as a
  per-tracer LANE in the Chrome trace export;
- every segment also feeds a :class:`LatencyHistogram` (exact
  bucket-sum mergeable, PR 11): the fleet ``/requests`` endpoint pools
  per-replica segment histograms the same way ``/metrics`` pools
  latency — sufficient statistics, the DrJAX MapReduce shape host-side;
- a :class:`~transmogrifai_tpu.utils.metrics.GaugeRing` of periodic
  gauge snapshots (queue depth, in-flight, shed, post-warmup compiles,
  drift verdicts) backs ``GET /metrics/history``.

Ownership model (why the record structs carry no locks): a
RequestTrace / BatchTrace is owned by exactly ONE thread at a time —
the request's handler thread creates it, the batcher's dispatcher
stamps it between the queue pop and ``done.set()``, and the handler
resumes only after ``done.wait()`` — every handoff happens-before
through that Event, so field access is single-owner by construction
and a lock would buy nothing on the hot path. The one exit that skips
the Event — a submit() timeout racing a dispatch — RECLAIMS the trace
(nulls the pending's slot under the batcher's condition; the
dispatcher reads it once), so at worst a stamp already in progress
lands on a structurally-sound record that is missing late segments.
Everything genuinely SHARED (ReqTracer's counters, the kept ring, the
histograms, the gauge ring) is locked.
"""
from __future__ import annotations

import os
import random
import sys
import threading
import time
import traceback
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.metrics import GaugeRing, LatencyHistogram, collector

__all__ = [
    "TRACE_HEADER", "DEBUG_SLEEP_HEADER", "SEGMENTS", "mint_trace_id",
    "parse_trace_header", "format_trace_header", "env_enabled",
    "RequestTrace", "BatchTrace", "TailSampler", "ReqTracer",
    "GaugeSampler", "thread_dump",
]

#: the hop-context header: request carries ``<trace_id>``, the reply
#: echoes ``<trace_id>;replica=<replica_id>`` so the caller learns WHO
#: served it without parsing the body
TRACE_HEADER = "X-Tmog-Trace"
#: test/chaos hook: when the replica runs with TMOG_DEBUG_SLEEP_MAX_MS
#: > 0, this header makes /score sleep (bounded) before scoring — the
#: ci.sh smoke injects its "artificially slow request" through it
DEBUG_SLEEP_HEADER = "X-Tmog-Debug-Sleep"

#: the segment glossary (docs/observability.md): histograms for these
#: are preallocated so the hot path never mutates the hist dict
SEGMENTS = ("parse", "validate", "queue", "batch", "device", "monitor",
            "debug_sleep", "respond", "route", "upstream")

_HEX = frozenset("0123456789abcdef")


def mint_trace_id() -> str:
    """16 hex chars of a uuid4 — unique across the fleet for any
    realistic retention window."""
    return uuid.uuid4().hex[:16]


def parse_trace_header(value: Optional[str]
                       ) -> Tuple[Optional[str], Dict[str, str]]:
    """(trace_id, attrs) from an ``X-Tmog-Trace`` value; (None, {}) when
    absent or malformed — a garbage header mints a fresh id rather than
    poisoning the corpus with unparseable keys."""
    if not value:
        return None, {}
    parts = str(value).split(";")
    tid = parts[0].strip().lower()
    if not tid or len(tid) > 32 or not set(tid) <= _HEX:
        return None, {}
    attrs: Dict[str, str] = {}
    for p in parts[1:]:
        if "=" in p:
            k, v = p.split("=", 1)
            attrs[k.strip()] = v.strip()
    return tid, attrs


def format_trace_header(trace_id: str, **attrs: Any) -> str:
    out = str(trace_id)
    for k, v in attrs.items():
        if v is not None:
            out += f";{k}={v}"
    return out


def env_enabled() -> bool:
    """Process-wide request-tracing kill switch (TMOG_REQTRACE=0)."""
    return os.environ.get("TMOG_REQTRACE", "1").strip().lower() \
        not in ("0", "off", "false", "no")


class RequestTrace:
    """One request's flat trace record.

    Slotted and preallocated at admission — the request path pays one
    object construction, then one ``(name, seconds)`` append per
    segment mark. The record is NOT a span tree; kept records are
    converted to lane spans once, at completion, off the latency path.
    Batch-level walls (assemble, device, monitor) are SHARED across
    every request of the batch by design: each rider really did wait
    out the whole device wall, so per-request segment sums still cover
    per-request e2e walls."""

    __slots__ = ("trace_id", "origin", "t0", "segs", "status",
                 "error_type", "shed", "retries", "shadow_dropped",
                 "bucket", "rows", "pad_fraction", "replica", "wall_s",
                 "kept")

    def __init__(self, trace_id: str, origin: str) -> None:
        self.trace_id = trace_id
        self.origin = origin           # "router" | "replica"
        self.t0 = time.perf_counter()
        self.segs: List[Tuple[str, float]] = []
        self.status: Optional[int] = None
        self.error_type: Optional[str] = None
        self.shed = False
        self.retries = 0
        self.shadow_dropped = False
        self.bucket: Optional[int] = None
        self.rows = 1
        self.pad_fraction: Optional[float] = None
        self.replica: Optional[str] = None
        self.wall_s = 0.0
        self.kept: Optional[str] = None

    def seg(self, name: str, seconds: float) -> None:
        """Stamp one segment duration (monotonic-clock arithmetic done
        by the caller; negatives clamp to 0 rather than corrupting the
        coverage sums)."""
        self.segs.append((name, max(float(seconds), 0.0)))

    def segments_ms(self) -> Dict[str, float]:
        """Segment durations in ms, same-name marks summed (a retried
        request has two `upstream` marks; their total is what covered
        the wall)."""
        out: Dict[str, float] = {}
        for name, s in self.segs:
            out[name] = out.get(name, 0.0) + s * 1e3
        return {k: round(v, 3) for k, v in out.items()}

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "trace_id": self.trace_id,
            "origin": self.origin,
            "replica": self.replica,
            "status": self.status,
            "wall_ms": round(self.wall_s * 1e3, 3),
            "segments": self.segments_ms(),
        }
        if self.kept is not None:
            out["kept"] = self.kept
        if self.error_type:
            out["error_type"] = self.error_type
        if self.shed:
            out["shed"] = True
        if self.retries:
            out["retries"] = self.retries
        if self.shadow_dropped:
            out["shadow_dropped"] = True
        if self.bucket is not None:
            out["bucket"] = self.bucket
        if self.rows != 1:
            out["rows"] = self.rows
        if self.pad_fraction is not None:
            out["pad_fraction"] = round(self.pad_fraction, 4)
        return out


class BatchTrace:
    """Per-dispatch batch accounting the engine fills while scoring: the
    assemble/device/monitor walls every request of the batch shares,
    plus pad accounting. One slotted object per TRACED dispatch (the
    batcher allocates it only when at least one rider carries a trace);
    bulk requests accumulate across the engine's internal max-bucket
    chunks."""

    __slots__ = ("bucket", "rows", "bucket_rows", "assemble_s",
                 "score_s", "monitor_s", "batches", "path")

    def __init__(self) -> None:
        self.bucket: Optional[int] = None
        self.rows = 0
        self.bucket_rows = 0
        self.assemble_s = 0.0
        self.score_s = 0.0
        self.monitor_s = 0.0
        self.batches = 0
        self.path = "bucket"

    def add(self, bucket: int, n: int, assemble_s: float, score_s: float,
            monitor_s: float = 0.0, path: str = "bucket") -> None:
        self.bucket = int(bucket)
        self.rows += int(n)
        self.bucket_rows += int(bucket)
        self.assemble_s += float(assemble_s)
        self.score_s += float(score_s)
        self.monitor_s += float(monitor_s)
        self.batches += 1
        self.path = path

    @property
    def pad_fraction(self) -> float:
        """Fraction of scored device rows that were padding."""
        return ((self.bucket_rows - self.rows) / self.bucket_rows
                if self.bucket_rows else 0.0)

    def stamp(self, rt: RequestTrace) -> None:
        """Write this batch's shared walls onto one rider's record."""
        rt.seg("batch", self.assemble_s)
        rt.seg("device", self.score_s)
        if self.monitor_s:
            rt.seg("monitor", self.monitor_s)
        rt.bucket = self.bucket
        rt.pad_fraction = self.pad_fraction


class TailSampler:
    """Keep/drop decided at request COMPLETION (tail-based sampling).

    Head-based sampling throws away exactly the traces worth keeping —
    the decision fires before anyone knows the request will shed, error,
    retry, or land in the tail. This sampler sees the outcome: errors
    (4xx/5xx/exception), sheds, retries and shadow-mirror drops are
    ALWAYS kept; anything at or past the live SLO quantile of the e2e
    histogram is kept as "slow"; the rest keep with probability `rate`.
    The SLO threshold is re-read from the shared histogram every
    `refresh` observations (a quantile walk is ~60 bucket reads — cheap,
    but not free per request)."""

    def __init__(self, hist: LatencyHistogram, *, rate: float = 0.01,
                 slo_quantile: float = 0.99,
                 min_count: Optional[int] = None,
                 refresh: int = 64) -> None:
        self.hist = hist
        self.rate = max(float(rate), 0.0)
        self.slo_quantile = float(slo_quantile)
        if min_count is None:
            # TMOG_TRACE_SLO_MIN_COUNT: how many observations before
            # the tail threshold is trusted — small fleets/smokes lower
            # it so a "slow" verdict exists within their traffic volume
            try:
                min_count = int(os.environ.get(
                    "TMOG_TRACE_SLO_MIN_COUNT", "200"))
            except ValueError:
                min_count = 200
        self.min_count = int(min_count)
        self.refresh = max(int(refresh), 1)
        self._lock = threading.Lock()
        self._cached_slo: Optional[float] = None
        self._cached_at = -1

    def slow_threshold(self) -> Optional[float]:
        """Current SLO-latency threshold in seconds, or None while the
        histogram has too few observations to estimate a tail."""
        count = self.hist.count
        if count < self.min_count:
            return None
        with self._lock:
            if self._cached_slo is None or \
                    count - self._cached_at >= self.refresh:
                self._cached_slo = self.hist.quantile(self.slo_quantile)
                self._cached_at = count
            return self._cached_slo

    def decide(self, rt: RequestTrace) -> Optional[str]:
        """The keep reason, or None to drop. Precedence: the rarest,
        most diagnostic outcomes first."""
        status = rt.status or 0
        if rt.shed or status == 503:
            return "shed"
        if rt.error_type is not None or status >= 400:
            return "error"
        if rt.retries:
            return "retry"
        if rt.shadow_dropped:
            return "shadow_drop"
        thr = self.slow_threshold()
        if thr is not None and rt.wall_s >= thr:
            return "slow"
        if self.rate > 0.0 and random.random() < self.rate:
            return "sample"
        return None


class ReqTracer:
    """Per-process request tracer: one per replica (and one in the
    router). Owns the mergeable aggregates — per-segment
    LatencyHistograms + counters — the bounded kept-trace ring, the
    tail sampler, and the lane export of kept traces into the span
    tree. Disabled (`enabled=False`), :meth:`start` returns None and
    the request path pays one attribute read."""

    def __init__(self, replica_id: str, *, origin: str = "replica",
                 enabled: bool = True,
                 sample_rate: Optional[float] = None,
                 slo_quantile: float = 0.99, keep: int = 64,
                 span_budget: Optional[int] = None) -> None:
        self.replica_id = str(replica_id)
        self.origin = origin
        self.enabled = bool(enabled)
        if sample_rate is None:
            try:
                sample_rate = float(os.environ.get("TMOG_TRACE_SAMPLE",
                                                   "0.01"))
            except ValueError:
                sample_rate = 0.01
        # preallocated segment families (the hot path never inserts)
        self.hist: Dict[str, LatencyHistogram] = {
            "e2e": LatencyHistogram("req_e2e")}
        for name in SEGMENTS:
            self.hist[name] = LatencyHistogram(f"req_{name}")
        self.sampler = TailSampler(self.hist["e2e"], rate=sample_rate,
                                   slo_quantile=slo_quantile)
        self._lock = threading.Lock()
        self.kept: "deque[Dict[str, Any]]" = deque(maxlen=int(keep))
        self.n_traces = 0
        self.n_kept = 0
        self.kept_by_reason: Dict[str, int] = {}
        self.in_flight = 0
        if span_budget is None:
            try:
                span_budget = int(os.environ.get(
                    "TMOG_REQTRACE_SPAN_BUDGET", "1000"))
            except ValueError:
                span_budget = 1000
        self._span_budget = int(span_budget)
        self._spans = 0

    # -- request lifecycle --------------------------------------------------
    def start(self, header: Optional[str] = None
              ) -> Optional[RequestTrace]:
        """A fresh RequestTrace (None when tracing is off): adopts the
        inbound header's trace id when one arrived (the router minted
        it, or the client supplied its own), mints otherwise."""
        if not self.enabled:
            return None
        tid, _ = parse_trace_header(header)
        rt = RequestTrace(tid or mint_trace_id(), self.origin)
        with self._lock:
            self.n_traces += 1
            self.in_flight += 1
        return rt

    def finish(self, rt: Optional[RequestTrace],
               wall_s: Optional[float] = None,
               status: Optional[int] = None,
               error_type: Optional[str] = None) -> Optional[str]:
        """Complete one record: stamp outcome, feed the segment
        histograms, run the tail sampler, and — only for KEPT traces —
        emit the event + lane spans. Returns the keep reason (None when
        dropped). None-safe so callers can finish unconditionally."""
        if rt is None:
            return None
        rt.wall_s = (float(wall_s) if wall_s is not None
                     else time.perf_counter() - rt.t0)
        if status is not None:
            rt.status = int(status)
        if error_type:
            rt.error_type = error_type
        if rt.replica is None and self.origin == "replica":
            rt.replica = self.replica_id
        # O(1) aggregate updates — these run for EVERY request; the
        # histograms carry their own locks
        self.hist["e2e"].record(rt.wall_s)
        for name, dur in rt.segs:
            h = self.hist.get(name)
            if h is None:
                with self._lock:
                    h = self.hist.setdefault(name,
                                             LatencyHistogram(
                                                 f"req_{name}"))
            h.record(dur)
        reason = self.sampler.decide(rt)
        with self._lock:
            self.in_flight = max(self.in_flight - 1, 0)
            if reason is not None:
                rt.kept = reason
                self.n_kept += 1
                self.kept_by_reason[reason] = \
                    self.kept_by_reason.get(reason, 0) + 1
                self.kept.append(rt.to_json())
        if reason is not None:
            self._emit(rt)
        return reason

    def _emit(self, rt: RequestTrace) -> None:
        """One kept trace -> a `request_trace` event + (span budget
        permitting) a request window with its segment chain on this
        tracer's LANE of the Chrome trace. Runs after the response was
        sent — never on the request's latency path."""
        collector.event("request_trace", **rt.to_json())
        if not collector.enabled:
            return
        with self._lock:
            if self._spans >= self._span_budget:
                return
            self._spans += 1
        tree = collector.trace
        lane = f"req:{self.replica_id}"
        end = tree.now()
        start = max(end - rt.wall_s, 0.0)
        sp = tree.add_window(
            f"request[{rt.trace_id}]", "request", start, end, lane=lane,
            trace_id=rt.trace_id, status=rt.status, kept=rt.kept,
            replica=rt.replica, error=rt.error_type is not None)
        # segments laid end-to-end inside the request window (their
        # recorded order; unattributed gaps collapse) — clamped so
        # children never escape the parent (trace-report containment)
        cur = start
        for name, dur in rt.segs:
            seg_end = min(cur + dur, end)
            tree.add_window(name, "request_seg", cur, seg_end,
                            parent_span=sp, lane=lane)
            cur = seg_end

    # -- payloads -----------------------------------------------------------
    def requests_payload(self) -> Dict[str, Any]:
        """The ``GET /requests`` body: per-segment histograms (the fleet
        merge unit — exact bucket sums, like /metrics latency), the
        kept-trace ring newest-last, and counters."""
        with self._lock:
            hists = dict(self.hist)
            kept = list(self.kept)
            counters = {"traces": self.n_traces, "kept": self.n_kept,
                        "kept_by_reason": dict(self.kept_by_reason),
                        "in_flight": self.in_flight}
        return {"replica": self.replica_id, "origin": self.origin,
                "enabled": self.enabled,
                "sample_rate": self.sampler.rate,
                # families this process never recorded are omitted (a
                # replica preallocates the router's route/upstream too;
                # serving their empty histograms would make the fleet
                # merge claim segments nobody measured)
                "segments": {nm: h.to_json() for nm, h in hists.items()
                             if h.count or nm == "e2e"},
                "kept": kept, "counters": counters}


class GaugeSampler:
    """Daemon thread appending one gauge snapshot per interval into a
    GaugeRing (``TMOG_GAUGE_INTERVAL_S``, default 1s). The sample
    callable runs OFF the request path on this thread; its failures are
    contained — a gauge bug must not take down sampling, let alone
    serving."""

    def __init__(self, fn: Callable[[], Dict[str, Any]],
                 ring: Optional[GaugeRing] = None,
                 interval_s: Optional[float] = None,
                 maxlen: int = 720) -> None:
        self.fn = fn
        self.ring = ring if ring is not None else GaugeRing(maxlen)
        if interval_s is None:
            try:
                interval_s = float(os.environ.get(
                    "TMOG_GAUGE_INTERVAL_S", "1.0"))
            except ValueError:
                interval_s = 1.0
        self.interval_s = max(float(interval_s), 0.05)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="gauge-sampler", daemon=True)

    def start(self) -> "GaugeSampler":
        self.sample_once()  # history is never empty while serving
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(5.0)

    def sample_once(self) -> None:
        try:
            self.ring.append(**self.fn())
        except Exception:  # noqa: BLE001 - containment is the contract
            pass

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()


def thread_dump(limit_frames: int = 12) -> Dict[str, List[str]]:
    """{thread label: innermost stack frames} for every live thread
    (sys._current_frames) — the core of ``GET /debugz``, the "why is it
    stuck" snapshot: a wedged dispatcher or a lock convoy is visible as
    the frame every thread is parked on."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        frames = [f"{os.path.basename(fs.filename)}:{fs.lineno} {fs.name}"
                  for fs in traceback.extract_stack(frame)[-limit_frames:]]
        out[f"{names.get(ident, 'unknown')} ({ident})"] = frames
    return out
