"""ServingEngine: shape-bucketed, AOT-prewarmed scoring executables.

The batch score path compiles one XLA program per (layer, batch shape);
a server that accepts arbitrary batch sizes would compile on the request
path — exactly the cold-start the ROADMAP flags (11.6s cold vs 2.7s warm
for a 1M-row score, BENCH_TPU_R5). The fix is the same ahead-of-time
lower/compile discipline pjit training uses (PAPERS arxiv 2204.06514):

- a POWER-OF-TWO BUCKET LADDER (1, 8, 16, …, max_batch — the PR 3
  ``bucket_lanes`` idea applied to the batch axis): every request batch
  pads up to the smallest bucket that holds it, so the set of shapes the
  device ever sees is fixed and finite;
- PREWARM compiles every bucket once at startup by scoring a template
  batch through :meth:`WorkflowModel.score_fixed`. With the persistent
  compilation cache active (utils/platform.enable_compilation_cache,
  ``TMOG_COMPILE_CACHE_DIR``) the SECOND process start is all cache
  hits: ``serve --prewarm-only`` at deploy time means production
  restarts perform zero XLA compiles;
- PREALLOCATED INPUT BUFFERS per bucket: the raw-feature columns are
  allocated once and refilled in place per batch (the host-side analogue
  of the tileplane's donated carry — across the H2D boundary XLA owns
  the copy, so reuse on the host side is where allocation can actually
  be saved);
- a RECOMPILE WATCH: after warmup the engine samples the PR 4
  RecompileTracker after every batch; any compile that lands post-warmup
  increments ``post_warmup_compiles`` and emits a ``serve_recompile``
  event, which ``trace-report --check`` treats as a failure — "zero
  recompiles under traffic" is pinned at runtime, not asserted.

Observability: per-batch ``batch_assemble``/``device_score`` spans (span
emission stops after TMOG_SERVE_SPAN_BUDGET batches so the in-memory
tree stays bounded under traffic; histograms and events continue),
``serve_batch``/``serve_prewarm``/``serve_recompile`` events, and
streaming-quantile latency histograms (utils/metrics.LatencyHistogram)
that both the ``/metrics`` endpoint and bench.py --serving read.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import Column, Dataset, column_from_values
from ..filters.sketches import numeric_value as _numeric_value
from ..local.scoring import record_validator, score_function
from ..local.scoring import _extract as _extract_typed
from ..types import ColumnKind
from ..utils import tracing
from ..utils.metrics import LatencyHistogram, collector
from ..workflow.io import (load_serve_manifest, manifest_stamp,
                           save_serve_manifest, verify_serve_manifest)

Record = Dict[str, Any]

_log = logging.getLogger("transmogrifai_tpu.serve")

DEFAULT_MAX_BATCH = 64
#: first ladder rung above the single-record bucket (PR 3 bucket_lanes
#: floor): buckets 2..7 would each buy <1 row of padding saved per
#: request at the cost of one more compiled program per layer
_BUCKET_FLOOR = 8

_NUMERIC_KINDS = (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL)


def bucket_ladder(max_batch: int,
                  floor: Optional[int] = None) -> Tuple[int, ...]:
    """(1, 8, 16, …, 2^ceil(log2(max_batch))): the fixed batch shapes the
    engine compiles. The top rung rounds max_batch UP to a power of two —
    padding a full batch beats compiling an off-power shape. ``floor``
    overrides the first rung above the single-record bucket (the
    planner's serve_bucket_floor decision; default `_BUCKET_FLOOR`)."""
    mb = max(int(max_batch), 1)
    rungs = [1]
    if mb == 1:
        return (1,)
    b = max(int(floor) if floor else _BUCKET_FLOOR, 2)
    while b < mb:
        rungs.append(b)
        b *= 2
    rungs.append(b)
    return tuple(rungs)


def planned_bucket_ladder(max_batch: int) -> Tuple[int, ...]:
    """The plan-time ladder (docs/planning.md): the planner may move the
    floor rung from measured per-bucket dispatch walls; a cold corpus
    (or TMOG_PLAN=0) yields exactly ``bucket_ladder(max_batch)``. Any
    planner fault degrades to the hand ladder — serving startup must
    never depend on corpus health."""
    try:
        from ..planner.plan import plan_serving
        return plan_serving(max_batch).buckets
    except Exception:
        return bucket_ladder(max_batch)


_TEMPLATE_BY_KIND = {
    ColumnKind.FLOAT: 0.0,
    ColumnKind.INT: 0,
    ColumnKind.BOOL: False,
    ColumnKind.STRING: "",
    ColumnKind.STRING_LIST: [],
    ColumnKind.FLOAT_LIST: [],
    ColumnKind.STRING_SET: [],
    ColumnKind.MAP: {},
    ColumnKind.GEO: None,
    ColumnKind.VECTOR: None,
}


def template_record(raw_features: Sequence[Any]) -> Record:
    """A syntactically-valid record for prewarm batches: one neutral value
    per predictor feature (responses are never extracted at serving
    time). Values only shape the compiled programs — the scores of a
    prewarm batch are discarded."""
    return {f.name: _TEMPLATE_BY_KIND.get(f.feature_type.column_kind)
            for f in raw_features if not f.is_response}


class ServingEngine:
    """Loads (or wraps) a fitted WorkflowModel and serves fixed-shape
    score batches through prewarmed executables.

    `model`: a WorkflowModel or a saved-model directory path.
    `buckets`/`example` default from the model dir's ``serve.json``
    prewarm manifest when present (written by ``serve --prewarm-only``),
    else from `max_batch` / :func:`template_record`.
    `single_record="local"` routes batch-of-one requests through the
    pure-Python ``local/scoring.score_function`` replay instead of the
    bucket-1 executable — for small models the host replay can undercut
    device dispatch latency (tiny/odd-shape fallback; parity between the
    two paths is test-pinned).
    """

    def __init__(self, model: Any, *, max_batch: int = DEFAULT_MAX_BATCH,
                 buckets: Optional[Sequence[int]] = None,
                 example: Optional[Record] = None,
                 single_record: str = "bucket",
                 strict_keys: bool = True,
                 monitor: Optional[Any] = None):
        if isinstance(model, str):
            from ..workflow.workflow import WorkflowModel
            model = WorkflowModel.load(model)
        self.model = model
        manifest = load_serve_manifest(getattr(model, "source_path", None))
        if buckets is None and manifest and manifest.get("buckets"):
            buckets = [int(b) for b in manifest["buckets"]]
        if example is None and manifest and \
                isinstance(manifest.get("example"), dict):
            example = manifest["example"]
        # explicit buckets / manifest ladders are hand plans and win
        # outright; only the defaulted ladder consults the planner
        self.buckets: Tuple[int, ...] = (
            tuple(sorted({int(b) for b in buckets})) if buckets
            else planned_bucket_ladder(max_batch))
        if self.buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1: {self.buckets}")
        # manifest freshness (docs/fleet.md "The manifest contract"):
        # the stamp written at --prewarm-only time must still describe
        # THIS artifact, or the prewarm silently misses the persistent
        # cache. A mismatch is a warning here; `serve --strict-manifest`
        # (and every fleet replica) refuses to start on it.
        self.manifest_mismatch: List[str] = verify_serve_manifest(
            getattr(model, "source_path", None), manifest)
        if manifest and manifest.get("buckets") and \
                self.buckets != tuple(sorted({int(b) for b
                                              in manifest["buckets"]})):
            self.manifest_mismatch.append(
                f"bucket ladder {list(self.buckets)} != manifest "
                f"{manifest['buckets']} (prewarmed executables cover "
                f"different shapes)")
        if self.manifest_mismatch:
            _log.warning("serve: STALE serve.json manifest — %s. Re-run "
                         "`serve --prewarm-only` after saving the model.",
                         "; ".join(self.manifest_mismatch))
        self.max_batch = self.buckets[-1]
        if single_record not in ("bucket", "local"):
            raise ValueError("single_record must be 'bucket' or 'local'")
        self.single_record = single_record

        self.raw = model.raw_features()
        self._predictors = [(f, f.origin_stage) for f in self.raw
                            if not f.is_response]
        self._result_types = {f.name: f.feature_type
                              for f in model.result_features}
        self.example: Record = (dict(example) if example
                                else template_record(self.raw))
        #: typed 400-class validation (local/scoring.record_validator) —
        #: the batcher runs it BEFORE admission so one bad record can
        #: never poison a batch
        self.validate_record = record_validator(model,
                                                strict_keys=strict_keys)
        self._local_fn: Optional[Callable[[Record], Record]] = (
            score_function(model) if single_record == "local" else None)

        # preallocated per-bucket raw-feature columns (filled in place)
        self._buffers: Dict[int, Dict[str, Column]] = {}
        # serializes device scoring AND buffer reuse: batches from the
        # micro-batcher, bulk submit_many calls and prewarm never
        # interleave on the same buffers
        self._lock = threading.RLock()
        # leaf lock for the shared counters below: note_shed/observe_*
        # fire from every HTTP worker thread and the dispatcher, and
        # /metrics reads from yet another — a bare `+= 1` loses updates
        # under contention. Always acquired AFTER _lock, never around
        # device work (THR003: the global order is _lock -> _stat_lock)
        self._stat_lock = threading.Lock()

        self.hist: Dict[str, LatencyHistogram] = {
            "total": LatencyHistogram("serve_total"),
            "queue_wait": LatencyHistogram("serve_queue_wait"),
            "batch_assemble": LatencyHistogram("serve_batch_assemble"),
            "device_score": LatencyHistogram("serve_device_score"),
            "monitor_observe": LatencyHistogram("serve_monitor_observe"),
        }
        self.n_requests = 0
        self.n_batches = 0
        self.n_rows = 0
        #: pad accounting (the request-tracing segment decomposition,
        #: docs/observability.md): bucket_rows = device rows actually
        #: scored (incl. padding), pad_rows = the padding share — both
        #: plain sums, so the fleet merge is exact and
        #: pad_rows / bucket_rows is the fleet-wide pad fraction
        self.pad_rows = 0
        self.bucket_rows = 0
        self.n_shed = 0
        self.warm = False
        self.post_warmup_compiles = 0
        #: prewarm() summary, re-served under /metrics "prewarm": the
        #: fleet supervisor reads compiles/cache_hits off a restarted
        #: replica to assert the compile-free-rejoin contract from the
        #: RecompileTracker's counters rather than from log lines
        self.prewarm_summary: Optional[Dict[str, Any]] = None
        self._warm_compiles = 0
        self._anchor = None
        self._span_budget = int(os.environ.get("TMOG_SERVE_SPAN_BUDGET",
                                               "10000"))

        # -- drift monitor (monitor/window.ServeMonitor, docs/monitoring.md)
        # Observations run under self._lock after each scored batch: the
        # numeric sketch is an ASYNC device dispatch (nothing fetched
        # until a window rolls over), the hash/prediction paths are
        # host-side sums on the thread that assembled the batch. A
        # monitor whose profile names a feature this model lacks is
        # refused up front — comparing misaligned columns would report
        # garbage drift.
        self.monitor = monitor
        self.monitor_errors = 0
        #: set by _monitor_fault after repeated observation failures:
        #: observation stops, but the monitor object (and its counters,
        #: /metrics block and /drift report) stay visible — evidence of
        #: WHY the drift series stopped must not vanish with it
        self.monitor_disabled = False
        self._gen_by_name = {f.name: gen for f, gen in self._predictors}
        if monitor is not None:
            missing = (set(monitor.numeric_names)
                       | set(monitor.hashed_names)) - set(self._gen_by_name)
            if missing:
                _log.warning("serve: monitor profile names features this "
                             "model lacks (%s); monitoring DISABLED",
                             sorted(missing))
                self.monitor = None

    # -- buckets -----------------------------------------------------------
    def pick_bucket(self, n: int) -> int:
        """Smallest bucket >= n (n must fit the top rung)."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} exceeds max bucket "
                         f"{self.max_batch}")

    # -- assembly ----------------------------------------------------------
    def _bucket_columns(self, bucket: int) -> Dict[str, Column]:
        cols = self._buffers.get(bucket)
        if cols is None:
            cols = {}
            for f in self.raw:
                kind = f.feature_type.column_kind
                if kind == ColumnKind.VECTOR:
                    continue  # rare raw vectors: built fresh per batch
                if kind in _NUMERIC_KINDS:
                    arr = np.full(bucket, np.nan, np.float64)
                else:
                    arr = np.empty(bucket, dtype=object)
                # responses stay all-missing forever (serving records are
                # unlabeled); predictors refill per batch
                cols[f.name] = Column(kind=kind, data=arr)
            self._buffers[bucket] = cols
        return cols

    def _assemble(self, records: List[Record], bucket: int) -> Dataset:
        """Raw-feature Dataset for one padded batch, written into the
        bucket's preallocated buffers. Caller holds self._lock."""
        cols = dict(self._bucket_columns(bucket))
        for f, gen in self._predictors:
            col = cols.get(f.name)
            if col is None:  # vector-kind raw feature: no reusable buffer
                cols[f.name] = column_from_values(
                    f.feature_type, [_extract_typed(gen, r)
                                     for r in records])
                continue
            data = col.data
            if col.kind in _NUMERIC_KINDS:
                for i, rec in enumerate(records):
                    data[i] = _numeric_value(_extract_typed(gen, rec))
            else:
                for i, rec in enumerate(records):
                    data[i] = _extract_typed(gen, rec)
        return Dataset(cols, n_rows=bucket)

    # -- scoring -----------------------------------------------------------
    def score_batch(self, records: Sequence[Record],
                    batch_trace: Optional[Any] = None) -> List[Record]:
        """Score records through the bucket ladder; returns one
        {result_feature: value} dict per record (same row shapes as the
        local per-record path — map-typed predictions unpack to dicts).
        Batches above the top bucket chunk into max-bucket slices.
        `batch_trace` (reqtrace.BatchTrace) receives the batch's shared
        assemble/device/monitor walls + pad accounting; chunked bulk
        accumulates across the slices."""
        records = list(records)
        if not records:
            return []
        if len(records) > self.max_batch:
            out: List[Record] = []
            for s in range(0, len(records), self.max_batch):
                out.extend(self.score_batch(records[s:s + self.max_batch],
                                            batch_trace=batch_trace))
            return out
        with self._stat_lock:
            warm = self.warm
        if len(records) == 1 and self._local_fn is not None and warm:
            t0 = time.perf_counter()
            res = self._local_fn(records[0])  # host replay: no device lock
            row = self._local_row(res)
            t1 = time.perf_counter()
            mon_s = 0.0
            with self._lock:  # counters/histograms share the lock though
                self._observe_batch(1, 1, 0.0, t1 - t0, path="local")
                if self.monitor is not None and not self.monitor_disabled:
                    self._observe_monitor_record(records[0], row)
                    mon_s = time.perf_counter() - t1
                    self._observe_monitor_wall(mon_s)
            if batch_trace is not None:
                batch_trace.add(1, 1, 0.0, t1 - t0, monitor_s=mon_s,
                                path="local")
            return [row]
        n = len(records)
        bucket = self.pick_bucket(n)
        # pad by repeating the last record: real values keep every
        # stage's numerics on the fast path (readers/streaming pads the
        # same way); pad rows are dropped after scoring
        padded = records + [records[-1]] * (bucket - n)
        with self._lock:
            t0 = time.perf_counter()
            ds = self._assemble(padded, bucket)
            t1 = time.perf_counter()
            # the batch lock EXISTS to serialize device scoring +
            # buffer reuse (docs/serving.md "Lock ownership")
            # tmoglint: disable=THR002  serialized scoring IS the design
            scored = self.model.score_fixed(ds)
            from ..readers.streaming import _row_value
            cols = [(nm, scored.column(nm), t)
                    for nm, t in self._result_types.items() if nm in scored]
            out = [{nm: _row_value(col, i, t) for nm, col, t in cols}
                   for i in range(n)]
            t2 = time.perf_counter()
            self._observe_batch(bucket, n, t1 - t0, t2 - t1)
            mon_s = 0.0
            if self.monitor is not None and not self.monitor_disabled:
                # the monitor segment measures what the REQUEST PATH
                # pays for observation — the async sketch dispatch +
                # host hash/score sums, NOT the device wall (that is
                # fetched once per window close, off this path)
                self._observe_monitor(ds, out, n, bucket)
                mon_s = time.perf_counter() - t2  # tmoglint: disable=TPU005  dispatch cost IS the measurement
                self._observe_monitor_wall(mon_s)
            self._check_recompiles()
        if batch_trace is not None:
            batch_trace.add(bucket, n, t1 - t0, t2 - t1, monitor_s=mon_s)
        return out

    def _local_row(self, res: Record) -> Record:
        # the local replay yields FeatureType values; normalize maps to
        # plain dicts like the batch unpack does
        return {k: (dict(v.value) if hasattr(v, "value")
                    and isinstance(v.value, dict) else
                    (v.value if hasattr(v, "value") else v))
                for k, v in res.items()}

    def score_record(self, record: Record) -> Record:
        (out,) = self.score_batch([record])
        return out

    # -- drift monitoring --------------------------------------------------
    def _monitor_scores(self, out_rows: Sequence[Record]):
        pred = self.monitor.profile.prediction
        if pred is None:
            return None
        from ..monitor.profile import score_of
        vals = [score_of(r, pred.feature, pred.field) for r in out_rows]
        return np.asarray([v for v in vals if v is not None], np.float64)

    def _observe_monitor(self, ds: Dataset, out_rows: List[Record],
                         n: int, bucket: int) -> None:
        """Feed one scored batch into the window sketches (caller holds
        self._lock). The numeric matrix copies out of the reusable
        buffers (np.stack-to-f32 decouples it before the next batch
        refills them); the device dispatch is async and nothing syncs
        until a window rolls over. Monitoring must never fail a request:
        errors count, log, and after 20 the monitor shuts itself off."""
        mon = self.monitor
        try:
            X = w = None
            if mon.numeric_names:
                X = np.stack([np.asarray(ds.column(nm).data, np.float32)
                              for nm in mon.numeric_names], axis=1)
                w = np.zeros(bucket, np.float32)
                w[:n] = 1.0
            hashed = {nm: ds.column(nm).data[:n]
                      for nm in mon.hashed_names if nm in ds}
            mon.observe_batch(X, w, hashed, self._monitor_scores(out_rows),
                              n)
        except Exception:
            self._monitor_fault()

    def _observe_monitor_record(self, record: Record, row: Record) -> None:
        """Single-record local route: one [1, K] dispatch through the
        bucket-1 sketch executable + the host paths (caller holds
        self._lock)."""
        mon = self.monitor
        try:
            from ..monitor.offline import observe_raw_records
            observe_raw_records(mon, [record], self._gen_by_name)
            scores = self._monitor_scores([row])
            if scores is not None:
                mon.observe_scores(scores)
        except Exception:
            self._monitor_fault()

    def _monitor_fault(self) -> None:
        """Shared observation-failure accounting (both score routes):
        count, log the first few, self-disable after 20 — monitoring
        must never keep taxing a request path it cannot serve."""
        with self._stat_lock:
            self.monitor_errors += 1
            errs = self.monitor_errors
            disable = errs >= 20 and not self.monitor_disabled
            if disable:
                self.monitor_disabled = True
        if errs <= 3:
            _log.exception("serve: drift-monitor observation failed "
                           "(%d)", errs)
        if disable:
            _log.error("serve: drift monitor disabled after %d errors",
                       errs)

    def monitor_tick(self) -> None:
        """Timer-based window rollover for idle periods (the batcher's
        dispatcher calls this between batches so a `window_seconds`
        boundary closes even with no traffic arriving)."""
        with self._stat_lock:
            disabled = self.monitor_disabled
        if self.monitor is None or disabled:
            return
        with self._lock:
            self.monitor.maybe_rollover()

    def finish_monitor(self) -> None:
        """Force-close any partial window (drain/shutdown path)."""
        if self.monitor is None:
            return
        with self._lock:
            self.monitor.maybe_rollover(force=True)

    # -- prewarm -----------------------------------------------------------
    def prewarm(self) -> Dict[str, Any]:
        """Compile (or cache-load) every bucket's executables by scoring
        one template batch per rung, smallest first. Returns a summary
        dict; afterwards the recompile watch is armed."""
        from ..utils.platform import compile_cache_dir

        with self._lock:
            if collector.enabled:
                with self._stat_lock:
                    self._anchor = collector.trace.current()
            t0 = time.perf_counter()
            compiles0 = tracing.tracker.true_compiles
            hits0 = tracing.tracker.total_cache_hits
            per_bucket: List[Dict[str, Any]] = []
            for b in self.buckets:
                tb = time.perf_counter()
                cb0 = tracing.tracker.true_compiles
                recs = [dict(self.example) for _ in range(b)]
                ds = self._assemble(recs, b)
                # prewarm compiles serially under the batch lock BY
                # DESIGN (no traffic is admitted before warm)
                # tmoglint: disable=THR002  deliberate: prewarm owns the lock
                self.model.score_fixed(ds)
                per_bucket.append({
                    "bucket": b,
                    "wall_s": round(time.perf_counter() - tb, 4),
                    "compiles": tracing.tracker.true_compiles - cb0})
            if self.monitor is not None:
                # compile the per-bucket window sketch programs now:
                # monitoring must not add a single post-warmup compile
                # (the zero-recompile contract holds with monitoring on)
                self.monitor.prewarm(self.buckets)
            wall = time.perf_counter() - t0
            with self._stat_lock:
                self.warm = True
                # the watch counts TRUE compiles: persistent-cache loads
                # are not the cold-start cost the ladder exists to
                # eliminate
                self._warm_compiles = tracing.tracker.true_compiles
                self.post_warmup_compiles = 0
            summary = {"buckets": list(self.buckets),
                       "wall_s": round(wall, 4),
                       "compiles": (self._warm_compiles - compiles0
                                    if collector.enabled else None),
                       "cache_hits": (tracing.tracker.total_cache_hits
                                      - hits0 if collector.enabled
                                      else None),
                       "compile_cache_dir": compile_cache_dir(),
                       "per_bucket": per_bucket}
            with self._stat_lock:
                self.prewarm_summary = {
                    "wall_s": summary["wall_s"],
                    "compiles": summary["compiles"],
                    "cache_hits": summary["cache_hits"]}
            collector.event("serve_prewarm", buckets=list(self.buckets),
                            wall_seconds=round(wall, 6),
                            compiles=summary["compiles"],
                            cache_hits=summary["cache_hits"])
            _log.info("serve prewarm: %d bucket(s) %s in %.2fs "
                      "(%s compiles, %s cache hits; cache %s)",
                      len(self.buckets), list(self.buckets), wall,
                      summary["compiles"], summary["cache_hits"],
                      compile_cache_dir() or "inactive")
        return summary

    def write_manifest(self) -> Optional[str]:
        """Persist the prewarm manifest (serve.json) next to the model
        artifact so the next startup prewarms the identical ladder —
        the `serve --prewarm-only` deploy-time contract."""
        src = getattr(self.model, "source_path", None)
        if not src:
            return None
        return save_serve_manifest(src, {
            "buckets": list(self.buckets),
            "max_batch": self.max_batch,
            "single_record": self.single_record,
            "example": self.example,
            # freshness stamp (docs/fleet.md): adoption re-verifies both
            **manifest_stamp(src),
        })

    # -- telemetry ---------------------------------------------------------
    # Counter discipline: every mutable counter below is touched only
    # under _stat_lock — observe_request/note_shed run on HTTP worker
    # threads, _observe_batch on the dispatcher, metrics() on whoever
    # asks. The histograms keep their own internal locks.
    def observe_queue_wait(self, seconds: float) -> None:
        self.hist["queue_wait"].record(seconds)
        collector.latency("serve_queue_wait", seconds)
        with self._stat_lock:
            in_budget = self.n_batches <= self._span_budget
            anchor = self._anchor
        if collector.enabled and in_budget:
            collector.trace.add_complete("queue_wait", "serve", seconds,
                                         parent_span=anchor)

    def observe_request(self, seconds: float, bucket: int) -> None:
        with self._stat_lock:
            self.n_requests += 1
        self.hist["total"].record(seconds)
        collector.latency("serve_total", seconds)
        collector.event("serve_request",
                        wall_ms=round(seconds * 1e3, 3), bucket=bucket)

    def note_shed(self, queue_len: int) -> None:
        with self._stat_lock:
            self.n_shed += 1
            shed_total = self.n_shed
        collector.event("serve_shed", queue_len=queue_len,
                        shed_total=shed_total)

    def _observe_monitor_wall(self, seconds: float) -> None:
        """Book one batch's monitor-observation wall (request-path cost
        of the drift sketches — the `monitor` trace segment)."""
        self.hist["monitor_observe"].record(seconds)
        collector.latency("serve_monitor_observe", seconds)

    def _observe_batch(self, bucket: int, n_valid: int,
                       assemble_s: float, score_s: float,
                       path: str = "bucket") -> None:
        with self._stat_lock:
            self.n_batches += 1
            self.n_rows += n_valid
            self.pad_rows += bucket - n_valid
            self.bucket_rows += bucket
            in_budget = self.n_batches <= self._span_budget
            anchor = self._anchor
        self.hist["batch_assemble"].record(assemble_s)
        self.hist["device_score"].record(score_s)
        collector.latency("serve_batch_assemble", assemble_s)
        collector.latency("serve_device_score", score_s)
        collector.event("serve_batch", bucket=bucket, rows=n_valid,
                        path=path, assemble_ms=round(assemble_s * 1e3, 3),
                        score_ms=round(score_s * 1e3, 3))
        if collector.enabled and in_budget:
            collector.trace.add_complete(
                "batch_assemble", "serve", assemble_s,
                parent_span=anchor, bucket=bucket, rows=n_valid)
            collector.trace.add_complete(
                "device_score", "serve", score_s,
                parent_span=anchor, bucket=bucket, rows=n_valid,
                path=path)

    def _check_recompiles(self) -> None:
        """Post-warmup compile watch: with the tracker active (collection
        enabled), any XLA compile after prewarm is booked and flagged —
        the runtime pin behind the zero-recompiles-under-traffic claim."""
        if not collector.enabled:
            return
        with self._stat_lock:
            if not self.warm:
                return
            delta = tracing.tracker.true_compiles - self._warm_compiles
            new = delta - self.post_warmup_compiles
            if new > 0:
                self.post_warmup_compiles = delta
        if new > 0:
            collector.event("serve_recompile", compiles=new,
                            total_post_warmup=delta)
            _log.warning("serve: %d XLA compile(s) landed AFTER warmup "
                         "(total %d) — a request shape escaped the "
                         "bucket ladder", new, delta)

    def metrics(self) -> Dict[str, Any]:
        """Counters + latency quantiles, the /metrics payload (and the
        source bench.py --serving reads instead of re-timing)."""
        with self._stat_lock:
            out = {"warm": self.warm,
                   "buckets": list(self.buckets),
                   "max_batch": self.max_batch,
                   "single_record": self.single_record,
                   "requests": self.n_requests,
                   "batches": self.n_batches,
                   "rows": self.n_rows,
                   "pad_rows": self.pad_rows,
                   "bucket_rows": self.bucket_rows,
                   "shed": self.n_shed,
                   "post_warmup_compiles": self.post_warmup_compiles,
                   "prewarm": self.prewarm_summary,
                   "monitor_disabled": self.monitor_disabled,
                   "monitor_errors": self.monitor_errors}
        out["latency"] = {k: h.to_json() for k, h in self.hist.items()}
        disabled = out.pop("monitor_disabled")
        if self.monitor is not None:
            out["monitor"] = self.monitor.metrics()
            out["monitor"]["disabled"] = disabled
        else:
            out.pop("monitor_errors")
        return out

    def gauge_state(self) -> Dict[str, Any]:
        """One cheap gauge snapshot (counters only, no histogram
        serialization) — the GaugeSampler's per-interval read for the
        ``GET /metrics/history`` ring (docs/observability.md)."""
        with self._stat_lock:
            out: Dict[str, Any] = {
                "requests": self.n_requests,
                "rows": self.n_rows,
                "shed": self.n_shed,
                "post_warmup_compiles": self.post_warmup_compiles,
                "warm": self.warm}
        mon = self.monitor
        if mon is not None:
            out.update(mon.gauge_state())
        return out
