"""Production serving over a fitted workflow (docs/serving.md).

Reference: the local/ module's OpWorkflowModelLocal pitched low-millisecond
per-record scoring without a Spark session. This package is that pitch
rebuilt for the XLA runtime, plus what a real server needs on top:

- :mod:`engine` — ServingEngine: one fixed-shape scoring executable per
  power-of-two batch bucket, AOT-prewarmed (and persistent-cache-backed,
  so restarts skip XLA entirely), preallocated reused input buffers, a
  post-warmup recompile watch riding the PR 4 RecompileTracker;
- :mod:`batcher` — MicroBatcher: bounded admission queue, micro-batches
  that dispatch when full or after ``max_wait_ms``, typed
  :class:`~transmogrifai_tpu.serve.batcher.Overloaded` load-shedding and
  graceful drain;
- :mod:`frontend` — dependency-light stdlib HTTP/JSON frontend plus the
  in-process ``submit()`` API tests and bench drive, and the
  ``python -m transmogrifai_tpu serve`` CLI body.

Continuous train-vs-score drift monitoring rides the engine via
``monitor=`` (transmogrifai_tpu/monitor/, docs/monitoring.md): windowed
feature/prediction sketches, ``GET /drift``, and the optional
``/healthz`` hard gate.

One process is a replica; ``transmogrifai_tpu/fleet/`` (docs/fleet.md)
operates N of them — the ``GET /drain`` rotation endpoint, the
``serve.json`` freshness stamp + ``--strict-manifest`` refusal, and the
``GET /drift/window`` raw-sufficient-statistics endpoint here are the
replica-side half of that fleet contract.
"""
from .batcher import MicroBatcher, Overloaded
from .engine import ServingEngine, bucket_ladder, template_record
from .frontend import ServeFrontend, make_http_server, run_serve
from .reqtrace import (BatchTrace, GaugeSampler, ReqTracer, RequestTrace,
                       TailSampler, thread_dump)

__all__ = [
    "MicroBatcher", "Overloaded", "ServingEngine", "bucket_ladder",
    "template_record", "ServeFrontend", "make_http_server", "run_serve",
    "BatchTrace", "GaugeSampler", "ReqTracer", "RequestTrace",
    "TailSampler", "thread_dump",
]
