"""Hierarchical run tracing: span tree, recompile/HBM attribution, exports.

Reference: the OpSparkListener gave every run a per-stage/job/app metrics
story surfaced through the Spark UI and its event log. The TPU equivalent
here is a process-local span TREE (run -> workflow -> layer -> stage ->
kernel / sweep-round) with the two costs that dominate JAX/TPU runs
attributed per span:

- **XLA recompiles** — a `jax.monitoring` listener counts every backend
  compile and books it to the innermost open span (with a
  lowered-executable-count fallback for jax builds without monitoring),
  making claims like PR 3's "bounded recompiles on the bucket ladder"
  runtime-verifiable from any traced run;
- **device-memory watermarks** — `Device.memory_stats()` sampled at span
  close (None-safe: CPU hosts report nothing and the attrs are omitted).

Three consumers, one tree:

- Chrome `trace_event` JSON (`chrome_trace`/`write_chrome_trace`) loadable
  in Perfetto / chrome://tracing;
- the existing AppMetrics JSON (`utils/metrics.MetricsCollector.save`
  appends the span list under a new "spans" key, everything else
  byte-compatible);
- a streaming JSONL event log (`EventLog`) of timestamped run events, so a
  preempted multi-hour sweep is monitorable by tailing ONE file.

`trace_report(dir)` renders top-spans-by-self-time, per-program recompile
counts and the kernel roofline table; `trace_report(dir, check=True)` is
the schema validator CI runs (`python -m transmogrifai_tpu trace-report
<dir> --check`).

This module is import-light on purpose: jax is only touched lazily (and
only when it is already imported) so attaching tracing to a host-only run
never initializes a backend.
"""
from __future__ import annotations

import glob as _glob
import json
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Span", "TraceTree", "RecompileTracker", "tracker", "EventLog",
    "register_jit_fallback", "device_memory_attrs", "chrome_trace",
    "write_chrome_trace", "trace_report", "trace_report_rc",
    "event_log_paths", "iter_events", "requests_report",
    "requests_report_rc", "fmt_table",
]

# the monitoring event one XLA backend compilation emits (jax >= 0.4.x).
# NOTE (measured on this image's jaxlib): a persistent-compilation-cache
# HIT emits it too — but a hit is PRECEDED by the cache-retrieval event
# below, so the tracker classifies the pair and keeps a separate
# total_cache_hits counter (total_compiles keeps counting both, byte-
# compatible with every pre-serving consumer; true compiles =
# total_compiles - total_cache_hits, what the serving engine's
# post-warmup recompile watch reads)
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_retrieval_time_sec"


@dataclass
class Span:
    """One node of the run's span tree.

    t_start/t_end are seconds on the owning TraceTree's monotonic clock
    (perf_counter anchored at tree construction) — wall-time arithmetic
    between spans is exact regardless of system clock steps."""

    span_id: int
    parent_id: Optional[int]
    name: str
    kind: str               # run|workflow|layer|stage|kernel|sweep|
                            # sweep_round|tile|pod_round|pod_compute|
                            # pod_collective|pod_ingest (pod_* families:
                            # parallel/podtrace.py span glossary)
    t_start: float
    t_end: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)
    error: bool = False
    error_type: Optional[str] = None

    @property
    def duration(self) -> float:
        end = self.t_end if self.t_end is not None else self.t_start
        return max(end - self.t_start, 0.0)

    def to_json(self) -> Dict[str, Any]:
        out = {"span_id": self.span_id, "parent_id": self.parent_id,
               "name": self.name, "kind": self.kind,
               "t_start": round(self.t_start, 6),
               "t_end": round(self.t_end, 6)
               if self.t_end is not None else None,
               "duration_seconds": round(self.duration, 6),
               "error": self.error}
        if self.error_type:
            out["error_type"] = self.error_type
        if self.attrs:
            out["attrs"] = _jsonable(self.attrs)
        return out


def _jsonable_value(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    if isinstance(v, dict):
        # nested payloads (e.g. a request_trace's per-segment dict) keep
        # their structure instead of stringifying — events.jsonl lines
        # must stay machine-parseable JSON all the way down
        return {str(k): _jsonable_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable_value(x) for x in v]
    return str(v)


def _jsonable(d: Dict[str, Any]) -> Dict[str, Any]:
    return {k: _jsonable_value(v) for k, v in d.items()}


class TraceTree:
    """Span registry + open-span stack for one traced run (one enable()).

    Thread note: the tree is driven from the host thread that dispatches
    the run; the lock only exists so the jax.monitoring compile listener
    (which fires synchronously inside compile calls, possibly from helper
    threads in future jax versions) can attribute safely."""

    def __init__(self) -> None:
        self._clock0 = time.perf_counter()
        self._wall0 = time.time()
        self.spans: List[Span] = []
        self._stack: List[Span] = []
        self._next_id = 1
        self._lock = threading.RLock()
        # parent span_id -> children, so subtree walks (fallback compile
        # accounting, self-time) stay O(subtree), not O(all spans)
        self._children: Dict[int, List[Span]] = {}

    # -- clock -------------------------------------------------------------
    def now(self) -> float:
        return time.perf_counter() - self._clock0

    # -- structure ---------------------------------------------------------
    def current(self) -> Optional[Span]:
        with self._lock:
            return self._stack[-1] if self._stack else None

    def open(self, name: str, kind: str, **attrs: Any) -> Span:
        with self._lock:
            parent = self._stack[-1].span_id if self._stack else None
            sp = Span(span_id=self._next_id, parent_id=parent, name=name,
                      kind=kind, t_start=self.now(), attrs=dict(attrs))
            self._next_id += 1
            self.spans.append(sp)
            if parent is not None:
                self._children.setdefault(parent, []).append(sp)
            self._stack.append(sp)
        tracker.on_span_open(sp)
        return sp

    def children_of(self, span_id: int) -> List[Span]:
        with self._lock:
            return list(self._children.get(span_id, ()))

    def close(self, sp: Span, error_type: Optional[str] = None) -> None:
        with self._lock:
            # a double close (e.g. close_all() from save() racing the
            # still-open context manager's exit) must be a no-op: the
            # first close fixed t_end, and rewriting it would inflate the
            # span past its already-closed parent's window
            already_closed = sp.t_end is not None
            if not already_closed:
                sp.t_end = self.now()
            if error_type:
                sp.error = True
                sp.error_type = error_type
            # pop up to and including sp — tolerates children left open by
            # an exception unwinding through several context managers. A
            # close of a span no longer on the stack must not drain it.
            if any(top is sp for top in self._stack):
                while self._stack:
                    top = self._stack.pop()
                    if top is sp:
                        break
                    if top.t_end is None:
                        top.t_end = sp.t_end
                    top.attrs.pop("_jit_cache0", None)
        if already_closed:
            return
        tracker.on_span_close(sp, self)
        mem = device_memory_attrs()
        if mem:
            sp.attrs.update(mem)

    def add_complete(self, name: str, kind: str, duration: float,
                     parent_span: Optional["Span"] = None,
                     **attrs: Any) -> Span:
        """Record an already-measured child span (e.g. a kernel wall that
        was timed by its own block_until_ready window): t_end = now,
        t_start = now - duration, parented to the innermost open span —
        or to `parent_span` when given (a producer THREAD records its
        tile spans under the span that was current when its pass began;
        parenting to the consumer thread's transient stage spans would
        violate the children-inside-parent-window invariant)."""
        with self._lock:
            if parent_span is not None:
                parent = parent_span.span_id
            else:
                parent = self._stack[-1].span_id if self._stack else None
            end = self.now()
            sp = Span(span_id=self._next_id, parent_id=parent, name=name,
                      kind=kind, t_start=max(end - max(duration, 0.0), 0.0),
                      t_end=end, attrs=dict(attrs))
            self._next_id += 1
            self.spans.append(sp)
            if parent is not None:
                self._children.setdefault(parent, []).append(sp)
        return sp

    def add_window(self, name: str, kind: str, t_start: float,
                   t_end: float, parent_span: Optional["Span"] = None,
                   **attrs: Any) -> Span:
        """Record an already-measured span at an EXPLICIT window on the
        tree clock (both ends in tree-clock seconds, i.e. values from
        :meth:`now`). The request-trace exporter uses this to lay a kept
        request's segment chain end-to-end inside its request window —
        add_complete's end-is-now anchoring would stack every segment at
        the same instant."""
        with self._lock:
            parent = (parent_span.span_id if parent_span is not None
                      else (self._stack[-1].span_id if self._stack
                            else None))
            t0 = max(float(t_start), 0.0)
            t1 = max(float(t_end), t0)
            sp = Span(span_id=self._next_id, parent_id=parent, name=name,
                      kind=kind, t_start=t0, t_end=t1, attrs=dict(attrs))
            self._next_id += 1
            self.spans.append(sp)
            if parent is not None:
                self._children.setdefault(parent, []).append(sp)
        return sp

    def close_all(self) -> None:
        # pop-then-close WITHOUT holding the tree lock across close():
        # close() re-enters the lock itself and then calls the tracker
        # hooks outside it — holding the lock here would invert the
        # tracker->tree order _on_event uses (THR003: a compile landing
        # on another thread during close_all would deadlock)
        while True:
            with self._lock:
                if not self._stack:
                    return
                sp = self._stack[-1]
            self.close(sp)

    # -- derived -----------------------------------------------------------
    def self_seconds(self, sp: Span) -> float:
        child = sum(s.duration for s in self.children_of(sp.span_id))
        return max(sp.duration - child, 0.0)

    def to_json(self) -> List[Dict[str, Any]]:
        return [s.to_json() for s in self.spans]


# -- recompile attribution ---------------------------------------------------

# jitted entry points registered for the no-monitoring fallback: the sum of
# their lowered-executable cache sizes is sampled at span open/close and the
# delta (minus what nested spans already booked) becomes the span's compile
# count. Coarser than the listener — it only sees registered functions —
# but needs nothing from jax beyond the public-ish _cache_size().
_FALLBACK_JITS: List[Any] = []


def register_jit_fallback(*fns: Any) -> None:
    """Register jitted callables whose executable count stands in for the
    compile counter on jax builds without `jax.monitoring`. Idempotent."""
    for fn in fns:
        if fn is not None and all(fn is not g for g in _FALLBACK_JITS):
            _FALLBACK_JITS.append(fn)


def _fallback_cache_size() -> int:
    total = 0
    for fn in _FALLBACK_JITS:
        size = getattr(fn, "_cache_size", None)
        if size is None:
            continue
        try:
            total += int(size())
        except Exception:
            pass
    return total


class RecompileTracker:
    """Books every XLA backend compile to the innermost open span.

    Primary path: a `jax.monitoring` duration listener on
    /jax/core/compile/backend_compile_duration (registered once, gated on
    an active tree so an idle process pays one dict lookup per compile).
    Fallback (monitoring-less jax): lowered-executable-count sampling over
    `register_jit_fallback` functions at span boundaries."""

    def __init__(self) -> None:
        self._tree: Optional[TraceTree] = None
        self._listener_installed = False
        # override switch (tests force the fallback path with it); the
        # per-activation choice lives in _mode so a pre-jax enable()
        # falling back does not permanently disable the listener path
        self._use_monitoring = True
        self._mode = "monitoring"
        self.total_compiles = 0
        self.total_compile_seconds = 0.0
        self.total_cache_hits = 0
        # a retrieval event and ITS compile event fire back-to-back on
        # the SAME thread, so the pairing flag is thread-local: compiles
        # interleaving from helper threads cannot steal another thread's
        # pending hit and misclassify a true compile as a cache load
        self._pending = threading.local()
        self.by_program: Dict[str, int] = {}
        # guards the counters + activation state (tmoglint THR001): the
        # jax.monitoring listener fires on whatever thread compiles — a
        # serving dispatcher and a prewarm can land compiles
        # concurrently, and `total_compiles += 1` unlocked loses
        # updates exactly where the zero-recompile contract reads them.
        # Ordering: _lock may be held while taking the tree's lock,
        # never the reverse (TraceTree calls the tracker hooks OUTSIDE
        # its own lock)
        self._lock = threading.RLock()

    @property
    def true_compiles(self) -> int:
        """Compiles that actually ran XLA (persistent-cache loads
        excluded) — the serving engine's zero-recompile contract counts
        THESE; a prewarmed restart is all cache hits and reads 0."""
        with self._lock:
            return max(self.total_compiles - self.total_cache_hits, 0)

    # -- lifecycle ---------------------------------------------------------
    def activate(self, tree: TraceTree) -> None:
        with self._lock:
            self._tree = tree
            self.total_compiles = 0
            self.total_compile_seconds = 0.0
            self.total_cache_hits = 0
            self._pending = threading.local()
            self.by_program = {}
            if self._monitoring_available():
                self._install_listener()
                self._mode = "monitoring"
            else:
                self._mode = "fallback"

    def deactivate(self) -> None:
        with self._lock:
            self._tree = None

    def _monitoring_available(self) -> bool:
        if not self._use_monitoring:
            return False
        # only consult jax when something else already imported it (the
        # module contract): a host-only process enabling collection must
        # not pay the jax import here. With jax absent BOTH tracker paths
        # are inert — there is nothing compiling to count.
        jmod = sys.modules.get("jax")
        if jmod is None:
            return False
        try:
            import jax.monitoring  # cheap: jax itself is loaded
            return hasattr(jax.monitoring,
                           "register_event_duration_secs_listener")
        except Exception:
            return False

    def _install_listener(self) -> None:
        if self._listener_installed:
            return
        import jax
        jax.monitoring.register_event_duration_secs_listener(self._on_event)
        self._listener_installed = True

    # -- monitoring path ---------------------------------------------------
    def _on_event(self, event: str, duration: float, **_kw: Any) -> None:
        with self._lock:
            tree = self._tree
            # the listener survives activate/deactivate cycles (jax has
            # no public unregister); in fallback mode it must stay
            # silent or a later re-activation would double-book with
            # the sampler
            if tree is None or self._mode != "monitoring":
                return
            if event == _CACHE_HIT_EVENT:
                # a persistent-cache retrieval fires immediately BEFORE
                # its compile event (measured order, same thread); mark
                # the pair so THIS thread's next compile books as a
                # cache LOAD, not a true XLA compile
                self._pending.cache_hit = True
                return
            if event != _COMPILE_EVENT:
                return
            hit = getattr(self._pending, "cache_hit", False)
            self._pending.cache_hit = False
            self.total_compiles += 1
            self.total_compile_seconds += float(duration)
            if hit:
                self.total_cache_hits += 1
            # the whole read-modify-write under BOTH locks (tracker then
            # tree — the documented order): the listener may fire from
            # helper threads, and an unlocked attrs update would race
            # close()'s watermark update
            with tree._lock:
                sp = tree.current()
                if sp is None:
                    return
                sp.attrs["compiles"] = \
                    int(sp.attrs.get("compiles", 0)) + 1
                sp.attrs["compile_seconds"] = round(
                    float(sp.attrs.get("compile_seconds", 0.0))
                    + float(duration), 4)
                if hit:
                    sp.attrs["cache_hits"] = \
                        int(sp.attrs.get("cache_hits", 0)) + 1
                self.by_program[sp.name] = \
                    self.by_program.get(sp.name, 0) + 1

    # -- fallback path (span-boundary sampling) ----------------------------
    def on_span_open(self, sp: Span) -> None:
        with self._lock:
            if self._tree is None or self._mode != "fallback":
                return
        sp.attrs["_jit_cache0"] = _fallback_cache_size()

    def on_span_close(self, sp: Span, tree: TraceTree) -> None:
        with self._lock:
            active = self._tree is tree and self._mode == "fallback"
        if not active:
            sp.attrs.pop("_jit_cache0", None)
            return
        base = sp.attrs.pop("_jit_cache0", None)
        if base is None:
            return
        delta = _fallback_cache_size() - int(base)
        # subtract everything already booked in the WHOLE subtree (not
        # just direct children): compiles of a grandchild are inside this
        # span's cache-size delta too, and counting them again would
        # inflate every ancestor of the booking span. The children index
        # keeps this O(subtree) per close.
        booked = 0
        todo = tree.children_of(sp.span_id)
        while todo:
            s = todo.pop()
            booked += int(s.attrs.get("compiles", 0))
            todo.extend(tree.children_of(s.span_id))
        own = max(delta - booked, 0)
        if own:
            sp.attrs["compiles"] = int(sp.attrs.get("compiles", 0)) + own
            with self._lock:
                self.by_program[sp.name] = \
                    self.by_program.get(sp.name, 0) + own
                self.total_compiles += own


#: process-wide tracker the collector activates per enable()
tracker = RecompileTracker()


# -- device-memory watermark -------------------------------------------------

def device_memory_attrs() -> Dict[str, Any]:
    """HBM watermark attrs for the current local devices, or {} when jax is
    not imported / the backend reports nothing (CPU memory_stats() is
    None — the ISSUE's None-safety contract). Never initializes a backend:
    only consults jax when something else already imported it."""
    jmod = sys.modules.get("jax")
    if jmod is None:
        return {}
    try:
        stats = [d.memory_stats() for d in jmod.local_devices()]
    except Exception:
        return {}
    in_use = [s.get("bytes_in_use") for s in stats
              if isinstance(s, dict) and s.get("bytes_in_use") is not None]
    peak = [s.get("peak_bytes_in_use") for s in stats
            if isinstance(s, dict)
            and s.get("peak_bytes_in_use") is not None]
    out: Dict[str, Any] = {}
    if in_use:
        out["hbm_bytes_in_use"] = int(sum(in_use))
    if peak:
        out["hbm_peak_bytes"] = int(max(peak))
    return out


# -- streaming event log -----------------------------------------------------

#: default events.jsonl rotation threshold — generous on purpose: an
#: offline fit/score run never gets near it, while a long-running serve
#: replica (which emits per-request events forever) stays bounded
DEFAULT_EVENTLOG_MAX_MB = 256.0


def _eventlog_max_bytes(max_mb: Optional[float]) -> int:
    """Resolved rotation threshold in bytes; 0 disables rotation."""
    if max_mb is None:
        raw = os.environ.get("TMOG_EVENTLOG_MAX_MB", "").strip().lower()
        if raw in ("", "auto"):
            max_mb = DEFAULT_EVENTLOG_MAX_MB
        elif raw in ("0", "off", "false", "no"):
            max_mb = 0.0
        else:
            try:
                max_mb = float(raw)
            except ValueError:
                max_mb = DEFAULT_EVENTLOG_MAX_MB
    return int(max(float(max_mb), 0.0) * 1e6)


class EventLog:
    """Append-only JSONL of timestamped run events.

    Each line: {"seq": N, "t": monotonic_seconds, "ts": wall_epoch,
    "event": type, ...fields}. `t` is non-decreasing and `seq` strictly
    increasing — the monotonicity contract `trace_report --check`
    validates. Lines are flushed per event so `tail -f events.jsonl`
    follows a live run.

    ROTATION: under a long-running serve a per-request event stream
    grows without bound, so once the live file passes `max_mb`
    (``TMOG_EVENTLOG_MAX_MB``, default 256 — generous enough that
    offline runs never rotate; 0/off disables) it shifts to
    ``events.jsonl.1`` (older segments to ``.2`` … up to `keep`, the
    oldest dropped) and a fresh live file opens. `seq` and the monotonic
    clock CONTINUE across the boundary — concatenating the segments
    oldest-first (:func:`event_log_paths`) reproduces one monotone
    stream, which is exactly what trace-report reads."""

    def __init__(self, path: str, max_mb: Optional[float] = None,
                 keep: Optional[int] = None) -> None:
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._seq = 0
        self._mono0 = time.perf_counter()
        self._max_bytes = _eventlog_max_bytes(max_mb)
        if keep is None:
            try:
                keep = int(os.environ.get("TMOG_EVENTLOG_KEEP", "3"))
            except ValueError:
                keep = 3
        self.keep = max(int(keep), 1)
        self.rotations = 0

    def emit(self, event: str, **fields: Any) -> None:
        with self._lock:
            rec = {"seq": self._seq, "t": round(
                time.perf_counter() - self._mono0, 6),
                "ts": round(time.time(), 6), "event": event}
            rec.update(_jsonable(fields))
            self._seq += 1
            # this lock EXISTS to serialize the per-event line write +
            # flush: seq/t monotonicity across threads is the file's
            # contract, so the I/O inside the critical section is the
            # design, not an accident
            try:
                # tmoglint: disable=THR002  serialized write IS the lock's job
                self._f.write(json.dumps(rec, default=str) + "\n")
                # tmoglint: disable=THR002  flush pairs with the write
                self._f.flush()
                if self._max_bytes and self._f.tell() >= self._max_bytes:
                    self._rotate()
            except (ValueError, OSError):
                # closed file / full disk / flaky mount: the liveness
                # side channel must never kill the run it is monitoring
                pass

    def _rotate(self) -> None:
        """Shift the full live file to .1 (.1 -> .2 … oldest dropped)
        and reopen. Caller holds the lock; `seq`/`_mono0` deliberately
        survive so the stream stays monotone across segments."""
        try:
            self._f.close()
        except OSError:
            pass
        try:
            oldest = f"{self.path}.{self.keep}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            pass  # a failed shift falls through to reopening in place
        # the shift + reopen IS what the lock serializes: an emit racing
        # a half-rotated log would interleave segments
        # tmoglint: disable=THR002  rotation is the lock's job
        self._f = open(self.path, "a", encoding="utf-8")
        self.rotations += 1

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    def follow(self, *, stop: Optional[threading.Event] = None,
               poll_s: float = 0.1, from_start: bool = False) -> "Any":
        """Tail-subscribe to THIS log's path (:func:`follow_events`):
        yields parsed events seq-monotone across size-rotation
        boundaries until `stop` is set. Safe from another thread — the
        follower reads the files, never this writer's handle."""
        return follow_events(self.path, stop=stop, poll_s=poll_s,
                             from_start=from_start)


def event_log_paths(path: str) -> List[str]:
    """Every segment of a (possibly rotated) event log, OLDEST first —
    ``events.jsonl.N … events.jsonl.1 events.jsonl``. Reading them in
    this order reproduces one stream with `seq` strictly increasing
    across the rotation boundaries."""
    numbered: List[Tuple[int, str]] = []
    for p in _glob.glob(path + ".*"):
        suffix = p[len(path) + 1:]
        if suffix.isdigit():
            numbered.append((int(suffix), p))
    out = [p for _, p in sorted(numbered, reverse=True)]
    if os.path.exists(path):
        out.append(path)
    return out


def iter_events(path: str) -> "Any":
    """Yield every parsed event record across all rotated segments of
    `path`, oldest first (the tail-across-the-boundary reader).
    Unparseable lines are skipped — validation is trace-report's job."""
    for p in event_log_paths(path):
        try:
            with open(p, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue


def follow_events(path: str, *, stop: Optional[threading.Event] = None,
                  poll_s: float = 0.1, from_start: bool = False) -> "Any":
    """Tail-subscribe to a (possibly rotating) event log: yield every
    parsed event with a `seq` STRICTLY greater than the last one seen,
    until `stop` is set (the retrain controller's trigger source;
    :meth:`EventLog.follow` delegates here).

    The steady-state cost is `tail -f`'s: an open handle + byte offset
    on the LIVE file, reading only appended lines per poll. The cursor
    that survives rotation is the EventLog's own monotonicity contract —
    `seq` strictly increases across ``events.jsonl.N`` boundaries — so
    when the live file is REPLACED under the handle (inode change, or
    the file shrank), the follower rescans every segment oldest-first
    (:func:`iter_events`) and emits only records beyond the last seq:
    events appended just before the shift are seen exactly once, from
    the ``.1`` segment they rotated into. A segment dropped past `keep`
    between polls is lost — the same contract tail -f + logrotate gives.
    A torn final line (writer mid-append, or a crash) is held back until
    its newline lands; records without an integer `seq` are skipped
    (they also fail trace-report --check).

    `from_start=False` (default) begins AFTER the current end of the
    log — a subscriber attaching to a long-running serve must not
    replay history as fresh triggers. The log may not exist yet; the
    follower waits for it to appear."""
    last = -1
    # tail-mode attach consumes the first full-segment scan silently
    # (advancing `last` past history instead of pre-scanning AND
    # rescanning — history is parsed exactly once either way)
    primed = from_start

    f = None
    ino = None

    def _close():
        nonlocal f, ino
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        f, ino = None, None

    def _parse(line: str):
        nonlocal last
        line = line.strip()
        if not line:
            return None
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            return None
        s = rec.get("seq") if isinstance(rec, dict) else None
        if not isinstance(s, int) or s <= last:
            return None
        last = s
        return rec

    try:
        while stop is None or not stop.is_set():
            rotated = False
            try:
                st = os.stat(path)
            except OSError:
                _close()
                st = None
                if not primed:
                    # no LIVE file at attach time: skip whatever
                    # rotated history already exists (a follower
                    # attaching mid-rotation must not replay it);
                    # everything that lands later is fresh
                    for rec in iter_events(path):
                        s = rec.get("seq")
                        if isinstance(s, int) and s > last:
                            last = s
                    primed = True
            if st is not None:
                if f is None or st.st_ino != ino \
                        or st.st_size < f.tell():
                    # fresh file under the path: first attach, a
                    # rotation that shifted the one we were reading to
                    # .1, or a truncate-in-place (logrotate copytruncate
                    # keeps the inode but drops our offset past EOF) —
                    # catch up through ALL segments by seq (on a plain
                    # first attach with from_start=False this pass only
                    # advances `last` past pre-existing history)
                    rotated = True
                    _close()
                    for rec in iter_events(path):
                        s = rec.get("seq")
                        if isinstance(s, int) and s > last:
                            last = s
                            if primed:
                                yield rec
                    primed = True
                    try:
                        # read the fresh live file from byte 0 — lines
                        # the rescan already emitted are dropped by the
                        # seq filter, and a line appended between the
                        # rescan and this open is NOT missed (seeking to
                        # EOF here would skip it)
                        f = open(path, encoding="utf-8")
                        ino = st.st_ino
                    except OSError:
                        _close()
                if f is not None and not rotated:
                    while True:
                        pos = f.tell()
                        line = f.readline()
                        if not line:
                            break
                        if not line.endswith("\n"):
                            # torn tail: the writer is mid-append (or
                            # died mid-line); re-read once it completes
                            f.seek(pos)
                            break
                        rec = _parse(line)
                        if rec is not None:
                            yield rec
            if stop is None:
                time.sleep(poll_s)
            elif stop.wait(poll_s):
                return
    finally:
        _close()


# -- Chrome trace_event export -----------------------------------------------

def chrome_trace(tree: TraceTree, app_name: str = "transmogrifai_tpu"
                 ) -> Dict[str, Any]:
    """Chrome trace_event JSON (the format Perfetto and chrome://tracing
    load): one complete ("ph": "X") event per span, microsecond
    timestamps on the tree's monotonic clock, span/parent ids + attrs in
    `args` so the hierarchy survives round-trips through the viewer."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": app_name}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": 1,
         "args": {"name": "run"}},
    ]
    # per-LANE view: spans carrying a `lane` attr (the request-trace
    # exporter stamps one per tracer) render on their own tid row in
    # Perfetto instead of interleaving with the run hierarchy — kept
    # request windows + their segment chains read as swimlanes
    lanes: Dict[str, int] = {}
    for sp in tree.spans:
        lane = sp.attrs.get("lane")
        if isinstance(lane, str) and lane not in lanes:
            lanes[lane] = 2 + len(lanes)
    for lane, tid in lanes.items():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": lane}})
    end_default = tree.now()
    for sp in tree.spans:
        end = sp.t_end if sp.t_end is not None else end_default
        args = {"span_id": sp.span_id, "parent_id": sp.parent_id,
                "error": sp.error}
        if sp.error_type:
            args["error_type"] = sp.error_type
        args.update(_jsonable(sp.attrs))
        events.append({
            "ph": "X", "name": sp.name, "cat": sp.kind,
            "ts": round(sp.t_start * 1e6, 3),
            "dur": round(max(end - sp.t_start, 0.0) * 1e6, 3),
            "pid": pid, "tid": lanes.get(sp.attrs.get("lane"), 1),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"app_name": app_name,
                          "trace_wall_start": tree._wall0}}


def write_chrome_trace(path: str, tree: TraceTree,
                       app_name: str = "transmogrifai_tpu") -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(chrome_trace(tree, app_name), f, indent=1)


# -- trace-report ------------------------------------------------------------

def _load_trace_spans(path: str) -> Tuple[List[Dict[str, Any]], List[str]]:
    """(span dicts from a chrome trace file, schema problems)."""
    problems: List[str] = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [], [f"{path}: unreadable trace ({e})"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return [], [f"{path}: no traceEvents list"]
    spans = []
    ids = set()
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph is None:
            problems.append(f"{path}: event {i} missing 'ph'")
            continue
        if ph != "X":
            continue
        missing = [k for k in ("ts", "dur", "pid", "tid") if k not in ev]
        if missing:
            problems.append(f"{path}: X event {i} ({ev.get('name')}) "
                            f"missing {missing}")
            continue
        bad_num = [k for k in ("ts", "dur")
                   if not isinstance(ev[k], (int, float))
                   or isinstance(ev[k], bool) or ev[k] < 0]
        if bad_num:
            # flag AND drop: the containment arithmetic below must never
            # crash on the malformed input this validator exists to catch
            problems.append(f"{path}: X event {i} ({ev.get('name')}) "
                            f"non-numeric {bad_num}")
            continue
        args = ev.get("args", {})
        sid = args.get("span_id")
        if sid is not None:
            if sid in ids:
                problems.append(f"{path}: duplicate span_id {sid}")
            ids.add(sid)
        spans.append(ev)
    # parent integrity: every parent_id must be a recorded span_id
    for ev in spans:
        pid_ = ev.get("args", {}).get("parent_id")
        if pid_ is not None and pid_ not in ids:
            problems.append(f"{path}: span {ev.get('name')} has unknown "
                            f"parent_id {pid_}")
    # containment: a child's [ts, ts+dur] must sit inside its parent's
    # window (1ms slack for rounding)
    by_id = {ev["args"].get("span_id"): ev for ev in spans
             if ev.get("args", {}).get("span_id") is not None}
    slack_us = 1000.0
    for ev in spans:
        pid_ = ev.get("args", {}).get("parent_id")
        parent = by_id.get(pid_)
        if parent is None:
            continue
        if ev["ts"] + slack_us < parent["ts"] or \
                ev["ts"] + ev["dur"] > parent["ts"] + parent["dur"] \
                + slack_us:
            problems.append(
                f"{path}: span {ev.get('name')} escapes parent "
                f"{parent.get('name')} window")
    return spans, problems


def _check_event_log(paths: List[str]
                     ) -> Tuple[int, List[str], Dict[str, int]]:
    """(n valid events, schema problems, counts per event type) in ONE
    pass — report mode reuses the counts instead of re-parsing a log
    that can run 10^5+ lines on a long sweep. `paths` is the rotated
    segment chain OLDEST FIRST (event_log_paths): `seq`/`t`
    monotonicity is validated ACROSS rotation boundaries, because the
    EventLog rotation contract is that the concatenated segments are
    one monotone stream."""
    problems: List[str] = []
    counts: Dict[str, int] = {}
    n = 0
    last_t = None
    last_seq = None
    for path in paths:
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    problems.append(f"{path}:{lineno}: invalid JSON")
                    continue
                n += 1
                ev_name = rec.get("event", "?")
                counts[ev_name] = counts.get(ev_name, 0) + 1
                if "event" not in rec:
                    problems.append(f"{path}:{lineno}: missing 'event'")
                t = rec.get("t")
                if not isinstance(t, (int, float)):
                    problems.append(f"{path}:{lineno}: missing numeric "
                                    f"'t'")
                else:
                    # a re-attached log (resumed run) restarts the
                    # monotonic clock; monotonicity is per seq=0 segment
                    seq = rec.get("seq")
                    if last_t is not None and seq != 0 and t < last_t:
                        problems.append(f"{path}:{lineno}: timestamp "
                                        f"went backwards ({t} < "
                                        f"{last_t})")
                    last_t = t
                seq = rec.get("seq")
                if isinstance(seq, int) and isinstance(last_seq, int) \
                        and seq != 0 and seq <= last_seq:
                    problems.append(f"{path}:{lineno}: seq not "
                                    f"increasing")
                last_seq = seq if isinstance(seq, int) else last_seq
    return n, problems, counts


def fmt_table(rows: List[List[str]], header: List[str]) -> List[str]:
    """Left-justified fixed-width text table — the one formatter every
    report surface shares (trace-report, trace-report --requests,
    trace-report --pod via parallel/podtrace.py, the fleet status
    table) so their column alignment cannot drift apart."""
    if not rows:
        return ["(empty)"]
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return out


_fmt_table = fmt_table  # pre-pod-tracing private spelling, still imported


def trace_report_rc(run_dir: str, check: bool = False,
                    top: int = 15) -> Tuple[str, int]:
    """(report text, exit code) with the project-wide code table
    (docs/static_analysis.md "Exit codes", shared with tmoglint):
    0 = clean, 1 = validation problems found, 2 = usage error (`run_dir`
    is not a traced run directory at all — nothing to validate is a
    caller mistake, not a passing check and not a schema failure)."""
    text, ok = trace_report(run_dir, check=check, top=top)
    if text.startswith("trace-report: nothing to read"):
        return text, 2
    return text, 0 if ok else 1


def trace_report(run_dir: str, check: bool = False,
                 top: int = 15) -> Tuple[str, bool]:
    """Render (report text, ok) for a traced run directory.

    Reads every `*trace.json` (chrome traces), `events.jsonl` and
    `*stage_metrics.json` under `run_dir`. With check=True the text is a
    validation verdict (schema problems listed) and ok=False on any.
    CLI callers want :func:`trace_report_rc`, which distinguishes a
    directory with nothing to read (usage error, exit 2) from real
    schema problems (exit 1)."""
    trace_files = sorted(_glob.glob(os.path.join(run_dir, "*trace.json")))
    event_log = os.path.join(run_dir, "events.jsonl")
    log_paths = event_log_paths(event_log)
    metric_files = sorted(
        _glob.glob(os.path.join(run_dir, "*stage_metrics.json")))
    lines: List[str] = []
    problems: List[str] = []

    if not trace_files and not metric_files and not log_paths:
        return (f"trace-report: nothing to read in {run_dir} (no "
                f"*trace.json, *stage_metrics.json or events.jsonl)", False)

    # span ids restart at 1 in every trace file: key everything by
    # (file index, id) or a multi-trace dir (the ci.sh smoke layout)
    # would subtract one file's children from another file's self-time
    all_spans: List[Tuple[int, Dict[str, Any]]] = []
    for fidx, tf in enumerate(trace_files):
        spans, probs = _load_trace_spans(tf)
        all_spans.extend((fidx, ev) for ev in spans)
        problems.extend(probs)

    n_events = 0
    event_counts: Dict[str, int] = {}
    if log_paths:
        n_events, probs, event_counts = _check_event_log(log_paths)
        problems.extend(probs)
        # serving contract (docs/serving.md): the engine emits one
        # serve_recompile event for every XLA compile that lands AFTER
        # its warmup finished — under the prewarmed bucket ladder there
        # must be none, so any such event fails --check exactly like a
        # schema violation (the ci.sh serving smoke pins this)
        n_rc = event_counts.get("serve_recompile", 0)
        if n_rc:
            problems.append(
                f"{event_log}: {n_rc} serve_recompile event(s) — XLA "
                f"compile(s) landed after serving warmup")
        # drift contract (docs/monitoring.md): every threshold breach
        # the serve-side monitor saw is a drift_alert event; --check
        # surfaces them the same way — a monitored run that drifted is
        # not a clean run
        n_da = event_counts.get("drift_alert", 0)
        if n_da:
            problems.append(
                f"{event_log}: {n_da} drift_alert event(s) — serve-time "
                f"feature/prediction drift exceeded policy thresholds")

    for mf in metric_files:
        try:
            with open(mf) as f:
                doc = json.load(f)
            for key in ("app_name", "duration_seconds",
                        "total_stage_seconds", "stage_metrics"):
                if key not in doc:
                    problems.append(f"{mf}: missing AppMetrics key "
                                    f"'{key}'")
        except (OSError, json.JSONDecodeError) as e:
            problems.append(f"{mf}: unreadable ({e})")

    if check:
        lines.append(f"trace-report --check: {len(trace_files)} trace "
                     f"file(s), {n_events} event(s), "
                     f"{len(metric_files)} metrics file(s)")
        if problems:
            lines.append(f"{len(problems)} problem(s):")
            lines.extend(f"  {p}" for p in problems)
        else:
            lines.append("OK")
        return "\n".join(lines), not problems

    # -- report mode -------------------------------------------------------
    lines.append(f"# trace-report {run_dir}")
    if all_spans:
        # self time = dur - sum(direct children dur)
        child_dur: Dict[Any, float] = {}
        for fidx, ev in all_spans:
            pid_ = ev.get("args", {}).get("parent_id")
            if pid_ is not None:
                key = (fidx, pid_)
                child_dur[key] = child_dur.get(key, 0.0) + ev["dur"]
        rows = []
        for fidx, ev in all_spans:
            sid = (fidx, ev.get("args", {}).get("span_id"))
            self_us = max(ev["dur"] - child_dur.get(sid, 0.0), 0.0)
            rows.append((self_us, ev))
        rows.sort(key=lambda r: -r[0])
        table = [[ev.get("name", "?")[:48], ev.get("cat", ""),
                  f"{ev['dur'] / 1e6:.4f}", f"{self_us / 1e6:.4f}",
                  str(ev.get("args", {}).get("compiles", "")),
                  "ERR" if ev.get("args", {}).get("error") else ""]
                 for self_us, ev in rows[:top]]
        lines.append(f"\n## Top spans by self-time "
                     f"({len(all_spans)} spans)")
        lines.extend(_fmt_table(
            table, ["span", "kind", "total_s", "self_s", "compiles",
                    "err"]))

        # recompiles per program (span name)
        comp: Dict[str, Tuple[int, float]] = {}
        for _, ev in all_spans:
            args = ev.get("args", {})
            c = args.get("compiles")
            if c:
                n, s = comp.get(ev.get("name", "?"), (0, 0.0))
                comp[ev.get("name", "?")] = (
                    n + int(c), s + float(args.get("compile_seconds", 0.0)))
        lines.append("\n## Recompiles per program")
        if comp:
            lines.extend(_fmt_table(
                [[name[:48], str(n), f"{s:.2f}"]
                 for name, (n, s) in
                 sorted(comp.items(), key=lambda kv: -kv[1][0])],
                ["program", "compiles", "compile_s"]))
        else:
            lines.append("(none recorded)")

        # roofline table from kernel spans
        kern = [ev for _, ev in all_spans if ev.get("cat") == "kernel"]
        if kern:
            lines.append("\n## Kernel roofline")
            lines.extend(_fmt_table(
                [[ev.get("name", "?")[:40],
                  f"{ev['dur'] / 1e6:.4f}",
                  str(ev.get("args", {}).get("bytes_hbm", "")),
                  str(ev.get("args", {}).get("achieved_gbps", "")),
                  str(ev.get("args", {}).get("pct_of_roof", "")),
                  str(ev.get("args", {}).get("cold", ""))]
                 for ev in kern],
                ["kernel", "wall_s", "bytes_hbm", "gbps", "pct_roof",
                 "cold"]))

        # HBM watermark
        peaks = [ev.get("args", {}).get("hbm_peak_bytes")
                 for _, ev in all_spans
                 if ev.get("args", {}).get("hbm_peak_bytes") is not None]
        if peaks:
            lines.append(f"\nHBM peak across spans: "
                         f"{max(peaks) / 1e9:.3f} GB")

    if n_events:
        counts = event_counts
        lines.append(f"\n## Event log ({n_events} events)")
        lines.extend(_fmt_table(
            [[k, str(v)] for k, v in
             sorted(counts.items(), key=lambda kv: -kv[1])],
            ["event", "count"]))

    if problems:
        lines.append(f"\n## {len(problems)} schema problem(s)")
        lines.extend(f"  {p}" for p in problems)
    return "\n".join(lines), not problems


# -- trace-report --requests -------------------------------------------------

#: a request is flagged when its UNATTRIBUTED wall (e2e minus the sum of
#: its segments) exceeds BOTH bounds: the fraction catches slow requests
#: hiding real time outside the segment chain, the floor keeps
#: millisecond-scale requests from flagging on scheduler-wake jitter
#: (condition-variable wakeups cost whole milliseconds on a busy CPU
#: host — attributing those would need a segment per context switch)
REQUEST_COVERAGE_TOLERANCE = 0.25
REQUEST_COVERAGE_FLOOR_MS = 25.0


def load_request_traces(run_dir: str) -> List[Dict[str, Any]]:
    """Every `request_trace` event under `run_dir` — the kept traces of
    the tail sampler (docs/observability.md "Request tracing") — read
    across rotated event-log segments, oldest first."""
    path = os.path.join(run_dir, "events.jsonl")
    return [rec for rec in iter_events(path)
            if rec.get("event") == "request_trace"]


def _coverage_problems(recs: List[Dict[str, Any]],
                       tolerance: float, floor_ms: float) -> List[str]:
    problems: List[str] = []
    by_id: Dict[str, Dict[str, float]] = {}
    for rec in recs:
        tid = rec.get("trace_id")
        wall = rec.get("wall_ms")
        segs = rec.get("segments") or {}
        if not isinstance(wall, (int, float)) or isinstance(wall, bool):
            problems.append(f"request {tid}: non-numeric wall_ms")
            continue
        seg_sum = sum(float(v) for v in segs.values()
                      if isinstance(v, (int, float)))
        slack = max(tolerance * wall, floor_ms)
        label = f"{rec.get('origin', '?')} request {tid}"
        if wall - seg_sum > slack:
            problems.append(
                f"{label}: segments cover {seg_sum:.1f}ms of "
                f"{wall:.1f}ms e2e wall ({wall - seg_sum:.1f}ms "
                f"unattributed > {slack:.1f}ms tolerance)")
        elif seg_sum - wall > slack:
            problems.append(
                f"{label}: segments sum to {seg_sum:.1f}ms, OVER the "
                f"{wall:.1f}ms e2e wall by more than {slack:.1f}ms")
        if isinstance(tid, str):
            by_id.setdefault(tid, {})[rec.get("origin", "?")] = \
                float(wall)
    # cross-process sanity — DURATIONS only, never absolute-timestamp
    # arithmetic between two hosts' clocks: the replica's own e2e wall
    # for a traced request must fit inside the router's wall for the
    # same trace id (plus slack for response serialization/transport)
    for tid, origins in by_id.items():
        rep, rout = origins.get("replica"), origins.get("router")
        if rep is None or rout is None:
            continue
        slack = max(tolerance * rout, floor_ms)
        if rep > rout + slack:
            problems.append(
                f"request {tid}: replica-side wall {rep:.1f}ms exceeds "
                f"the router-side wall {rout:.1f}ms for the same trace")
    return problems


def requests_report(run_dir: str, top: int = 15,
                    tolerance: float = REQUEST_COVERAGE_TOLERANCE,
                    floor_ms: float = REQUEST_COVERAGE_FLOOR_MS
                    ) -> Tuple[str, bool]:
    """(report text, ok) over the kept request traces of a run dir: the
    top-`top` slowest kept traces with their segment breakdown, kept
    reasons, and the coverage check — any request whose segments do not
    cover its end-to-end wall within tolerance is flagged (ok=False)."""
    recs = load_request_traces(run_dir)
    if not recs:
        return (f"trace-report --requests: no request_trace events in "
                f"{run_dir} (request tracing off, or no kept traces)",
                False)
    problems = _coverage_problems(recs, tolerance, floor_ms)
    lines = [f"# trace-report --requests {run_dir}",
             f"{len(recs)} kept trace(s)"]
    reasons: Dict[str, int] = {}
    for rec in recs:
        k = str(rec.get("kept", "?"))
        reasons[k] = reasons.get(k, 0) + 1
    lines.append("kept by reason: " + ", ".join(
        f"{k}={v}" for k, v in sorted(reasons.items(), key=lambda kv:
                                      -kv[1])))
    ranked = sorted(
        recs, key=lambda r: -(r.get("wall_ms")
                              if isinstance(r.get("wall_ms"),
                                            (int, float)) else 0.0))
    rows = []
    for rec in ranked[:top]:
        segs = rec.get("segments") or {}
        seg_sum = sum(float(v) for v in segs.values()
                      if isinstance(v, (int, float)))
        wall = rec.get("wall_ms")
        cover = (f"{100.0 * seg_sum / wall:.0f}%"
                 if isinstance(wall, (int, float)) and wall else "?")
        rows.append([str(rec.get("trace_id", "?"))[:16],
                     str(rec.get("origin", "?")),
                     str(rec.get("replica", ""))[:20],
                     str(rec.get("status", "")),
                     str(rec.get("kept", "")),
                     f"{wall:.2f}" if isinstance(wall, (int, float))
                     else "?",
                     cover,
                     " ".join(f"{k}={v:.2f}" for k, v in segs.items()
                              if isinstance(v, (int, float)))[:72]])
    lines.append(f"\n## Top {min(top, len(ranked))} slowest kept traces")
    lines.extend(_fmt_table(rows, ["trace", "origin", "replica",
                                   "status", "kept", "wall_ms", "cover",
                                   "segments_ms"]))
    if problems:
        lines.append(f"\n## {len(problems)} coverage problem(s)")
        lines.extend(f"  {p}" for p in problems)
    else:
        lines.append("\ncoverage OK (every kept trace's segments cover "
                     "its e2e wall within tolerance)")
    return "\n".join(lines), not problems


def requests_report_rc(run_dir: str, top: int = 15) -> Tuple[str, int]:
    """(text, exit code) with the project-wide code table
    (docs/static_analysis.md "Exit codes"): 0 = clean, 1 = coverage
    problems, 2 = nothing to read (no kept request traces at all)."""
    text, ok = requests_report(run_dir, top=top)
    if text.startswith("trace-report --requests: no request_trace"):
        return text, 2
    return text, 0 if ok else 1
