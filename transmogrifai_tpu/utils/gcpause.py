"""Generational-GC pause for columnar hot paths.

The reference runs on the JVM, where Spark's executors absorb GC cost and
OpSparkListener merely *reports* it (utils/.../spark/OpSparkListener.scala).
CPython's generational collector is a different beast: a workflow over a
multi-million-row Dataset keeps millions of tracked containers alive
(object-dtype cells, FeatureType wrappers, per-key dicts), and every gen-2
collection rescans all of them. Measured on the 1M-row wide-transmogrify
bench, collections turned a linear columnar pass superlinear (score 10.4s
-> 7.1s at 400K rows, 4x at 1M, with the collector off).

``paused_gc()`` disables the collector for the duration of a train/score
pass and restores the caller's setting afterwards. Reference-counting still
reclaims everything acyclic immediately — only cycle *detection* is
deferred, which is safe for bounded passes that allocate mostly arrays.
"""
from __future__ import annotations

import contextlib
import gc


@contextlib.contextmanager
def paused_gc():
    """Disable cyclic GC inside the block; restore the previous state.

    Re-entrant: nested pauses simply keep the collector off until the
    outermost block exits (and leave it off if the caller had it off).
    """
    was_enabled = gc.isenabled()
    gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()
