"""ASCII table formatting for summaries (reference
utils/.../table/Table.scala — the renderer behind the README model
summary tables and summaryPretty output)."""
from __future__ import annotations

from typing import Any, List, Optional, Sequence


def format_table(columns: Sequence[str], rows: Sequence[Sequence[Any]],
                 title: Optional[str] = None,
                 max_col_width: int = 45) -> str:
    """Render rows as a boxed ASCII table.

    Cells stringify (floats to 6 significant digits) and truncate to
    `max_col_width` with an ellipsis; numeric cells right-align, text
    left-aligns — matching the reference Table's formatting rules.
    """
    def cell(v: Any) -> str:
        if isinstance(v, float):
            s = f"{v:.6g}"
        else:
            s = str(v)
        if len(s) > max_col_width:
            s = s[: max_col_width - 1] + "…"
        return s

    def is_num(v: Any) -> bool:
        return isinstance(v, (int, float)) and not isinstance(v, bool)

    headers = [cell(c) for c in columns]
    body = [[cell(v) for v in r] for r in rows]
    n_cols = len(headers)
    widths = [len(h) for h in headers]
    for r in body:
        for j in range(min(len(r), n_cols)):
            widths[j] = max(widths[j], len(r[j]))
    right = [all(is_num(r[j]) for r in rows if j < len(r) and r[j] is not None)
             and any(j < len(r) for r in rows)
             for j in range(n_cols)]

    def fmt_row(cells: List[str]) -> str:
        out = []
        for j in range(n_cols):
            s = cells[j] if j < len(cells) else ""
            out.append(s.rjust(widths[j]) if right[j] else s.ljust(widths[j]))
        return "| " + " | ".join(out) + " |"

    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines = []
    if title:
        total = len(sep)
        t = title if len(title) <= total - 4 \
            else title[: max(total - 5, 0)] + "…"
        lines.append("+" + "-" * (total - 2) + "+")
        lines.append("| " + t.ljust(total - 4) + " |")
    lines.append(sep)
    lines.append(fmt_row(headers))
    lines.append(sep)
    for r in body:
        lines.append(fmt_row(r))
    lines.append(sep)
    return "\n".join(lines)
