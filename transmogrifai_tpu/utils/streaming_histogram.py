"""Ben-Haim / Tom-Tov streaming histogram.

Reference: utils/src/main/java/com/salesforce/op/utils/stats/
StreamingHistogram.java (299 LoC, the reference's only Java file) +
RichStreamingHistogram.scala — a fixed-size mergeable histogram sketch
(merge the two closest centroids when over capacity) used for feature
distributions. Mergeability is what made it Spark-reduce-friendly; here the
same property makes it the host-side sketch for >HBM streams feeding
RawFeatureFilter.
"""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence, Tuple


class StreamingHistogram:
    """At most `max_bins` (centroid, count) pairs, kept sorted."""

    def __init__(self, max_bins: int = 100):
        if max_bins < 2:
            raise ValueError("max_bins must be >= 2")
        self.max_bins = int(max_bins)
        self._p: List[float] = []   # centroids (sorted)
        self._m: List[float] = []   # counts

    # -- updates ------------------------------------------------------------
    def update(self, value: float, count: float = 1.0) -> "StreamingHistogram":
        i = bisect.bisect_left(self._p, value)
        if i < len(self._p) and self._p[i] == value:
            self._m[i] += count
        else:
            self._p.insert(i, float(value))
            self._m.insert(i, float(count))
            self._compress()
        return self

    def update_all(self, values: Iterable[float]) -> "StreamingHistogram":
        for v in values:
            self.update(float(v))
        return self

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Union of sketches (the treeAggregate combine step)."""
        out = StreamingHistogram(max(self.max_bins, other.max_bins))
        for p, m in sorted(zip(self._p + other._p, self._m + other._m)):
            i = bisect.bisect_left(out._p, p)
            if i < len(out._p) and out._p[i] == p:
                out._m[i] += m
            else:
                out._p.insert(i, p)
                out._m.insert(i, m)
        out._compress()
        return out

    def _compress(self) -> None:
        while len(self._p) > self.max_bins:
            # merge the pair with the smallest centroid gap (BHTT rule)
            gaps = [self._p[i + 1] - self._p[i]
                    for i in range(len(self._p) - 1)]
            i = min(range(len(gaps)), key=gaps.__getitem__)
            m = self._m[i] + self._m[i + 1]
            self._p[i] = (self._p[i] * self._m[i]
                          + self._p[i + 1] * self._m[i + 1]) / m
            self._m[i] = m
            del self._p[i + 1]
            del self._m[i + 1]

    # -- queries ------------------------------------------------------------
    def bins(self) -> List[Tuple[float, float]]:
        return list(zip(self._p, self._m))

    def total(self) -> float:
        return sum(self._m)

    def sum_to(self, b: float) -> float:
        """Estimated count of points <= b (reference `sum` procedure:
        trapezoidal interpolation within the straddling bin)."""
        if not self._p:
            return 0.0
        if b < self._p[0]:
            return 0.0
        if b >= self._p[-1]:
            return self.total()
        i = bisect.bisect_right(self._p, b) - 1
        p_i, p_j = self._p[i], self._p[i + 1]
        m_i, m_j = self._m[i], self._m[i + 1]
        frac = (b - p_i) / (p_j - p_i)
        m_b = m_i + (m_j - m_i) * frac
        s = (m_i + m_b) * frac / 2.0
        return sum(self._m[:i]) + m_i / 2.0 + s

    def quantile(self, q: float) -> float:
        """Inverse of sum_to by bisection over the centroid span."""
        if not self._p:
            return 0.0
        target = q * self.total()
        lo, hi = self._p[0], self._p[-1]
        for _ in range(64):
            mid = (lo + hi) / 2.0
            if self.sum_to(mid) < target:
                lo = mid
            else:
                hi = mid
        return (lo + hi) / 2.0

    def density(self, edges: Sequence[float]) -> List[float]:
        """Histogram mass between consecutive edges (for JS-divergence
        against a fixed binning)."""
        out = []
        prev = self.sum_to(edges[0])
        for e in edges[1:]:
            cur = self.sum_to(e)
            out.append(max(cur - prev, 0.0))
            prev = cur
        return out
