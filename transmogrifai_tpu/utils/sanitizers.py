"""Numeric + purity sanitizers for the compiled feature pipeline.

SURVEY §5 "race detection / sanitizers": the reference has none in-repo
(immutable RDDs and the JVM are its whole story; the closest analogues are
`checkSerializable` closure checks at OpWorkflow.scala:265 and the
scalastyle gate). The failure modes of a compiled-array pipeline are
different — silent NaN/Inf propagation through fused XLA programs, stages
mutating shared input buffers, impure `get_jax_fn`s whose Python side
effects bake stale values into a trace — so the sanitizers here target
those:

* `debug_nans()` / `debug_infs()` — context managers flipping JAX's
  trap-on-NaN/Inf modes for a scoped block (fit or score), restoring prior
  state on exit.
* `check_finite(ds)` — one pass over a Dataset's numeric/vector columns
  reporting NaN/Inf counts per column (cheap reductions, no device sync
  beyond the scalars).
* `assert_stage_pure(stage, ds)` — fits/transforms twice and verifies
  (a) the input columns were not mutated, (b) repeated transforms are
  bit-identical (catches RNG/global-state leaks into traces).

All opt-in, all host-side orchestration; nothing here runs inside a jitted
program.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterator, Optional

import numpy as np

from ..data.dataset import Column, Dataset
from ..types import ColumnKind


@contextlib.contextmanager
def debug_nans(enable: bool = True) -> Iterator[None]:
    """Trap NaNs produced by any jax computation in this block (jax
    re-runs the offending primitive un-jitted and raises with a stack)."""
    import jax
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


@contextlib.contextmanager
def debug_infs(enable: bool = True) -> Iterator[None]:
    import jax
    prev = jax.config.jax_debug_infs
    jax.config.update("jax_debug_infs", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_debug_infs", prev)


def check_finite(ds: Dataset, columns: Optional[list] = None
                 ) -> Dict[str, Dict[str, int]]:
    """Per-column NaN/Inf counts over numeric and vector columns.

    NaN in a FLOAT/INT/BOOL column is the *encoding of missing* and is NOT
    reported (it is expected); NaN or Inf inside a VECTOR column — the
    post-vectorizer device matrix — is always a defect and is.
    """
    report: Dict[str, Dict[str, int]] = {}
    names = columns if columns is not None else ds.column_names()
    for name in names:
        col = ds.column(name)
        if col.kind == ColumnKind.VECTOR:
            data = np.asarray(col.data)
            nan = int(np.isnan(data).sum())
            inf = int(np.isinf(data).sum())
            if nan or inf:
                report[name] = {"nan": nan, "inf": inf}
        elif col.kind in (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL):
            data = np.asarray(col.data, np.float64)
            inf = int(np.isinf(data).sum())
            if inf:
                report[name] = {"nan": 0, "inf": inf}
    return report


def _snapshot(col: Column) -> Any:
    data = col.data
    if isinstance(data, np.ndarray) and data.dtype != object:
        return data.copy()
    return [v.copy() if isinstance(v, (dict, list, set, np.ndarray)) else v
            for v in data]


def _rows_equal(a: Any, b: Any) -> bool:
    """Structural row equality that treats NaN == NaN (a deterministic
    stage may legitimately emit NaN) and handles ndarray/dict/list rows."""
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) and isinstance(b, float):
        return a == b or (np.isnan(a) and np.isnan(b))
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        try:
            return np.array_equal(np.asarray(a), np.asarray(b),
                                  equal_nan=True)
        except TypeError:  # non-numeric arrays: elementwise
            return np.array_equal(np.asarray(a), np.asarray(b))
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_rows_equal(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_rows_equal(x, y)
                                        for x, y in zip(a, b))
    return a == b


def _unchanged(before: Any, col: Column) -> bool:
    data = col.data
    if isinstance(before, np.ndarray):
        return np.array_equal(before, np.asarray(data), equal_nan=True)
    return all(_rows_equal(a, b) for a, b in zip(before, data))


def _columns_equal(a: Column, b: Column) -> bool:
    da, db = a.data, b.data
    if isinstance(da, np.ndarray) and da.dtype != object:
        return np.array_equal(da, np.asarray(db), equal_nan=True)
    return len(da) == len(db) and all(_rows_equal(x, y)
                                      for x, y in zip(da, db))


def assert_stage_pure(stage, ds: Dataset) -> None:
    """Purity laws for a stage against a dataset:

    1. transform/fit must not mutate its input columns;
    2. transforming twice must be bit-identical (impure jax_fns or global
       RNG leaking into the trace break this).

    Raises AssertionError with the offending column/stage names.
    """
    from ..stages.base import Estimator

    in_names = stage.input_names()
    before = {n: _snapshot(ds.column(n)) for n in in_names}
    model = stage.fit(ds) if isinstance(stage, Estimator) else stage
    out1 = model.transform(ds).column(model.output_name())
    for n in in_names:
        assert _unchanged(before[n], ds.column(n)), \
            f"{stage.stage_name} mutated its input column '{n}'"
    out2 = model.transform(ds).column(model.output_name())
    assert _columns_equal(out1, out2), \
        f"{stage.stage_name}: repeated transform is not deterministic"
