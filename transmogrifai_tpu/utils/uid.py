"""Deterministic-ish UID generation for stages & features.

Reference: utils/.../UID.scala — ids of form ``ClassName_%012x``. A process-
local counter keeps ids reproducible within a run (the reference uses random
hex; we use a counter seeded per-process so tests are stable, with the same
printed format so persisted artifacts look alike).
"""
from __future__ import annotations

import itertools
import re
import threading
from typing import Tuple

_counter = itertools.count(1)
_lock = threading.Lock()

_UID_RE = re.compile(r"^(\w+)_(\w{12})$")


def make_uid(cls_or_name) -> str:
    name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
    with _lock:
        n = next(_counter)
    return f"{name}_{n:012x}"


def parse_uid(uid: str) -> Tuple[str, str]:
    """Split a uid into (stage class name, hex suffix). Raises on malformed."""
    m = _UID_RE.match(uid)
    if not m:
        raise ValueError(f"Invalid UID: {uid}")
    return m.group(1), m.group(2)


def reset_uids() -> None:
    """Reset the counter (test isolation only)."""
    global _counter
    _counter = itertools.count(1)
