"""Run metrics + tracing registry.

Reference: utils/.../spark/OpSparkListener.scala:56-164 — per-stage/job/app
metrics (durations, GC, shuffle/IO bytes) collected by a Spark listener,
opt-in via OpParams.collectStageMetrics, surfaced at app end. The TPU
equivalents are per-stage wall clock + row counts + XLA compile counts, and
a `trace()` context manager around jax.profiler for device timelines.

Collection is opt-in and process-local: `enable()` (or
OpParams.collect_stage_metrics=True through the runner) turns it on; the
workflow engine reports fit/transform spans here.

Since the hierarchical-tracing PR every record is also a node of a span
TREE (utils/tracing.py): enable() opens a root span and activates the
recompile tracker; span()/trace_span() nest under it; kernel() and
sweep_convergence() attach as child spans. The flat StageMetric /
KernelRoofline / SweepConvergence lists stay exactly as before so
AppMetrics.to_json() remains byte-compatible for existing consumers — the
tree adds a "spans" key in save(), a Chrome-trace export
(save_chrome_trace) and an optional streaming event log
(attach_event_log / event)."""
from __future__ import annotations

import collections
import contextlib
import json
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from . import tracing
from .tracing import EventLog, TraceTree


@dataclass
class StageMetric:
    """One fit/transform span (reference StageMetrics case class).

    error/error_type: a span is recorded even when its body raises (the
    `finally` path), and before the tracing PR it silently dropped that
    fact — a failed fit read exactly like a fast one. Both fields ride
    into to_json()/the trace export; absent errors serialize as
    error=False / error_type=None, which old readers ignore."""

    stage_name: str
    uid: str
    phase: str              # 'fit' | 'transform' | 'fused-transform'
    wall_seconds: float
    n_rows: int = 0
    n_stages_fused: int = 1
    error: bool = False
    error_type: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


# HBM roof (GB/s) by device-kind substring, most specific first — the
# denominator of every %-of-roof figure the kernel spans report. Sources:
# published per-chip HBM bandwidth specs for each TPU generation.
HBM_ROOF_GBPS = [("v6e", 1640.0), ("v6", 1640.0), ("v5p", 2765.0),
                 ("v5", 819.0), ("v4", 1228.0), ("v3", 900.0),
                 ("v2", 700.0)]


def hbm_roof_gbps(device_kind: str) -> Optional[float]:
    """HBM bandwidth roof for a jax device_kind string, or None when the
    generation is unknown (CPU hosts, new hardware)."""
    kind = (device_kind or "").lower()
    return next((r for s, r in HBM_ROOF_GBPS if s in kind), None)


# Peak dense-compute roof (GFLOP/s, bf16 matmul peak per chip) by
# device-kind substring — the denominator of the pod flight recorder's
# MFU column (parallel/podtrace.py). Sources: published per-chip peak
# compute specs for each TPU generation. Same substring-match contract
# as HBM_ROOF_GBPS: most specific first, None off-TPU.
FLOPS_ROOF_GFLOPS = [("v6e", 918000.0), ("v6", 918000.0),
                     ("v5p", 459000.0), ("v5", 197000.0),
                     ("v4", 275000.0), ("v3", 123000.0),
                     ("v2", 45000.0)]


def flops_roof_gflops(device_kind: str) -> Optional[float]:
    """Peak-compute roof for a jax device_kind string, or None when the
    generation is unknown (CPU hosts, new hardware)."""
    kind = (device_kind or "").lower()
    return next((r for s, r in FLOPS_ROOF_GFLOPS if s in kind), None)


def roofline_fields(wall_seconds: float, bytes_hbm: float,
                    roof_gbps: Optional[float]) -> Dict[str, Any]:
    """THE achieved-GB/s / %-of-roof arithmetic, shared by every
    consumer (collector.kernel spans in BENCH_*.json and bench.py's
    --hist-roofline micro-bench) so their numbers cannot diverge in
    rounding or clamping. 3-decimal GB/s so tiny CPU-fallback figures
    stay nonzero; roof fields None off-TPU."""
    gbps = bytes_hbm / max(wall_seconds, 1e-9) / 1e9
    return {"achieved_gbps": round(gbps, 3),
            "roof_gbps": roof_gbps,
            "pct_of_roof": (round(100.0 * gbps / roof_gbps, 2)
                            if roof_gbps else None)}


@dataclass
class KernelRoofline:
    """One timed kernel/sweep span with analytic HBM traffic attached.

    bytes_hbm comes from the kernel's own traffic model (e.g.
    ops/pallas_hist.fused_fit_bytes) — analytic by construction, since
    per-invocation byte counters cannot exist inside a jitted program.
    achieved_gbps = bytes_hbm / wall; pct_of_roof is against the device
    generation's published HBM bandwidth (None off-TPU). cold=True marks
    the first run of a program: its wall includes jit trace + compile,
    so only cold=False spans are valid bandwidth claims."""

    kernel: str
    wall_seconds: float
    bytes_hbm: float
    achieved_gbps: float = 0.0
    roof_gbps: Optional[float] = None
    pct_of_roof: Optional[float] = None
    cold: Optional[bool] = None

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class SweepConvergence:
    """Round/pass telemetry of one convergence-aware GLM sweep
    (ops/glm_sweep.py). `data_passes` counts executed streaming passes
    over X inside the fit kernels (the one-time standardization stats
    pass is excluded and noted in docs/performance.md); `lane_passes` is
    the USEFUL work — sum over rounds of active_lanes x iterations (the
    corrected FLOP model, bench.py::glm_flops_estimate, bills the
    sweep's `padded_lane_passes`: bucket_size x iterations, what the
    device actually executed). kernel: "gram" (squared-loss sufficient
    statistics, exactly one pass), "rounds" (retirement driver) or
    "global" (legacy run-to-global-convergence fallback)."""

    family: str
    kernel: str
    rounds: int
    data_passes: int
    lane_passes: int
    lanes_total: int
    lanes_retired: int
    active_per_round: List[int] = field(default_factory=list)
    iters_per_round: List[int] = field(default_factory=list)
    bucket_sizes: List[int] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class StatsPass:
    """One pass of the one-pass statistics engine (ops/stats_engine.py).

    `passes` is the number of logical reads of X the driver performed
    (1 by construction — the engine exists so the SanityChecker's
    pre-model statistics stop costing 4+G passes); `tiles` the scan/tile
    count inside that read; `bytes_hbm` the analytic traffic
    (stats_pass_bytes). The wall is fenced with block_until_ready, so a
    companion kernel-roofline span named stats_pass[<driver>] carries
    the achieved-GB/s attribution next to the sweep kernels."""

    driver: str             # 'fused' | 'sharded' | 'streamed'
    rows: int
    cols: int
    tiles: int
    bytes_hbm: float
    wall_seconds: float
    passes: int = 1
    label: str = "stats"
    cold: Optional[bool] = None

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class IngestPass:
    """One parallel-parse pass of the sharded ingest engine
    (parallel/ingest.py ShardedSource).

    `workers` is the parse-worker count the pass actually ran with
    (after the min(workers, shards) clamp), `parse_seconds` the SUM of
    per-worker decode time (compare against `wall_seconds` for the
    overlap factor: parse_seconds > wall_seconds means the pool decoded
    in parallel), `chunks` the columnar chunk count reassembled in shard
    order. Serial degradations (workers <= 1) are recorded too so A/B
    runs land both sides in one metrics doc."""

    label: str
    workers: int
    shards: int
    chunks: int
    rows: int
    parse_seconds: float
    wall_seconds: float

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


class LatencyHistogram:
    """Streaming-quantile latency histogram (the serving engine's p50/p95/
    p99 source, docs/serving.md).

    Fixed log-spaced buckets — `_BPD` per decade from 1µs to ~1000s — so
    recording is O(1), memory is constant regardless of request count, and
    quantiles come from the cumulative bucket counts with log-linear
    interpolation inside the winning bucket (relative error bounded by the
    bucket ratio, ~33% of a decade step at 7/decade — tight enough for
    p50-vs-p99 shape, which is what the histogram exists to show).
    Thread-safe: the serving engine records from the batcher thread and
    every HTTP worker thread concurrently."""

    _BPD = 7                     # buckets per decade
    _LO = 1e-6                   # 1µs floor
    _DECADES = 9                 # 1µs .. 1000s
    _N = _BPD * _DECADES

    def __init__(self, name: str = "latency") -> None:
        self.name = name
        self.count = 0
        self.total_seconds = 0.0
        self.max_seconds = 0.0
        self._counts = [0] * (self._N + 1)  # +1 overflow bucket
        self._lock = threading.Lock()

    def _bucket(self, seconds: float) -> int:
        if seconds <= self._LO:
            return 0
        b = int(math.log10(seconds / self._LO) * self._BPD)
        return min(b, self._N)

    #: upper bound of bucket b in seconds
    def _bound(self, b: int) -> float:
        return self._LO * 10.0 ** ((b + 1) / self._BPD)

    def record(self, seconds: float) -> None:
        s = max(float(seconds), 0.0)
        with self._lock:
            self.count += 1
            self.total_seconds += s
            if s > self.max_seconds:
                self.max_seconds = s
            self._counts[self._bucket(s)] += 1

    def quantile(self, q: float) -> float:
        """Latency (seconds) at quantile q in [0, 1]; 0.0 when empty."""
        with self._lock:
            if not self.count:
                return 0.0
            target = q * self.count
            seen = 0
            for b, c in enumerate(self._counts):
                if not c:
                    continue
                if seen + c >= target:
                    lo = self._LO * 10.0 ** (b / self._BPD) \
                        if b else 0.0
                    hi = min(self._bound(b), self.max_seconds)
                    frac = (target - seen) / c
                    return lo + (max(hi, lo) - lo) * frac
                seen += c
            return self.max_seconds

    @property
    def mean_seconds(self) -> float:
        return self.total_seconds / self.count if self.count else 0.0

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Add `other`'s observations into this histogram, in place.

        EXACT bucket-sum semantics: the log-spaced buckets are identical
        across all instances, so merged counts equal the counts of
        recording the union stream, and every quantile of the merge
        equals the union-stream quantile bit-for-bit (quantiles read
        only bucket counts + the max, both of which merge losslessly).
        This is what makes a fleet p99 from summed per-replica buckets
        honest — no histogram re-fitting, no approximation beyond the
        bucket resolution each replica already had. Merging an empty
        histogram is the identity. Locks are taken one at a time
        (snapshot `other`, then apply), never nested."""
        with other._lock:
            counts = list(other._counts)
            count, total = other.count, other.total_seconds
            mx = other.max_seconds
        with self._lock:
            self.count += count
            self.total_seconds += total
            if mx > self.max_seconds:
                self.max_seconds = mx
            for b, c in enumerate(counts):
                if c:
                    self._counts[b] += c
        return self

    #: bucket key (the "buckets_ms" label of to_json) -> bucket index;
    #: built once — from_json must invert the exact formatting record()
    #: and to_json() use, or a merged fleet histogram would misplace mass
    _KEY_TO_BUCKET: Optional[Dict[str, int]] = None

    @classmethod
    def _key_map(cls) -> Dict[str, int]:
        if cls._KEY_TO_BUCKET is None:
            lo = cls._LO
            cls._KEY_TO_BUCKET = {
                f"{lo * 10.0 ** ((b + 1) / cls._BPD) * 1e3:.3g}": b
                for b in range(cls._N + 1)}
        return cls._KEY_TO_BUCKET

    @staticmethod
    def from_json(doc: Dict[str, Any]) -> "LatencyHistogram":
        """Rebuild a histogram from its to_json() payload (the fleet
        telemetry path: each replica serves its histograms over
        /metrics, the fleet merges the parsed copies). Bucket counts and
        the total count round-trip exactly; mean/max carry to_json()'s
        4-decimal-ms rounding, so to_json(from_json(j)) == j."""
        h = LatencyHistogram(str(doc.get("name", "latency")))
        count = int(doc.get("count", 0))
        # factory-local: `h` is unshared until returned (the same
        # happens-before-sharing argument the __init__ exemption makes)
        h.count, h.total_seconds, h.max_seconds = (  # tmoglint: disable=THR001
            count, float(doc.get("mean_ms", 0.0)) * count / 1e3,
            float(doc.get("max_ms", 0.0)) / 1e3)
        key_map = h._key_map()
        for key, c in (doc.get("buckets_ms") or {}).items():
            b = key_map.get(str(key))
            if b is None:
                raise ValueError(f"unknown latency bucket key {key!r}")
            h._counts[b] += int(c)
        return h

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            count, total = self.count, self.total_seconds
            mx = self.max_seconds
            nonzero = {f"{self._bound(b) * 1e3:.3g}": c
                       for b, c in enumerate(self._counts) if c}
        ms = 1e3
        return {"name": self.name, "count": count,
                "mean_ms": round(total / count * ms, 4) if count else 0.0,
                "p50_ms": round(self.quantile(0.50) * ms, 4),
                "p95_ms": round(self.quantile(0.95) * ms, 4),
                "p99_ms": round(self.quantile(0.99) * ms, 4),
                "max_ms": round(mx * ms, 4),
                "buckets_ms": nonzero}


class GaugeRing:
    """Fixed-length ring of gauge snapshots — the ``GET /metrics/history``
    time-series (docs/observability.md "Request tracing").

    Each sample is one flat JSON-able dict stamped with the ring's
    monotonic `t` (seconds since construction) and wall `ts` (epoch).
    The deque bound makes memory constant under a long-running serve no
    matter the cadence; dropping the oldest snapshot is the design, not
    data loss — the ring is a recent-history window, the mergeable
    aggregates (counters + latency histograms) carry the full run.
    Thread-safe: the sampler thread appends while HTTP workers read."""

    def __init__(self, maxlen: int = 720) -> None:
        self._snaps: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=int(maxlen))
        self._lock = threading.Lock()
        self._mono0 = time.perf_counter()

    def append(self, **gauges: Any) -> Dict[str, Any]:
        snap: Dict[str, Any] = {
            "t": round(time.perf_counter() - self._mono0, 3),
            "ts": round(time.time(), 3)}
        snap.update(gauges)
        with self._lock:
            self._snaps.append(snap)
        return snap

    def to_json(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(s) for s in self._snaps]

    def __len__(self) -> int:
        with self._lock:
            return len(self._snaps)


@dataclass
class AppMetrics:
    """Whole-run metrics (reference AppMetrics)."""

    app_name: str = "transmogrifai_tpu"
    start_time: float = 0.0
    end_time: float = 0.0
    stage_metrics: List[StageMetric] = field(default_factory=list)
    kernel_metrics: List[KernelRoofline] = field(default_factory=list)
    sweep_metrics: List[SweepConvergence] = field(default_factory=list)
    stats_metrics: List[StatsPass] = field(default_factory=list)
    ingest_metrics: List[IngestPass] = field(default_factory=list)
    latency_metrics: Dict[str, LatencyHistogram] = field(
        default_factory=dict)

    @property
    def duration_seconds(self) -> float:
        return max(self.end_time - self.start_time, 0.0)

    def total_stage_seconds(self) -> float:
        return sum(m.wall_seconds for m in self.stage_metrics)

    def to_json(self) -> Dict[str, Any]:
        out = {"app_name": self.app_name,
               "duration_seconds": self.duration_seconds,
               "total_stage_seconds": self.total_stage_seconds(),
               "stage_metrics": [m.to_json() for m in self.stage_metrics]}
        if self.kernel_metrics:
            out["kernel_metrics"] = [m.to_json()
                                     for m in self.kernel_metrics]
        if self.sweep_metrics:
            out["sweep_metrics"] = [m.to_json()
                                    for m in self.sweep_metrics]
        if self.stats_metrics:
            out["stats_metrics"] = [m.to_json()
                                    for m in self.stats_metrics]
        if self.ingest_metrics:
            out["ingest_metrics"] = [m.to_json()
                                     for m in self.ingest_metrics]
        if self.latency_metrics:
            out["latency_metrics"] = {k: h.to_json() for k, h
                                      in self.latency_metrics.items()}
        return out

    def pretty(self) -> str:
        lines = [f"{'Stage':<42}{'Phase':<18}{'Rows':>9}{'Seconds':>10}"]
        for m in self.stage_metrics:
            lines.append(f"{m.stage_name[:41]:<42}{m.phase:<18}"
                         f"{m.n_rows:>9}{m.wall_seconds:>10.4f}")
        lines.append(f"Total: {self.total_stage_seconds():.4f}s over "
                     f"{len(self.stage_metrics)} spans")
        return "\n".join(lines)


class MetricsCollector:
    """Process-local registry (the listener's slot in this runtime)."""

    def __init__(self) -> None:
        self.enabled = False
        self.current = AppMetrics()
        self.trace = TraceTree()
        self._finished = False
        self._event_log: Optional[EventLog] = None
        # lifecycle lock (tmoglint THR001): enable/finish/attach run on
        # the driving thread while event()/latency()/span checks fire
        # from serving + tileplane threads — the state swap in enable()
        # must never interleave with a half-read (enabled, trace) pair.
        # RLock: save() -> finish() nests. Ordering: _lock may be held
        # while taking TraceTree._lock or EventLog._lock, never the
        # reverse (THR003)
        self._lock = threading.RLock()

    def enable(self, app_name: str = "transmogrifai_tpu") -> None:
        """Start (or join) a collected run. Reentrancy-safe: when a run is
        ALREADY being collected (an outer bench/BENCH_TRACE_DIR trace, a
        library user's own enable) a nested enable — e.g. runner.run with
        collect_stage_metrics inside it — must NOT reset the outer span
        tree mid-run; the nested run's spans simply join the existing
        tree. disable(), or finish() having closed the run, re-arms a
        fresh enable."""
        with self._lock:
            if self.enabled and not self._finished:
                return
            self.enabled = True
            self._finished = False
            self.current = AppMetrics(app_name=app_name,
                                      start_time=time.time())
            self.trace = TraceTree()
            # activate BEFORE opening the root span so the fallback
            # tracker samples the root too — compiles landing at run
            # level (between child spans) must not be invisible on
            # monitoring-less jax
            tracing.tracker.activate(self.trace)
            self.trace.open(app_name, "run")

    @property
    def collecting(self) -> bool:
        """True while an UNFINISHED run is being collected — the state a
        nested enable() joins instead of resetting (callers that enable
        conditionally, like runner.run, key their cleanup on this)."""
        with self._lock:
            return self.enabled and not self._finished

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            tracing.tracker.deactivate()

    def finish(self) -> AppMetrics:
        """Close the run. Idempotent: end_time (and therefore
        duration_seconds) freezes on the FIRST call — save() and
        runner._finish both call here, and the second call used to
        silently rewrite the run's duration."""
        with self._lock:
            if not self._finished:
                self.current.end_time = time.time()
                self.trace.close_all()
                self._finished = True
            return self.current

    # -- event log ---------------------------------------------------------
    @property
    def has_event_log(self) -> bool:
        with self._lock:
            return self._event_log is not None

    def attach_event_log(self, path: str) -> EventLog:
        """Open (append) the streaming JSONL event log. Events flow
        independently of `enabled` — the log is the tail-able liveness
        channel of a long sweep even when span collection is off. The new
        log opens BEFORE the old one closes: a failed open (unwritable
        path) raises with the working log still attached."""
        new_log = EventLog(path)
        with self._lock:
            if self._event_log is not None:
                self._event_log.close()
            self._event_log = new_log
        return new_log

    def detach_event_log(self) -> None:
        with self._lock:
            log = self._event_log
            self._event_log = None
        if log is not None:
            log.close()

    def event(self, event: str, **fields: Any) -> None:
        """Emit one run event to the attached log (no-op without one).
        The reference is taken under the lock, the emit happens outside
        it: a detach racing a serve-thread event sees either the old log
        (which swallows writes after close) or none — never a torn
        state, and the file write never extends the lock hold
        (tmoglint THR002)."""
        with self._lock:
            log = self._event_log
        if log is not None:
            log.emit(event, **fields)

    # -- spans ---------------------------------------------------------------
    _EVENTED_KINDS = ("run", "workflow", "stage")

    @contextlib.contextmanager
    def trace_span(self, name: str, kind: str = "span",
                   **attrs: Any) -> Iterator[Optional[tracing.Span]]:
        """Generic span context: nests under the innermost open span,
        records error/error_type when the body raises, samples the device
        memory watermark and recompile attribution at close. Yields the
        Span (None when collection is off) so callers can add attrs."""
        with self._lock:
            if not self.enabled:
                sp = trace = None
            else:
                # capture the TREE that opened the span: a concurrent
                # enable() may swap self.trace mid-span, and the close
                # must land on the tree the span belongs to
                trace = self.trace
                sp = trace.open(name, kind, **attrs)
        if sp is None:
            yield None
            return
        if kind in self._EVENTED_KINDS:
            self.event("span_start", name=name, kind=kind)
        err: Optional[str] = None
        try:
            yield sp
        except BaseException as e:
            err = type(e).__name__
            raise
        finally:
            trace.close(sp, error_type=err)
            if kind in self._EVENTED_KINDS:
                self.event("span_end", name=name, kind=kind,
                           wall_seconds=round(sp.duration, 6),
                           error=err is not None,
                           **({"error_type": err} if err else {}))

    @contextlib.contextmanager
    def span(self, stage_name: str, uid: str, phase: str,
             n_rows: int = 0, n_stages_fused: int = 1) -> Iterator[None]:
        with self._lock:
            if not self.enabled:
                sp = trace = cur = None
            else:
                t0 = time.time()
                trace = self.trace
                cur = self.current
                sp = trace.open(stage_name, "stage", uid=uid,
                                phase=phase, n_rows=n_rows,
                                n_stages_fused=n_stages_fused)
        if sp is None:
            yield
            return
        self.event("stage_start", stage=stage_name, uid=uid, phase=phase)
        err: Optional[str] = None
        try:
            yield
        except BaseException as e:
            # the span records even when the body raises; WITHOUT the
            # error mark a failed fit reads exactly like a fast one
            err = type(e).__name__
            raise
        finally:
            trace.close(sp, error_type=err)
            wall = time.time() - t0
            cur.stage_metrics.append(StageMetric(
                stage_name=stage_name, uid=uid, phase=phase,
                wall_seconds=wall, n_rows=n_rows,
                n_stages_fused=n_stages_fused,
                error=err is not None, error_type=err))
            self.event("stage_end", stage=stage_name, uid=uid, phase=phase,
                       wall_seconds=round(wall, 6), error=err is not None,
                       **({"error_type": err} if err else {}))

    def kernel(self, name: str, wall_seconds: float, bytes_hbm: float,
               cold: Optional[bool] = None,
               attrs: Optional[Dict[str, Any]] = None
               ) -> Optional[KernelRoofline]:
        """Record one kernel-roofline span (no-op unless enabled). The
        roof is resolved from the default backend's device kind at record
        time; achieved GB/s and %-of-roof are derived here so every
        consumer (bench.py, BENCH_*.json) reports the same arithmetic.
        cold=True flags a span whose wall includes jit trace/compile.
        The record also lands as a `kernel` child span of the innermost
        open span (trace export), with `attrs` merged in."""
        with self._lock:
            if not self.enabled:
                return None
            cur, trace = self.current, self.trace
        roof = None
        try:
            import jax
            if jax.default_backend() == "tpu":
                roof = hbm_roof_gbps(jax.devices()[0].device_kind)
        except Exception:
            pass
        rec = KernelRoofline(
            kernel=name, wall_seconds=round(wall_seconds, 4),
            bytes_hbm=float(bytes_hbm), cold=cold,
            **roofline_fields(wall_seconds, bytes_hbm, roof))
        cur.kernel_metrics.append(rec)
        trace.add_complete(
            name, "kernel", wall_seconds, bytes_hbm=rec.bytes_hbm,
            achieved_gbps=rec.achieved_gbps, roof_gbps=rec.roof_gbps,
            pct_of_roof=rec.pct_of_roof, cold=rec.cold, **(attrs or {}))
        return rec

    def sweep_convergence(self, family: str, kernel: str, rounds: int,
                          data_passes: int, lane_passes: int,
                          lanes_total: int, lanes_retired: int,
                          active_per_round=(), iters_per_round=(),
                          bucket_sizes=()) -> Optional[SweepConvergence]:
        """Record one sweep's round/pass telemetry (no-op unless enabled).
        The validator reports here after every streamed GLM sweep; bench.py
        reads the same numbers off Validator.last_streamed_telemetry for
        its executed-FLOP accounting."""
        with self._lock:
            if not self.enabled:
                return None
            cur, trace = self.current, self.trace
        rec = SweepConvergence(
            family=family, kernel=kernel, rounds=int(rounds),
            data_passes=int(data_passes), lane_passes=int(lane_passes),
            lanes_total=int(lanes_total), lanes_retired=int(lanes_retired),
            active_per_round=[int(v) for v in active_per_round],
            iters_per_round=[int(v) for v in iters_per_round],
            bucket_sizes=[int(v) for v in bucket_sizes])
        cur.sweep_metrics.append(rec)
        trace.add_complete(
            f"{family}:{kernel}", "sweep", 0.0, **rec.to_json())
        return rec

    def stats_pass(self, driver: str, rows: int, cols: int, tiles: int,
                   bytes_hbm: float, wall_seconds: float,
                   cold: Optional[bool] = None, passes: int = 1,
                   label: str = "stats") -> Optional[StatsPass]:
        """Record one statistics-engine pass (no-op unless enabled).

        Three artifacts from one call, so every consumer sees the same
        numbers: a StatsPass telemetry record (rides AppMetrics JSON as
        "stats_metrics" and attaches under the innermost open span — the
        SanityChecker fit stage when the workflow is traced), a
        kernel-roofline span named stats_pass[<driver>] (bytes/roofline
        attribution in the trace's kernel table and BENCH JSON's
        kernel_roofline list), and a `stats_pass` event on the streaming
        event log."""
        with self._lock:
            if not self.enabled:
                return None
            cur = self.current
        rec = StatsPass(driver=driver, rows=int(rows), cols=int(cols),
                        tiles=int(tiles), bytes_hbm=float(bytes_hbm),
                        wall_seconds=round(wall_seconds, 6),
                        passes=int(passes), label=label, cold=cold)
        cur.stats_metrics.append(rec)
        self.kernel(f"stats_pass[{driver}]", wall_seconds, bytes_hbm,
                    cold=cold, attrs={"rows": int(rows), "cols": int(cols),
                                      "tiles": int(tiles),
                                      "passes": int(passes),
                                      "label": label})
        self.event("stats_pass", driver=driver, rows=int(rows),
                   cols=int(cols), tiles=int(tiles), passes=int(passes),
                   bytes_hbm=float(bytes_hbm),
                   wall_seconds=round(wall_seconds, 6), label=label)
        return rec

    def ingest_pass(self, label: str, workers: int, shards: int,
                    chunks: int, rows: int, parse_seconds: float,
                    wall_seconds: float) -> Optional[IngestPass]:
        """Record one sharded-ingest parse pass (no-op unless enabled).

        Mirrors stats_pass: an IngestPass telemetry record (rides
        AppMetrics JSON as "ingest_metrics") plus an `ingest_pass` event
        on the streaming event log (docs/observability.md). The per-tile
        decode walls themselves ride as `tile_parse` spans emitted by
        the parse workers, one Perfetto lane per worker."""
        with self._lock:
            if not self.enabled:
                return None
            cur = self.current
        rec = IngestPass(label=label, workers=int(workers),
                         shards=int(shards), chunks=int(chunks),
                         rows=int(rows),
                         parse_seconds=round(parse_seconds, 6),
                         wall_seconds=round(wall_seconds, 6))
        cur.ingest_metrics.append(rec)
        self.event("ingest_pass", label=label, workers=int(workers),
                   shards=int(shards), chunks=int(chunks), rows=int(rows),
                   parse_seconds=round(parse_seconds, 6),
                   wall_seconds=round(wall_seconds, 6))
        return rec

    def latency(self, name: str, wall_seconds: float
                ) -> Optional[LatencyHistogram]:
        """Record one latency observation into the named streaming
        histogram (no-op unless enabled). The serving engine reports its
        per-request/per-phase walls here so p50/p95/p99 ride AppMetrics
        JSON under "latency_metrics" next to the kernel/sweep telemetry —
        same numbers the engine's own /metrics endpoint serves."""
        with self._lock:
            if not self.enabled:
                return None
            hist = self.current.latency_metrics.get(name)
            if hist is None:
                hist = self.current.latency_metrics.setdefault(
                    name, LatencyHistogram(name))
        hist.record(wall_seconds)  # the histogram has its own lock
        return hist

    def save(self, path: str, close: bool = True) -> None:
        """AppMetrics JSON + (new) the span tree under "spans" — every
        pre-existing key keeps its exact shape (golden-tested), the tree
        rides along for trace-report.

        close=False writes a SNAPSHOT without finishing: a run that
        JOINED an outer collection (runner.run inside a BENCH_TRACE_DIR
        trace) must not close the outer span tree mid-run — its artifact
        is the enclosing run's state so far, duration up to now."""
        with self._lock:
            # snapshot under the lifecycle lock (latency() inserts into
            # latency_metrics from serving threads mid-iteration
            # otherwise); the file write below happens OUTSIDE it
            if close:
                doc = self.finish().to_json()
            else:
                doc = self.current.to_json()
                if not self._finished:
                    doc["duration_seconds"] = max(
                        time.time() - self.current.start_time, 0.0)
            if self.trace.spans:
                doc["spans"] = self.trace.to_json()
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)

    def save_chrome_trace(self, path: str, close: bool = True) -> None:
        """Chrome trace_event export of the span tree — open the file in
        Perfetto (ui.perfetto.dev) or chrome://tracing. close=False (a
        joined collection, see save) exports with still-open spans drawn
        up to now instead of closing them."""
        if close:
            self.finish()
        with self._lock:
            trace, app_name = self.trace, self.current.app_name
        tracing.write_chrome_trace(path, trace, app_name=app_name)


# the process-wide collector the workflow engine reports to
collector = MetricsCollector()


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Device-timeline tracing via jax.profiler (the reference's Spark UI /
    event-log slot). View with TensorBoard or xprof."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
