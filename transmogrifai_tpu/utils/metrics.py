"""Run metrics + tracing registry.

Reference: utils/.../spark/OpSparkListener.scala:56-164 — per-stage/job/app
metrics (durations, GC, shuffle/IO bytes) collected by a Spark listener,
opt-in via OpParams.collectStageMetrics, surfaced at app end. The TPU
equivalents are per-stage wall clock + row counts + XLA compile counts, and
a `trace()` context manager around jax.profiler for device timelines.

Collection is opt-in and process-local: `enable()` (or
OpParams.collect_stage_metrics=True through the runner) turns it on; the
workflow engine reports fit/transform spans here.
"""
from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


@dataclass
class StageMetric:
    """One fit/transform span (reference StageMetrics case class)."""

    stage_name: str
    uid: str
    phase: str              # 'fit' | 'transform' | 'fused-transform'
    wall_seconds: float
    n_rows: int = 0
    n_stages_fused: int = 1

    def to_json(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class AppMetrics:
    """Whole-run metrics (reference AppMetrics)."""

    app_name: str = "transmogrifai_tpu"
    start_time: float = 0.0
    end_time: float = 0.0
    stage_metrics: List[StageMetric] = field(default_factory=list)

    @property
    def duration_seconds(self) -> float:
        return max(self.end_time - self.start_time, 0.0)

    def total_stage_seconds(self) -> float:
        return sum(m.wall_seconds for m in self.stage_metrics)

    def to_json(self) -> Dict[str, Any]:
        return {"app_name": self.app_name,
                "duration_seconds": self.duration_seconds,
                "total_stage_seconds": self.total_stage_seconds(),
                "stage_metrics": [m.to_json() for m in self.stage_metrics]}

    def pretty(self) -> str:
        lines = [f"{'Stage':<42}{'Phase':<18}{'Rows':>9}{'Seconds':>10}"]
        for m in self.stage_metrics:
            lines.append(f"{m.stage_name[:41]:<42}{m.phase:<18}"
                         f"{m.n_rows:>9}{m.wall_seconds:>10.4f}")
        lines.append(f"Total: {self.total_stage_seconds():.4f}s over "
                     f"{len(self.stage_metrics)} spans")
        return "\n".join(lines)


class MetricsCollector:
    """Process-local registry (the listener's slot in this runtime)."""

    def __init__(self) -> None:
        self.enabled = False
        self.current = AppMetrics()

    def enable(self, app_name: str = "transmogrifai_tpu") -> None:
        self.enabled = True
        self.current = AppMetrics(app_name=app_name, start_time=time.time())

    def disable(self) -> None:
        self.enabled = False

    def finish(self) -> AppMetrics:
        self.current.end_time = time.time()
        return self.current

    @contextlib.contextmanager
    def span(self, stage_name: str, uid: str, phase: str,
             n_rows: int = 0, n_stages_fused: int = 1) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.time()
        try:
            yield
        finally:
            self.current.stage_metrics.append(StageMetric(
                stage_name=stage_name, uid=uid, phase=phase,
                wall_seconds=time.time() - t0, n_rows=n_rows,
                n_stages_fused=n_stages_fused))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.finish().to_json(), f, indent=2)


# the process-wide collector the workflow engine reports to
collector = MetricsCollector()


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Device-timeline tracing via jax.profiler (the reference's Spark UI /
    event-log slot). View with TensorBoard or xprof."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
