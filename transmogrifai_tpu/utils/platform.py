"""Platform forcing: run JAX on an emulated multi-device CPU mesh.

This image's sitecustomize dials a TPU tunnel on first jax backend init;
when the tunnel is down, init hangs indefinitely or raises. Every entry
point that is *defined* to run on emulated CPU devices (tests, the driver's
multichip dryrun, bench fallback) must force the CPU platform BEFORE any
backend initializes. Env vars alone are too late when jax was already
imported at interpreter startup, so we also update the live jax config —
the same defense tests/conftest.py applied in round 1, now shared.
"""
from __future__ import annotations

import logging
import os
import re
from typing import Optional

_COUNT_FLAG = "--xla_force_host_platform_device_count"

_log = logging.getLogger("transmogrifai_tpu.platform")

#: the directory enable_compilation_cache last pointed jax at (None =
#: cache disabled / not yet configured) — serve --prewarm-only reports it
_cache_dir: Optional[str] = None
_cache_logged: object = ()  # last state logged; () = nothing yet


def _log_cache_state(state: Optional[str], msg: str, *args: object) -> None:
    """One line per distinct cache state — startup logs once, and a
    re-point (force_cpu re-scoping the dir) logs the new location
    instead of leaving the stale line as the record."""
    global _cache_logged
    if _cache_logged != state:
        _cache_logged = state
        _log.info(msg, *args)


def compile_cache_dir() -> Optional[str]:
    """Active persistent-compilation-cache directory, or None when the
    cache is disabled (opt-out, read-only home, old jax)."""
    return _cache_dir


def force_cpu(n_devices: int = 8) -> None:
    """Force the CPU backend with >= n_devices virtual devices.

    Safe to call multiple times; raises nothing if backends are already
    initialized (callers assert on the device count they actually got).
    Must run before jax.devices()/device_put/jit execution.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(re.escape(_COUNT_FLAG) + r"=(\d+)", flags)
    if m:
        if int(m.group(1)) < n_devices:
            flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
            os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        # Backends already initialized — nothing safe to change; the caller's
        # device-count assert will report what is actually available.
        pass
    # re-point the persistent cache now that the platform is known: the
    # import-time enable ran before JAX_PLATFORMS was set, so it chose
    # the TPU/default dir — CPU-forced processes must not share it (their
    # executables carry different CPU target tuning; see the -cpu scope
    # note in enable_compilation_cache)
    enable_compilation_cache()


def enable_compilation_cache() -> None:
    """Point XLA's persistent compilation cache at a durable directory.

    Every workflow train/score and every example previously re-paid all
    XLA compiles on each cold process (VERDICT r2: op_titanic_simple
    149s CPU, compile-dominated). The cache persists compiled
    executables keyed by HLO fingerprint, so a second run of the same
    flow skips compilation entirely — the serving-cold-start story of
    the reference's MLeap path, solved the XLA way.

    Directory: `TMOG_COMPILE_CACHE_DIR` (the documented knob — an
    explicit directory taken as-is, or "0"/"off" to disable; the serve
    prewarm story in docs/serving.md keys off it), falling back to the
    older `TMOG_COMPILE_CACHE` spelling, else a machine-scoped default
    under ~/.cache/transmogrifai_tpu/xla-*. One line is logged at startup
    (logger `transmogrifai_tpu.platform`) saying whether the cache is
    active and where — `serve --prewarm-only` is only useful when it is.
    Safe to call repeatedly and before or after backend init, BUT the
    dir must be settled before the process's FIRST compile: jax
    initializes its compilation-cache singleton on first use, and a
    re-point after that is silently ignored (measured — a serving
    restart therefore exports TMOG_COMPILE_CACHE_DIR at launch, not
    mid-process). force_cpu's re-point is fine: it runs before any
    compile by the module contract.
    """
    global _cache_dir, _cache_logged
    loc = os.environ.get("TMOG_COMPILE_CACHE_DIR",
                         os.environ.get("TMOG_COMPILE_CACHE", "")).strip()
    if loc.lower() in ("0", "off", "none", "disable"):
        _cache_dir = None
        _log_cache_state(None, "persistent compile cache: DISABLED "
                               "(opt-out)")
        return
    if not loc:
        # scope the default cache by the host's CPU feature set: XLA:CPU
        # AOT results bake in target machine features, and this image
        # migrates across hosts — loading an avx512-variant executable on
        # a host without those features risks SIGILL (cpu_aot_loader
        # warns exactly this). An explicit $TMOG_COMPILE_CACHE is taken
        # as-is (single-machine setups, the bench's per-run dirs).
        import hashlib
        import platform as _pf
        tag = _pf.machine()
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    # x86 lists "flags", aarch64 lists "Features"
                    if line.startswith(("flags", "Features")):
                        tag += hashlib.sha1(
                            line.encode()).hexdigest()[:10]
                        break
        except OSError:
            pass
        # AOT entries also bake in XLA-version-specific target tuning
        # (e.g. prefer-no-scatter) that /proc/cpuinfo cannot see: entries
        # from another jaxlib spam cpu_aot_loader incompatibility errors
        # on every load, so the version is part of the scope
        try:
            import jaxlib
            tag += f"-jl{jaxlib.__version__}"
        except Exception:
            pass
        # a TPU-backend process compiles its host-side CPU executables
        # with different target tuning (+prefer-no-scatter/-gather) than
        # a pure-CPU process; sharing one dir makes every cross-load
        # spam cpu_aot_loader feature-mismatch errors. Scope explicit
        # CPU-platform processes into their own dir (the TPU/default dir
        # keeps its name so existing warm entries stay valid).
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            tag += "-cpu"
        loc = os.path.join(os.path.expanduser("~"), ".cache",
                           "transmogrifai_tpu", f"xla-{tag}")
    try:
        os.makedirs(loc, exist_ok=True)
    except OSError:
        _cache_dir = None
        _log_cache_state(None, "persistent compile cache: DISABLED "
                               "(cannot create %s)", loc)
        return  # read-only home: run uncached
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", loc)
        # default min compile time is 1s; AutoML DAGs are MANY small
        # programs (a titanic train is ~100 executables mostly compiling
        # in 0.05-0.2s each), so cache every compile — the write cost is
        # microseconds against disk
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # bound the cache (LRU eviction) — cache-everything without a cap
        # would grow ~/.cache without bound across datasets/shapes
        jax.config.update("jax_compilation_cache_max_size",
                          2 * 1024 ** 3)
    except Exception:
        _cache_dir = None
        _log_cache_state(None, "persistent compile cache: DISABLED "
                               "(jax too old for cache configs)")
        return  # older jax without these configs: run uncached
    _cache_dir = loc
    _log_cache_state(loc, "persistent compile cache: ACTIVE at %s", loc)
