"""Platform forcing: run JAX on an emulated multi-device CPU mesh.

This image's sitecustomize dials a TPU tunnel on first jax backend init;
when the tunnel is down, init hangs indefinitely or raises. Every entry
point that is *defined* to run on emulated CPU devices (tests, the driver's
multichip dryrun, bench fallback) must force the CPU platform BEFORE any
backend initializes. Env vars alone are too late when jax was already
imported at interpreter startup, so we also update the live jax config —
the same defense tests/conftest.py applied in round 1, now shared.
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu(n_devices: int = 8) -> None:
    """Force the CPU backend with >= n_devices virtual devices.

    Safe to call multiple times; raises nothing if backends are already
    initialized (callers assert on the device count they actually got).
    Must run before jax.devices()/device_put/jit execution.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(re.escape(_COUNT_FLAG) + r"=(\d+)", flags)
    if m:
        if int(m.group(1)) < n_devices:
            flags = flags.replace(m.group(0), f"{_COUNT_FLAG}={n_devices}")
            os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        # Backends already initialized — nothing safe to change; the caller's
        # device-count assert will report what is actually available.
        pass
