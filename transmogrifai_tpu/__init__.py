"""TransmogrifAI-TPU: a TPU-native AutoML framework for structured data.

A ground-up rebuild of the capabilities of TransmogrifAI (Salesforce's
Scala/Spark AutoML library) designed for TPUs: typed feature pipelines compile
to XLA programs, automated feature engineering/validation run as device
reductions over an HBM-resident feature matrix, and the model-selection
cross-validation sweep runs as vmapped/sharded JAX programs over a device
mesh (batch x fold x grid axes) instead of a Spark cluster.

Public API mirrors the reference's (OpWorkflow, FeatureBuilder,
Transmogrifier, SanityChecker, ModelSelectors, evaluators) so a reference
user can switch with minimal relearning.
"""
from __future__ import annotations

import os as _os

__version__ = "0.4.0"

# Honor an explicit JAX_PLATFORMS=cpu at the CONFIG level before any
# backend init: this image's sitecustomize registers a remote-TPU plugin
# whose half-up tunnel can hang backend creation even when the env var is
# set (the register hook bypasses the env filter; jax.config does not).
# Examples, CI and user scripts then cannot deadlock on the tunnel.
if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    try:
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
    except Exception:  # jax absent/old: nothing to guard
        pass

# Persistent XLA compilation cache: cold processes (examples, CI, local
# serving starts) stop re-paying every compile. Point it with
# TMOG_COMPILE_CACHE_DIR=<dir> (serve prewarm, docs/serving.md), opt out
# with TMOG_COMPILE_CACHE_DIR=0; see
# utils/platform.enable_compilation_cache.
try:
    from .utils.platform import enable_compilation_cache as _ecc
    _ecc()
except Exception:
    pass

from . import types
from .types import *  # noqa: F401,F403 — feature type hierarchy
from .features.feature import Feature, FeatureHandle, FeatureHistory
from .features.builder import FeatureBuilder, infer_feature_type
from .features.generator import FeatureGeneratorStage
from .stages.base import (
    Estimator,
    JaxTransformer,
    LambdaTransformer,
    PipelineStage,
    Transformer,
    binary_transformer,
    unary_transformer,
)
from .stages.params import Param, ParamMap, param_grid
from .data.dataset import Column, Dataset, column_from_values
from .data.vector import VectorColumnMetadata, VectorMetadata
from . import dsl  # installs rich feature syntax (reference dsl/ implicits)

__all__ = [n for n in dir() if not n.startswith("_")]
