"""Misc feature transformers: alias, occurrence, scaling, calibration,
missing-value fill, vector index drops, label-driven bucketization.

Reference: core/.../impl/feature/{AliasTransformer, ToOccurTransformer,
ScalerTransformer(186), FillMissingWithMean, PercentileCalibrator,
DropIndicesByTransformer, DecisionTreeNumericBucketizer(300)}.scala.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..data.dataset import Column
from ..data.vector import VectorColumnMetadata, VectorMetadata
from ..stages.base import Estimator, JaxTransformer, Transformer
from ..stages.params import Param
from ..types import (
    Binary, BinaryMap, ColumnKind, FeatureType, Integral, OPMap, OPVector,
    PickListMap, Real, RealMap, RealNN, TextMap,
)


class AliasTransformer(JaxTransformer):
    """Identity renaming a feature (reference AliasTransformer)."""

    input_types = (FeatureType,)
    output_type = Real  # replaced at set_input time

    def __init__(self, name: str = "alias", uid: Optional[str] = None,
                 **params):
        self.alias = name
        params.pop("operation_name", None)
        super().__init__(f"alias_{name}", uid=uid, **params)

    def set_input(self, *features):
        out = super().set_input(*features)
        self.output_type = features[0].feature_type
        return out

    def get_jax_fn(self):
        return lambda a: a

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.pop("lambda", None)
        d.update(name=self.alias)
        return d


class ToOccurTransformer(Transformer):
    """Any feature -> Binary(non-empty) (reference ToOccurTransformer)."""

    input_types = (FeatureType,)
    output_type = Binary

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "toOccur"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        return Binary(not vals[0].is_empty)

    def transform_columns(self, *cols: Column) -> Column:
        c = cols[0]
        if c.kind in (ColumnKind.FLOAT, ColumnKind.INT, ColumnKind.BOOL):
            data = (~np.isnan(np.asarray(c.data, np.float64))).astype(np.float64)
        else:
            data = np.array([0.0 if self._is_empty(v) else 1.0
                             for v in c.data], np.float64)
        return Column(kind=ColumnKind.BOOL, data=data)

    @staticmethod
    def _is_empty(v) -> bool:
        if v is None:
            return True
        if isinstance(v, float) and np.isnan(v):
            return True
        return isinstance(v, (str, list, tuple, set, dict)) and len(v) == 0


class ScalerTransformer(JaxTransformer):
    """Linear/log scaling with recorded scaling args so a downstream
    DescalerTransformer can invert predictions (reference
    ScalerTransformer.scala:186 stores ScalingArgs in metadata)."""

    input_types = (Real,)
    output_type = Real

    @classmethod
    def _declare_params(cls):
        return [Param("scaling_type", "linear|logarithmic", "linear"),
                Param("slope", "linear slope", 1.0),
                Param("intercept", "linear intercept", 0.0)]

    def __init__(self, scaling_type: str = "linear", slope: float = 1.0,
                 intercept: float = 0.0, uid: Optional[str] = None, **params):
        params.setdefault("scaling_type", scaling_type)
        params.setdefault("slope", slope)
        params.setdefault("intercept", intercept)
        super().__init__(params.pop("operation_name", "scaled"),
                         uid=uid, **params)

    def scaling_args(self) -> Dict[str, Any]:
        return {"scaling_type": self.get_param("scaling_type"),
                "slope": self.get_param("slope"),
                "intercept": self.get_param("intercept")}

    def get_jax_fn(self):
        import jax.numpy as jnp
        kind = self.get_param("scaling_type")
        if kind == "logarithmic":
            return lambda a: jnp.where(a > 0, jnp.log(jnp.maximum(a, 1e-12)),
                                       jnp.nan)
        m, b = float(self.get_param("slope")), float(self.get_param("intercept"))
        return lambda a: m * a + b


class DescalerTransformer(Transformer):
    """Inverts a ScalerTransformer's scaling on another feature (reference
    DescalerTransformer reads ScalingArgs from metadata; here the scaler
    stage is referenced directly by the dsl)."""

    input_types = (Real, Real)   # (value_to_descale, scaled_source)
    output_type = Real

    def __init__(self, scaler: Optional[ScalerTransformer] = None,
                 uid: Optional[str] = None, **params):
        self.scaler = scaler
        super().__init__(params.pop("operation_name", "descaled"),
                         uid=uid, **params)

    def _invert(self, arr: np.ndarray) -> np.ndarray:
        args = self.scaler.scaling_args() if self.scaler else \
            {"scaling_type": "linear", "slope": 1.0, "intercept": 0.0}
        if args["scaling_type"] == "logarithmic":
            return np.exp(arr)
        m = float(args["slope"]) or 1.0
        return (arr - float(args["intercept"])) / m

    def transform_value(self, *vals):
        v = vals[0].value
        if v is None:
            return Real(None)
        return Real(float(self._invert(np.asarray([v]))[0]))

    def transform_columns(self, *cols: Column) -> Column:
        return Column(kind=ColumnKind.FLOAT,
                      data=self._invert(np.asarray(cols[0].data, np.float64)))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(scaling_args=self.scaler.scaling_args() if self.scaler
                 else None)
        return d

    @classmethod
    def from_save_args(cls, args: Dict[str, Any]) -> "DescalerTransformer":
        t = cls(uid=args.get("uid"))
        sa = args.get("scaling_args")
        if sa:
            t.scaler = ScalerTransformer(**sa)
        return t


class FillMissingWithMean(Estimator):
    """Real -> RealNN, empties replaced by the train mean (reference
    FillMissingWithMean.scala). The stat pass is an XLA reduction."""

    input_types = (Real,)
    output_type = RealNN

    @classmethod
    def _declare_params(cls):
        return [Param("default_value", "fill when column all-empty", 0.0)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "fillWithMean"),
                         uid=uid, **params)

    def fit_columns(self, *cols: Column) -> Transformer:
        data = np.asarray(cols[0].data, np.float64)
        valid = data[~np.isnan(data)]
        mean = float(valid.mean()) if len(valid) else \
            float(self.get_param("default_value"))
        return FillMissingWithMeanModel(mean, operation_name=self.operation_name)


class FillMissingWithMeanModel(JaxTransformer):
    input_types = (Real,)
    output_type = RealNN

    def __init__(self, mean: float = 0.0, uid: Optional[str] = None, **params):
        self.mean = float(mean)
        super().__init__(params.pop("operation_name", "fillWithMean"),
                         uid=uid, **params)

    def get_jax_fn(self):
        import jax.numpy as jnp
        m = self.mean
        return lambda a: jnp.where(jnp.isnan(a), m, a)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.pop("lambda", None)
        d.update(mean=self.mean)
        return d


class PercentileCalibrator(Estimator):
    """RealNN score -> RealNN percentile bucket [0, buckets-1] (reference
    PercentileCalibrator.scala: spline over ntile boundaries)."""

    input_types = (RealNN,)
    output_type = RealNN

    @classmethod
    def _declare_params(cls):
        return [Param("buckets", "number of percentile buckets", 100)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "percentileCalibrator"),
                         uid=uid, **params)

    def fit_columns(self, *cols: Column) -> Transformer:
        b = int(self.get_param("buckets"))
        data = np.asarray(cols[0].data, np.float64)
        qs = np.quantile(data[~np.isnan(data)],
                         np.arange(1, b) / b) if len(data) else np.zeros(b - 1)
        return PercentileCalibratorModel(np.asarray(qs, np.float64),
                                         operation_name=self.operation_name)


class PercentileCalibratorModel(JaxTransformer):
    input_types = (RealNN,)
    output_type = RealNN

    def __init__(self, splits: Optional[np.ndarray] = None,
                 uid: Optional[str] = None, **params):
        self.splits = np.asarray(splits if splits is not None else [],
                                 np.float64)
        super().__init__(params.pop("operation_name", "percentileCalibrator"),
                         uid=uid, **params)

    def get_jax_fn(self):
        import jax.numpy as jnp
        splits = jnp.asarray(self.splits, jnp.float32)
        return lambda a: jnp.searchsorted(
            splits, jnp.asarray(a, jnp.float32).reshape(a.shape),
            side="right").astype(jnp.float32)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.pop("lambda", None)
        d.update(splits=self.splits)
        return d


class DropIndicesByTransformer(Transformer):
    """OPVector -> OPVector dropping columns whose metadata matches a
    predicate (reference DropIndicesByTransformer — e.g. drop null
    indicators before LOCO)."""

    input_types = (OPVector,)
    output_type = OPVector

    def __init__(self, predicate: Optional[Callable[[VectorColumnMetadata], bool]]
                 = None, uid: Optional[str] = None, **params):
        self.predicate = predicate or (lambda c: False)
        self._keep: Optional[List[int]] = None
        super().__init__(params.pop("operation_name", "dropIndices"),
                         uid=uid, **params)

    def transform_columns(self, *cols: Column) -> Column:
        vec = cols[0]
        md = vec.metadata
        if md is None:
            return vec
        keep = [c.index for c in md.columns if not self.predicate(c)]
        self._keep = keep
        return Column(kind=ColumnKind.VECTOR,
                      data=np.ascontiguousarray(vec.data[:, keep]),
                      metadata=md.select(keep))

    def transform_value(self, *vals):
        X = np.asarray(vals[0].value, np.float32)
        if self._keep is None:
            return OPVector(X)
        return OPVector(X[self._keep])


def find_label_splits(x: np.ndarray, label: np.ndarray, max_splits: int,
                      min_info_gain: float) -> List[float]:
    """Label-aware bucket boundaries for one numeric column: grow a single
    decision tree on (x -> label) with ops/trees.grow_tree (one XLA
    program) and read the split thresholds off the grown nodes. Shared by
    the scalar and per-map-key bucketizers (reference
    DecisionTreeNumericBucketizer.scala:300 /
    DecisionTreeNumericMapBucketizer.scala)."""
    import jax
    import jax.numpy as jnp
    from ..ops import trees as T

    ok = ~(np.isnan(x) | np.isnan(label))
    depth = max(1, math.ceil(math.log2(max_splits + 1)))
    splits: List[float] = []
    if ok.sum() >= 4 and np.nanstd(x[ok]) > 0:
        xv = x[ok].astype(np.float32)[:, None]
        yv = label[ok].astype(np.float32)
        n_classes = int(yv.max()) + 1 if yv.size else 2
        G = (np.eye(max(n_classes, 2), dtype=np.float32)[yv.astype(int)]
             if n_classes <= 20 else yv[:, None])
        edges = T.quantile_edges(jnp.asarray(xv), 64)
        Xb = T.bin_matrix(jnp.asarray(xv), edges)
        tree = T.grow_tree(
            Xb, jnp.asarray(G), jnp.ones(len(yv), jnp.float32),
            jax.random.PRNGKey(0), depth=depth, n_bins=64,
            leaf_mode="mean", min_info_gain=min_info_gain,
            min_instances=max(1.0, 0.01 * len(yv)))
        tv = np.asarray(T.thresholds_to_values(tree.feat, tree.thresh,
                                               edges))
        splits = sorted({float(t) for t in tv if np.isfinite(t)})
        splits = splits[:max_splits]
    return splits


class DecisionTreeNumericBucketizer(Estimator):
    """(label RealNN, Real) -> OPVector one-hot of label-driven buckets.

    Reference DecisionTreeNumericBucketizer.scala:300 fits a single Spark
    decision tree on (feature -> label) and uses its split points as bucket
    boundaries. Here the tree is ops/trees.grow_tree on the one feature —
    still one XLA program — and splits are read off the grown nodes.
    """

    input_types = (RealNN, Real)
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        return [Param("max_splits", "max bucket boundaries", 15),
                Param("min_info_gain", "min split gain", 0.01),
                Param("track_nulls", "emit null indicator column", True),
                Param("track_invalid", "keep bucketizing when no signal", False)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "dtBucketizer"),
                         uid=uid, **params)

    def fit_columns(self, *cols: Column) -> Transformer:
        label = np.asarray(cols[0].data, np.float64)
        x = np.asarray(cols[1].data, np.float64)
        splits = find_label_splits(
            x, label, int(self.get_param("max_splits")),
            float(self.get_param("min_info_gain")))
        return DecisionTreeNumericBucketizerModel(
            splits=np.asarray(splits, np.float64),
            track_nulls=bool(self.get_param("track_nulls")),
            feature_name=(self._input_features[1].name
                          if len(self._input_features) > 1 else "feature"),
            operation_name=self.operation_name)


class DecisionTreeNumericBucketizerModel(Transformer):
    input_types = (RealNN, Real)
    output_type = OPVector

    def __init__(self, splits: Optional[np.ndarray] = None,
                 track_nulls: bool = True, feature_name: str = "feature",
                 uid: Optional[str] = None, **params):
        self.splits = np.asarray(splits if splits is not None else [],
                                 np.float64)
        self.track_nulls = bool(track_nulls)
        self.feature_name = feature_name
        super().__init__(params.pop("operation_name", "dtBucketizer"),
                         uid=uid, **params)

    @property
    def n_buckets(self) -> int:
        return len(self.splits) + 1

    def _encode(self, x: np.ndarray) -> np.ndarray:
        n = len(x)
        width = self.n_buckets + (1 if self.track_nulls else 0)
        out = np.zeros((n, width), np.float32)
        isnan = np.isnan(x)
        bucket = np.searchsorted(self.splits, x, side="right")
        bucket = np.where(isnan, 0, bucket)
        out[np.arange(n), bucket] = (~isnan).astype(np.float32)
        if self.track_nulls:
            out[:, -1] = isnan.astype(np.float32)
        return out

    def transform_columns(self, *cols: Column) -> Column:
        x = np.asarray(cols[-1].data, np.float64)
        return Column(kind=ColumnKind.VECTOR, data=self._encode(x),
                      metadata=self.output_metadata())

    def transform_value(self, *vals):
        v = vals[-1].value
        x = np.asarray([np.nan if v is None else float(v)])
        return OPVector(self._encode(x)[0])

    def output_metadata(self) -> Optional[VectorMetadata]:
        cols = [VectorColumnMetadata(
            parent_feature_name=self.feature_name,
            parent_feature_type="Real", grouping=self.feature_name,
            indicator_value=f"bucket_{i}", index=i)
            for i in range(self.n_buckets)]
        if self.track_nulls:
            from ..data.vector import NULL_STRING
            cols.append(VectorColumnMetadata(
                parent_feature_name=self.feature_name,
                parent_feature_type="Real", grouping=self.feature_name,
                indicator_value=NULL_STRING, index=self.n_buckets))
        return VectorMetadata(name=self.output_name() or "bucketized",
                              columns=cols)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(splits=self.splits, track_nulls=self.track_nulls,
                 feature_name=self.feature_name)
        return d


class DecisionTreeNumericMapBucketizer(Estimator):
    """(label RealNN, numeric OPMap) -> OPVector of label-driven buckets
    PER MAP KEY.

    Reference DecisionTreeNumericMapBucketizer.scala (170 LoC): the scalar
    DecisionTreeNumericBucketizer applied independently to every key of a
    Real/Integral/Currency/Percent map. Keys are discovered at fit; each
    key's split search is the same single-tree XLA program
    (find_label_splits); keys with no informative splits emit only their
    null column (shouldSplit=false in the reference).
    """

    # declared RealMap for data-generation/tooling; check_input_types
    # accepts every numeric OPMap subtype
    input_types = (RealNN, RealMap)
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        return [Param("max_splits", "max bucket boundaries per key", 15),
                Param("min_info_gain", "min split gain", 0.01),
                Param("track_nulls", "emit per-key null indicator", True),
                Param("clean_keys", "normalize map keys", False)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "dtMapBucketizer"),
                         uid=uid, **params)

    def check_input_types(self, features) -> None:
        from ..types import OPMap, RealNN as _RealNN
        if len(features) != 2:
            raise TypeError(f"{self.stage_name} expects (label, map) inputs")
        if not issubclass(features[0].feature_type, _RealNN):
            raise TypeError(f"{self.stage_name} label must be RealNN")
        if not issubclass(features[1].feature_type, OPMap):
            raise TypeError(f"{self.stage_name} input 1 must be an OPMap")

    def fit_columns(self, *cols: Column) -> Transformer:
        from ..automl.vectorizers.encoding import extract_key_columns
        from ..automl.vectorizers.maps import clean_key

        label = np.asarray(cols[0].data, np.float64)
        data = cols[1].data
        clean = bool(self.get_param("clean_keys"))
        keys = sorted({clean_key(str(k), clean)
                       for m in data if m for k in m})
        key_cols = extract_key_columns(
            data, keys, (lambda k: clean_key(k, True)) if clean else None)
        max_splits = int(self.get_param("max_splits"))
        min_gain = float(self.get_param("min_info_gain"))
        splits_per_key = []
        for k in keys:
            x = np.array([np.nan if v is None else float(v)
                          for v in key_cols[k]], np.float64)
            splits_per_key.append(
                find_label_splits(x, label, max_splits, min_gain))
        return DecisionTreeNumericMapBucketizerModel(
            keys=keys, splits_per_key=splits_per_key,
            track_nulls=bool(self.get_param("track_nulls")),
            clean_keys=clean,
            map_name=(self._input_features[1].name
                      if len(self._input_features) > 1 else "map"),
            operation_name=self.operation_name)


class DecisionTreeNumericMapBucketizerModel(Transformer):
    input_types = (RealNN, RealMap)
    output_type = OPVector
    is_sequence = False

    def __init__(self, keys: Optional[Sequence[str]] = None,
                 splits_per_key: Optional[Sequence[Sequence[float]]] = None,
                 track_nulls: bool = True, clean_keys: bool = False,
                 map_name: str = "map", uid: Optional[str] = None, **params):
        self.keys = list(keys or [])
        self.splits_per_key = [np.asarray(s, np.float64)
                               for s in (splits_per_key or [])]
        self.track_nulls = bool(track_nulls)
        self.clean_keys = bool(clean_keys)
        self.map_name = map_name
        super().__init__(params.pop("operation_name", "dtMapBucketizer"),
                         uid=uid, **params)

    def _key_width(self, splits: np.ndarray) -> int:
        # a key with no informative splits keeps only its null column
        buckets = len(splits) + 1 if len(splits) else 0
        return buckets + (1 if self.track_nulls else 0)

    def _encode(self, key_cols: Dict[str, List[Any]], n: int) -> np.ndarray:
        # width may legitimately be 0 (no informative splits, nulls
        # untracked) — a 0-wide block keeps width == len(metadata.columns),
        # the invariant downstream vector indexing relies on
        width = sum(self._key_width(s) for s in self.splits_per_key)
        out = np.zeros((n, width), np.float32)
        at = 0
        for k, splits in zip(self.keys, self.splits_per_key):
            x = np.array([np.nan if v is None else float(v)
                          for v in key_cols[k]], np.float64)
            isnan = np.isnan(x)
            if len(splits):
                nb = len(splits) + 1
                bucket = np.searchsorted(splits, x, side="right")
                bucket = np.where(isnan, 0, bucket)
                rows = np.arange(n)
                out[rows, at + bucket] = (~isnan).astype(np.float32)
                at += nb
            if self.track_nulls:
                out[:, at] = isnan.astype(np.float32)
                at += 1
        return out

    def transform_columns(self, *cols: Column) -> Column:
        from ..automl.vectorizers.encoding import extract_key_columns
        from ..automl.vectorizers.maps import clean_key
        data = cols[-1].data
        key_cols = extract_key_columns(
            data, self.keys,
            (lambda k: clean_key(k, True)) if self.clean_keys else None)
        return Column(kind=ColumnKind.VECTOR,
                      data=self._encode(key_cols, len(data)),
                      metadata=self.output_metadata())

    def transform_value(self, *vals):
        m = vals[-1].value or {}
        from ..automl.vectorizers.maps import clean_key
        if self.clean_keys:
            # first-wins on cleaned-key collisions — must mirror
            # extract_key_columns so row scoring matches the columnar path
            cleaned: Dict[str, Any] = {}
            for k, v in m.items():
                cleaned.setdefault(clean_key(str(k), True), v)
            m = cleaned
        key_cols = {k: [m.get(k)] for k in self.keys}
        return OPVector(self._encode(key_cols, 1)[0])

    def output_metadata(self) -> Optional[VectorMetadata]:
        from ..data.vector import NULL_STRING
        cols: List[VectorColumnMetadata] = []
        i = 0
        for k, splits in zip(self.keys, self.splits_per_key):
            if len(splits):
                for b in range(len(splits) + 1):
                    cols.append(VectorColumnMetadata(
                        parent_feature_name=self.map_name,
                        parent_feature_type="OPMap", grouping=k,
                        indicator_value=f"bucket_{b}", index=i))
                    i += 1
            if self.track_nulls:
                cols.append(VectorColumnMetadata(
                    parent_feature_name=self.map_name,
                    parent_feature_type="OPMap", grouping=k,
                    indicator_value=NULL_STRING, index=i))
                i += 1
        return VectorMetadata(name=self.output_name() or "bucketizedMap",
                              columns=cols)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(keys=self.keys,
                 splits_per_key=[list(map(float, s))
                                 for s in self.splits_per_key],
                 track_nulls=self.track_nulls, clean_keys=self.clean_keys,
                 map_name=self.map_name)
        return d


class FilterMapKeys(Transformer):
    """OPMap -> OPMap keeping/blocking keys (reference
    RichMapFeature.filter:58 — whiteList/blackList key filtering)."""

    input_types = (OPMap,)

    def __init__(self, allow: Optional[Sequence[str]] = None,
                 block: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None, **params):
        self.allow = list(allow) if allow else None
        self._allow_set = set(self.allow) if self.allow is not None else None
        self.block = set(block) if block else set()
        super().__init__(params.pop("operation_name", "filterMapKeys"),
                         uid=uid, **params)

    def set_input(self, *features):
        out = super().set_input(*features)
        self.output_type = features[0].feature_type
        return out

    def _filter(self, m):
        if m is None:
            return None
        allowed = self._allow_set
        return {k: v for k, v in m.items()
                if (allowed is None or k in allowed) and k not in self.block}

    def transform_value(self, *vals):
        return self.output_type(self._filter(vals[0].value))

    def transform_columns(self, *cols: Column) -> Column:
        data = cols[0].data
        out = np.empty(len(data), dtype=object)
        for i, m in enumerate(data):
            out[i] = self._filter(m)
        return Column(kind=ColumnKind.MAP, data=out)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(allow=self.allow, block=sorted(self.block))
        return d


class DateToUnitCircleTransformer(Transformer):
    """Date -> OPVector [sin, cos] of one calendar period (reference
    DateToUnitCircleTransformer.scala; periods as in RichDateFeature
    .toUnitCircle — default HourOfDay). Missing dates map to the origin
    (0, 0), which is equidistant from every point on the circle."""

    input_types = (Integral,)  # Date/DateTime extend Integral
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        return [Param("time_period", "HourOfDay|DayOfWeek|DayOfMonth|"
                      "DayOfYear|WeekOfYear|MonthOfYear", "HourOfDay")]

    def __init__(self, time_period: str = "HourOfDay",
                 uid: Optional[str] = None, **params):
        params.setdefault("time_period", time_period)
        super().__init__(params.pop("operation_name", "toUnitCircle"),
                         uid=uid, **params)

    def _encode(self, ms: np.ndarray) -> np.ndarray:
        from ..automl.vectorizers.dates import unit_circle
        s, c, _ = unit_circle(ms, str(self.get_param("time_period")))
        out = np.empty((len(ms), 2), np.float32)
        out[:, 0] = s
        out[:, 1] = c
        return out

    def transform_columns(self, *cols: Column) -> Column:
        ms = np.asarray(cols[0].data, np.float64)
        return Column(kind=ColumnKind.VECTOR, data=self._encode(ms),
                      metadata=self.output_metadata())

    def transform_value(self, *vals):
        v = vals[0].value
        ms = np.asarray([np.nan if v is None else float(v)])
        return OPVector(self._encode(ms)[0])

    def output_metadata(self) -> Optional[VectorMetadata]:
        name = (self._input_features[0].name if self._input_features
                else "date")
        p = str(self.get_param("time_period"))
        return VectorMetadata(name=self.output_name() or "unitCircle",
                              columns=[
            VectorColumnMetadata(parent_feature_name=name,
                                 parent_feature_type="Date",
                                 descriptor_value=f"{p}_sin", index=0),
            VectorColumnMetadata(parent_feature_name=name,
                                 parent_feature_type="Date",
                                 descriptor_value=f"{p}_cos", index=1)])


class DateToListTransformer(Transformer):
    """Date -> DateList (reference RichDateFeature.toDateList:54 — wraps
    the single timestamp so list aggregators/vectorizers apply)."""

    input_types = (Integral,)

    def __init__(self, uid: Optional[str] = None, **params):
        from ..types import DateList
        self.output_type = DateList
        super().__init__(params.pop("operation_name", "toDateList"),
                         uid=uid, **params)

    def set_input(self, *features):
        out = super().set_input(*features)
        from ..types import DateTime, DateTimeList
        if issubclass(features[0].feature_type, DateTime):
            self.output_type = DateTimeList
        return out

    def transform_value(self, *vals):
        v = vals[0].value
        return self.output_type([] if v is None else [float(v)])

    def transform_columns(self, *cols: Column) -> Column:
        data = np.asarray(cols[0].data, np.float64)
        out = np.empty(len(data), dtype=object)
        for i, v in enumerate(data):
            out[i] = [] if np.isnan(v) else [float(v)]
        return Column(kind=ColumnKind.FLOAT_LIST, data=out)


class ReplaceWithTransformer(Transformer):
    """Replace one value with another, any type (reference
    RichFeature.replaceWith:75). Values compare on the raw `.value`."""

    input_types = (FeatureType,)

    def __init__(self, old_value: Any = None, new_value: Any = None,
                 uid: Optional[str] = None, **params):
        self.old_value = old_value
        self.new_value = new_value
        super().__init__(params.pop("operation_name", "replaceWith"),
                         uid=uid, **params)

    def set_input(self, *features):
        out = super().set_input(*features)
        self.output_type = features[0].feature_type
        return out

    @staticmethod
    def _values_eq(a, b) -> bool:
        if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
            return np.array_equal(np.asarray(a), np.asarray(b))
        return a == b

    def transform_value(self, *vals):
        v = vals[0].value
        return self.output_type(
            self.new_value if self._values_eq(v, self.old_value) else v)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(old_value=self.old_value, new_value=self.new_value)
        return d


class PhoneValidityMap(Transformer):
    """PhoneMap/TextMap -> BinaryMap of per-key phone validity (reference
    RichMapFeature.isValidPhoneDefaultCountryMap via libphonenumber;
    validation shares transformers/text.parse_phone)."""

    input_types = (TextMap,)  # PhoneMap is a TextMap subtype

    @classmethod
    def _declare_params(cls):
        return [Param("default_region", "region for bare numbers", "US")]

    output_type = BinaryMap

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "phoneValidMap"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        from ..transformers.text import parse_phone
        m = vals[0].value or {}
        region = str(self.get_param("default_region"))
        return BinaryMap({k: parse_phone(str(v), region)[0]
                          for k, v in m.items() if v is not None})


class MimeTypeMap(Transformer):
    """Base64Map -> PickListMap of per-key detected MIME types (reference
    RichMapFeature.detectMimeTypes via Tika; detection shares the scalar
    detector's magic-byte matcher, transformers/text.detect_mime)."""

    input_types = (TextMap,)  # Base64Map is a TextMap subtype
    output_type = PickListMap

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "mimeMap"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        from ..transformers.text import detect_mime
        m = vals[0].value or {}
        out = {}
        for k, v in m.items():
            mime = detect_mime(v)
            if mime is not None:
                out[k] = mime
        return PickListMap(out)
