"""Feature-engineering transformers (reference core/.../impl/feature/):
math ops, text processing, scaling/calibration, label-driven bucketization."""
from . import math, misc, text  # noqa: F401 — registered stage modules

__all__ = ["math", "misc", "text"]
