"""Trainable statistical NER tagger — the model-backed analyzer seam.

Reference: core/.../utils/text/OpenNLPNameEntityTagger.scala loads binary
maxent models from models/src/main/resources/OpenNLP/*.bin. Those JVM
artifacts are not shipped here; instead this module provides the same
capability class — a trained, context-sensitive statistical tagger with a
model FILE the stage loads at construction — as an averaged perceptron
over orthographic + contextual features. `NameEntityRecognizer`
(ner.py) takes `model_path=` and falls back to the regex+gazetteer
heuristic when no model is given; the measured lift of model over
heuristic is pinned in tests/test_ner_embedding_quality.py.

The feature design is the standard maxent-NER set (word shape, affixes,
context words, gazetteer flags) — what lets the model tag tokens the
gazetteer has never seen ("Kowalczyk signed...") from their context and
morphology.
"""
from __future__ import annotations

import json
import re
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_CAP_RE = re.compile(r"^[A-Z][a-z'-]+$")
_ALLCAP_RE = re.compile(r"^[A-Z]{2,}$")
_DIGIT_RE = re.compile(r"\d")

OUTSIDE = "O"


def _shape(tok: str) -> str:
    if _ALLCAP_RE.match(tok):
        return "AA"
    if _CAP_RE.match(tok):
        return "Aa"
    if _DIGIT_RE.search(tok):
        return "d"
    return "a"


def token_features(tokens: Sequence[str], i: int,
                   gazetteer: Optional[Dict[str, set]] = None) -> List[str]:
    """Sparse binary features for token i in its sentence."""
    tok = tokens[i]
    low = tok.lower()
    prev = tokens[i - 1].lower() if i > 0 else "<s>"
    nxt = tokens[i + 1].lower() if i + 1 < len(tokens) else "</s>"
    feats = [
        f"w={low}", f"shape={_shape(tok)}",
        f"suf3={low[-3:]}", f"suf4={low[-4:]}", f"pre3={low[:3]}",
        f"prev={prev}", f"next={nxt}",
        f"prevshape={_shape(tokens[i - 1]) if i > 0 else '<s>'}",
        f"nextshape={_shape(tokens[i + 1]) if i + 1 < len(tokens) else '</s>'}",
        f"shape2={_shape(tok)}+{nxt}",
        f"first={i == 0}",
    ]
    if gazetteer:
        for ent, words in gazetteer.items():
            if low in words:
                feats.append(f"gaz={ent}")
            if i > 0 and tokens[i - 1].lower() in words:
                feats.append(f"prevgaz={ent}")
    return feats


class PerceptronNerTagger:
    """Averaged perceptron sequence-less token classifier (the maxent-model
    role of the reference's OpenNLP tagger)."""

    def __init__(self, weights: Optional[Dict[str, Dict[str, float]]] = None,
                 classes: Optional[List[str]] = None,
                 gazetteer: Optional[Dict[str, List[str]]] = None):
        self.weights: Dict[str, Dict[str, float]] = weights or {}
        self.classes: List[str] = classes or []
        self.gazetteer = {k: set(v) for k, v in (gazetteer or {}).items()}

    # -- inference ---------------------------------------------------------
    def _score(self, feats: Iterable[str]) -> Dict[str, float]:
        scores = {c: 0.0 for c in self.classes}
        for f in feats:
            w = self.weights.get(f)
            if w:
                for c, v in w.items():
                    scores[c] += v
        return scores

    def predict_tokens(self, tokens: Sequence[str]) -> List[str]:
        out = []
        for i in range(len(tokens)):
            feats = token_features(tokens, i, self.gazetteer)
            scores = self._score(feats)
            best = max(scores, key=scores.get) if scores else OUTSIDE
            # unseen feature patterns score ~0 for every class: that is
            # "no evidence", not a coin-flip entity — predict outside
            if best != OUTSIDE and scores[best] <= 0.0:
                best = OUTSIDE
            out.append(best)
        return out

    # -- training ----------------------------------------------------------
    @classmethod
    def train(cls, sentences: Sequence[Sequence[Tuple[str, str]]],
              gazetteer: Optional[Dict[str, set]] = None,
              epochs: int = 8, seed: int = 0) -> "PerceptronNerTagger":
        """sentences: [(token, label)] with label OUTSIDE for plain words."""
        import numpy as np

        classes = sorted({lab for s in sentences for _, lab in s})
        gaz = {k: set(v) for k, v in (gazetteer or {}).items()}
        w: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        totals: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        stamps: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int))
        rng = np.random.default_rng(seed)
        order = np.arange(len(sentences))
        t = 0

        def upd(feat: str, cl: str, delta: float) -> None:
            totals[feat][cl] += (t - stamps[feat][cl]) * w[feat][cl]
            stamps[feat][cl] = t
            w[feat][cl] += delta

        for _ in range(epochs):
            rng.shuffle(order)
            for si in order:
                sent = sentences[si]
                tokens = [tok for tok, _ in sent]
                for i, (tok, gold) in enumerate(sent):
                    t += 1
                    feats = token_features(tokens, i, gaz)
                    scores = {c: 0.0 for c in classes}
                    for f in feats:
                        if f in w:
                            for c, v in w[f].items():
                                scores[c] += v
                    guess = max(scores, key=scores.get)
                    if guess != gold:
                        for f in feats:
                            upd(f, gold, 1.0)
                            upd(f, guess, -1.0)
        # average
        avg: Dict[str, Dict[str, float]] = {}
        for f, per in w.items():
            row = {}
            for c, v in per.items():
                total = totals[f][c] + (t - stamps[f][c]) * v
                a = total / max(t, 1)
                if abs(a) > 1e-9:
                    row[c] = round(a, 6)
            if row:
                avg[f] = row
        return cls(weights=avg, classes=classes,
                   gazetteer={k: sorted(v) for k, v in gaz.items()})

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump({"classes": self.classes, "weights": self.weights,
                       "gazetteer": {k: sorted(v)
                                     for k, v in self.gazetteer.items()}},
                      fh)

    @classmethod
    def load(cls, path: str) -> "PerceptronNerTagger":
        with open(path) as fh:
            d = json.load(fh)
        return cls(weights=d["weights"], classes=d["classes"],
                   gazetteer=d.get("gazetteer", {}))
