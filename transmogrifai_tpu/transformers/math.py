"""Arithmetic feature transformers.

Reference: core/.../impl/feature/MathTransformers.scala (393 LoC) and the
RichNumericFeature dsl operators (core/.../dsl/RichNumericFeature.scala).
Every op is a JaxTransformer — pure array math over the column block, fused
into the layer's single XLA program; empties are NaN and propagate exactly
as the reference's None-propagating semantics.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..stages.base import Estimator, JaxTransformer
from ..stages.params import Param
from ..types import OPNumeric, Real, RealNN

_EPS = 1e-12


class _BinaryMath(JaxTransformer):
    # any numeric subtype is accepted, as in RichNumericFeature's implicits
    input_types = (OPNumeric, OPNumeric)
    output_type = Real

    def __init__(self, uid: Optional[str] = None, **params):
        params.pop("operation_name", None)
        super().__init__(self._op_name, uid=uid, **params)


class AddTransformer(_BinaryMath):
    """x + y (reference BinaryOperationTransformer '+')."""
    _op_name = "plus"

    def get_jax_fn(self):
        return lambda a, b: a + b


class SubtractTransformer(_BinaryMath):
    _op_name = "minus"

    def get_jax_fn(self):
        return lambda a, b: a - b


class MultiplyTransformer(_BinaryMath):
    _op_name = "multiply"

    def get_jax_fn(self):
        return lambda a, b: a * b


class DivideTransformer(_BinaryMath):
    """x / y; division by ~0 yields empty (reference divide semantics)."""
    _op_name = "divide"

    def get_jax_fn(self):
        def fn(a, b):
            tiny = jnp.abs(b) < _EPS
            # guard the denominator so the eager numpy path (row-level
            # transform_value) cannot emit divide-by-zero warnings
            out = a / jnp.where(tiny, 1.0, b)
            return jnp.where(tiny, jnp.nan, out)
        return fn


class _ScalarMath(JaxTransformer):
    input_types = (OPNumeric,)
    output_type = Real

    @classmethod
    def _declare_params(cls):
        return [Param("scalar", "scalar operand", 0.0)]

    def __init__(self, scalar: float = 0.0, uid: Optional[str] = None,
                 **params):
        params.setdefault("scalar", scalar)
        params.pop("operation_name", None)
        super().__init__(self._op_name, uid=uid, **params)


class ScalarAddTransformer(_ScalarMath):
    _op_name = "plusS"

    def get_jax_fn(self):
        s = float(self.get_param("scalar"))
        return lambda a: a + s


class ScalarSubtractTransformer(_ScalarMath):
    _op_name = "minusS"

    def get_jax_fn(self):
        s = float(self.get_param("scalar"))
        return lambda a: a - s


class ScalarMultiplyTransformer(_ScalarMath):
    _op_name = "multiplyS"

    def get_jax_fn(self):
        s = float(self.get_param("scalar"))
        return lambda a: a * s


class ScalarDivideTransformer(_ScalarMath):
    _op_name = "divideS"

    def get_jax_fn(self):
        s = float(self.get_param("scalar"))
        return (lambda a: a / s) if abs(s) > _EPS else (
            lambda a: jnp.full_like(a, jnp.nan))


class _UnaryMath(JaxTransformer):
    input_types = (OPNumeric,)
    output_type = Real

    def __init__(self, uid: Optional[str] = None, **params):
        params.pop("operation_name", None)
        super().__init__(self._op_name, uid=uid, **params)


class AbsTransformer(_UnaryMath):
    _op_name = "abs"

    def get_jax_fn(self):
        return jnp.abs


class CeilTransformer(_UnaryMath):
    _op_name = "ceil"

    def get_jax_fn(self):
        return jnp.ceil


class FloorTransformer(_UnaryMath):
    _op_name = "floor"

    def get_jax_fn(self):
        return jnp.floor


class RoundTransformer(_UnaryMath):
    """Round half away from zero (reference RoundTransformer)."""
    _op_name = "round"

    def get_jax_fn(self):
        return lambda a: jnp.sign(a) * jnp.floor(jnp.abs(a) + 0.5)


class ExpTransformer(_UnaryMath):
    _op_name = "exp"

    def get_jax_fn(self):
        return jnp.exp

class SqrtTransformer(_UnaryMath):
    """sqrt; negative input yields empty."""
    _op_name = "sqrt"

    def get_jax_fn(self):
        return lambda a: jnp.where(a < 0, jnp.nan, jnp.sqrt(jnp.maximum(a, 0)))


class LogTransformer(_UnaryMath):
    """log base b; non-positive input yields empty (reference LogTransformer)."""
    _op_name = "log"

    @classmethod
    def _declare_params(cls):
        return [Param("base", "logarithm base", float(np.e))]

    def __init__(self, base: float = float(np.e), uid: Optional[str] = None,
                 **params):
        params.setdefault("base", base)
        super().__init__(uid=uid, **params)

    def get_jax_fn(self):
        lb = float(np.log(self.get_param("base")))
        return lambda a: jnp.where(a > 0, jnp.log(jnp.maximum(a, _EPS)) / lb,
                                   jnp.nan)


class PowerTransformer(_UnaryMath):
    _op_name = "power"

    @classmethod
    def _declare_params(cls):
        return [Param("exponent", "power", 1.0)]

    def __init__(self, exponent: float = 1.0, uid: Optional[str] = None,
                 **params):
        params.setdefault("exponent", exponent)
        super().__init__(uid=uid, **params)

    def get_jax_fn(self):
        p = float(self.get_param("exponent"))
        return lambda a: jnp.power(a, p)


class ZNormalizeEstimator(Estimator):
    """Real -> RealNN z-score (reference RichNumericFeature.zNormalize
    via OpScalarStandardScaler): fit mean/std over the present values,
    transform to (x - mean) / std with NaN -> 0 after scaling (the
    centered empty value)."""

    input_types = (Real,)
    output_type = RealNN

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "zNormalize"),
                         uid=uid, **params)

    def fit_columns(self, *cols):
        x = np.asarray(cols[0].data, np.float64)
        ok = np.isfinite(x)
        mean = float(x[ok].mean()) if ok.any() else 0.0
        # sample std (ddof=1), matching Spark StandardScaler's estimator
        # semantics the reference wraps; a single present value has no
        # spread -> unit scale
        std = float(x[ok].std(ddof=1)) if ok.sum() > 1 else 1.0
        return ZNormalizeModel(mean=mean, std=max(std, _EPS),
                               operation_name=self.operation_name)


class ZNormalizeModel(JaxTransformer):
    input_types = (Real,)
    output_type = RealNN

    def __init__(self, mean: float = 0.0, std: float = 1.0,
                 operation_name: str = "zNormalize",
                 uid: Optional[str] = None, **params):
        self.mean = float(mean)
        self.std = float(std)
        super().__init__(operation_name, uid=uid, **params)

    def get_jax_fn(self):
        m, s = self.mean, self.std
        return lambda a: jnp.nan_to_num((a - m) / s, nan=0.0)

    def save_args(self):
        d = super().save_args()
        d.update(mean=self.mean, std=self.std)
        return d
