"""Name-entity recognition stage.

Reference: core/.../impl/feature/NameEntityRecognizer.scala backed by
core/.../utils/text/{OpenNLPAnalyzer, OpenNLPNameEntityTagger,
OpenNLPSentenceSplitter}.scala — OpenNLP statistical taggers producing a
MultiPickListMap of token -> entity-type sets.

The JVM model files cannot (and should not) be reproduced here; this stage
keeps the same output contract with a deterministic host-side
regex + gazetteer + orthography tagger: DATE/TIME/MONEY/PERCENTAGE via
pattern rules, LOCATION via a country/major-city gazetteer, ORGANIZATION
via corporate suffixes, PERSON via honorifics and capitalized-sequence
heuristics. Swappable: pass `extra_gazetteers` to extend entity lexicons.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Set

from ..stages.base import Transformer
from ..stages.params import Param
from ..types import MultiPickListMap, Text

# -- lexicons ---------------------------------------------------------------

_COUNTRIES = {
    "afghanistan", "argentina", "australia", "austria", "bangladesh",
    "belgium", "brazil", "canada", "chile", "china", "colombia", "cuba",
    "denmark", "egypt", "england", "ethiopia", "finland", "france",
    "germany", "ghana", "greece", "india", "indonesia", "iran", "iraq",
    "ireland", "israel", "italy", "jamaica", "japan", "kenya", "korea",
    "mexico", "morocco", "nepal", "netherlands", "nigeria", "norway",
    "pakistan", "peru", "philippines", "poland", "portugal", "romania",
    "russia", "scotland", "singapore", "spain", "sweden", "switzerland",
    "taiwan", "thailand", "turkey", "uganda", "ukraine", "usa", "venezuela",
    "vietnam", "wales", "zimbabwe",
}
_CITIES = {
    "amsterdam", "athens", "atlanta", "austin", "bangkok", "barcelona",
    "beijing", "berlin", "boston", "cairo", "chicago", "dallas", "delhi",
    "denver", "dubai", "dublin", "geneva", "houston", "istanbul", "jakarta",
    "karachi", "lagos", "lima", "lisbon", "london", "madrid", "manila",
    "melbourne", "miami", "moscow", "mumbai", "munich", "nairobi", "osaka",
    "oslo", "paris", "prague", "rome", "santiago", "seattle", "seoul",
    "shanghai", "singapore", "stockholm", "sydney", "taipei", "tokyo",
    "toronto", "vienna", "warsaw", "zurich",
}
_ORG_SUFFIXES = {
    "inc", "corp", "ltd", "llc", "plc", "gmbh", "co", "company",
    "corporation", "incorporated", "limited", "group", "holdings",
    "partners", "ventures", "labs", "bank", "university", "institute",
}
_HONORIFICS = {"mr", "mrs", "ms", "miss", "dr", "prof", "sir", "madam",
               "president", "senator", "judge", "captain"}
_COMMON_FIRST_NAMES = {
    "james", "john", "robert", "michael", "william", "david", "richard",
    "joseph", "thomas", "charles", "mary", "patricia", "jennifer", "linda",
    "elizabeth", "barbara", "susan", "jessica", "sarah", "karen", "nancy",
    "maria", "ana", "juan", "carlos", "jose", "luis", "wei", "li", "chen",
    "yuki", "hiroshi", "ahmed", "fatima", "mohammed", "aisha", "olga",
    "ivan", "pierre", "marie", "hans", "greta", "paolo", "giulia",
}

_DATE_RE = re.compile(
    r"^(\d{1,4}[-/]\d{1,2}[-/]\d{1,4}"
    # month names must match exactly (full or 3-letter form): the old
    # open-ended (mar)[a-z]* tail tagged words like 'Maria' as Date
    r"|(january|february|march|april|may|june|july|august|september"
    r"|october|november|december"
    r"|jan|feb|mar|apr|jun|jul|aug|sep|sept|oct|nov|dec)\.?,?"
    r"|\d{4}|\d{1,2}(st|nd|rd|th))$", re.IGNORECASE)
_TIME_RE = re.compile(r"^\d{1,2}:\d{2}(:\d{2})?(am|pm)?$|^\d{1,2}(am|pm)$",
                      re.IGNORECASE)
_MONEY_RE = re.compile(r"^[$€£¥]\d[\d,.]*[kmb]?$|^\d[\d,.]*[$€£¥]$")
_PERCENT_RE = re.compile(r"^\d[\d,.]*%$")
_WORD_SPLIT_RE = re.compile(r"[^\w$€£¥%:/,.'-]+", re.UNICODE)
_CAP_RE = re.compile(r"^[A-Z][a-z'-]+$")


# built once at import: per-row tagging must not re-union the gazetteers
_BASE_LEXICON: Dict[str, Set[str]] = {
    "Location": _COUNTRIES | _CITIES,
    "Organization": set(),
    "Person": set(),
}


def merge_lexicon(extra: Optional[Dict[str, Set[str]]]
                  ) -> Dict[str, Set[str]]:
    """Base gazetteers + user-supplied entity lexicons (lowercased)."""
    if not extra:
        return _BASE_LEXICON
    lex = {ent: set(words) for ent, words in _BASE_LEXICON.items()}
    for ent, words in extra.items():
        lex.setdefault(ent, set())
        lex[ent] |= {w.lower() for w in words}
    return lex


def tag_tokens(text: Optional[str],
               extra: Optional[Dict[str, Set[str]]] = None,
               lexicon: Optional[Dict[str, Set[str]]] = None,
               tagger=None) -> Dict[str, List[str]]:
    """Tag a sentence: token -> sorted entity-type list (one entry per
    distinct tagged token, matching the reference tagger's token->set map).
    Callers tagging many rows should pass a prebuilt `lexicon`
    (merge_lexicon(extra)) so gazetteers merge once, not per row.

    With a trained `tagger` (ner_model.PerceptronNerTagger — the
    OpenNLP-model slot), Person/Organization/Location come from the model
    while the numeric entity classes (Date/Time/Money/Percentage) stay on
    the deterministic regexes, mirroring the reference's split between
    statistical and rule-based tagging."""
    if not text:
        return {}
    lex = lexicon if lexicon is not None else merge_lexicon(extra)
    raw = [t.strip(".,") for t in _WORD_SPLIT_RE.split(text)]
    raw = [t for t in raw if t]
    tags: Dict[str, Set[str]] = {}

    def add(tok: str, ent: str) -> None:
        tags.setdefault(tok, set()).add(ent)

    if tagger is not None:
        from .ner_model import OUTSIDE
        for tok, lab in zip(raw, tagger.predict_tokens(raw)):
            # numeric-shaped tokens belong to the regex classes below; the
            # statistical tagger only owns Person/Organization/Location
            if lab != OUTSIDE and not any(c.isdigit() for c in tok):
                add(tok, lab)
            if _DATE_RE.match(tok):
                add(tok, "Date")
            if _TIME_RE.match(tok):
                add(tok, "Time")
            if _MONEY_RE.match(tok):
                add(tok, "Money")
            if _PERCENT_RE.match(tok):
                add(tok, "Percentage")
        return {tok: sorted(ents) for tok, ents in tags.items()}

    for i, tok in enumerate(raw):
        low = tok.lower()
        if _DATE_RE.match(tok):
            add(tok, "Date")
        if _TIME_RE.match(tok):
            add(tok, "Time")
        if _MONEY_RE.match(tok):
            add(tok, "Money")
        if _PERCENT_RE.match(tok):
            add(tok, "Percentage")
        for ent, words in lex.items():
            if low in words:
                add(tok, ent)
        if low in _ORG_SUFFIXES and i > 0 and _CAP_RE.match(raw[i - 1]):
            # "Acme Corp" -> both tokens Organization
            add(raw[i - 1], "Organization")
            add(tok, "Organization")
        is_cap = bool(_CAP_RE.match(tok))
        prev_low = raw[i - 1].lower() if i > 0 else ""
        if is_cap and (low in _COMMON_FIRST_NAMES
                       or prev_low in _HONORIFICS):
            add(tok, "Person")
            # capitalized successor of a tagged first/honorific name is the
            # surname ("Dr Smith", "Maria Garcia")
            if i + 1 < len(raw) and _CAP_RE.match(raw[i + 1]):
                add(raw[i + 1], "Person")

    return {tok: sorted(ents) for tok, ents in tags.items()}


class NameEntityRecognizer(Transformer):
    """Text -> MultiPickListMap of token -> entity types (reference
    NameEntityRecognizer.scala output contract)."""

    input_types = (Text,)
    output_type = MultiPickListMap

    @classmethod
    def _declare_params(cls):
        return [Param("extra_gazetteers",
                      "entity -> extra lexicon words", None),
                Param("model_path", "trained PerceptronNerTagger JSON "
                      "(OpenNLP-model slot); None = heuristic tagger", None)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "ner"), uid=uid,
                         **params)
        self._lexicon: Optional[Dict[str, Set[str]]] = None
        self._tagger = None
        self._tagger_loaded = False

    def _lex(self) -> Dict[str, Set[str]]:
        if self._lexicon is None:
            extra = self.get_param("extra_gazetteers")
            self._lexicon = merge_lexicon(
                {k: set(v) for k, v in extra.items()} if extra else None)
        return self._lexicon

    def _model(self):
        if not self._tagger_loaded:
            self._tagger_loaded = True
            path = self.get_param("model_path")
            if path:
                from .ner_model import PerceptronNerTagger
                self._tagger = PerceptronNerTagger.load(path)
        return self._tagger

    def transform_value(self, *vals):
        return MultiPickListMap(tag_tokens(vals[0].value,
                                           lexicon=self._lex(),
                                           tagger=self._model()))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        extra = self.get_param("extra_gazetteers")
        d.update(extra_gazetteers={k: sorted(v) for k, v in extra.items()}
                 if extra else None,
                 model_path=self.get_param("model_path"))
        return d
