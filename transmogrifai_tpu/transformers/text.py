"""Text-processing transformers.

Reference: core/.../impl/feature/{TextTokenizer(196), NGramSimilarity,
JaccardSimilarity, OpCountVectorizer, TextLenTransformer, SubstringTransformer,
OpStringIndexer, OpIndexToString, LangDetector, MimeTypeDetector,
PhoneNumberParser(566)}.scala + utils/.../text analyzers.

Host/device split (SURVEY hard-parts): tokenization/parsing stays host-side
(strings never reach the device); everything downstream emits fixed-width
numeric columns. The reference leaned on Lucene/Optimaize/Tika/libphonenumber
(all JVM); these are self-contained re-implementations of the behaviors the
AutoML pipeline actually consumes — analyzers are pluggable the same way the
reference's TextAnalyzer interface is.
"""
from __future__ import annotations

import base64 as b64mod
import json
import math
import re
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import Column, column_from_values
from ..stages.base import Estimator, Transformer
from ..stages.params import Param
from ..types import (
    Binary, ColumnKind, Integral, MultiPickList, OPVector, PickList, Real,
    RealNN, Text, TextList,
)

# token = maximal run of unicode alphanumerics or apostrophes (underscore is
# a separator). For pure-ASCII text this is exactly the C++ fused tokenizer's
# [A-Za-z0-9'] rule (native/hashing.cpp:104), so the native fast path can be
# used whenever the input is ASCII; non-ASCII text keeps unicode tokens like
# Lucene's (unicode-aware) standard analyzer instead of mangling them.
_TOKEN_RE = re.compile(r"(?:[^\W_]|')+", re.UNICODE)
_STOPWORDS = {
    "a", "an", "and", "are", "as", "at", "be", "but", "by", "for", "if",
    "in", "into", "is", "it", "no", "not", "of", "on", "or", "such", "that",
    "the", "their", "then", "there", "these", "they", "this", "to", "was",
    "will", "with",
}


def tokenize_text(value: Optional[str], min_token_length: int = 1,
                  to_lowercase: bool = True,
                  filter_stopwords: bool = False) -> List[str]:
    """The default analyzer (reference TextTokenizer.Analyzer / Lucene
    standard analyzer behavior)."""
    if not value:
        return []
    s = value.lower() if to_lowercase else value
    toks = [t for t in _TOKEN_RE.findall(s) if len(t) >= min_token_length]
    if filter_stopwords:
        toks = [t for t in toks if t not in _STOPWORDS]
    return toks


class TextTokenizer(Transformer):
    """Text -> TextList (reference TextTokenizer.scala:196)."""

    input_types = (Text,)
    output_type = TextList

    @classmethod
    def _declare_params(cls):
        return [Param("min_token_length", "min token length", 1),
                Param("to_lowercase", "lowercase before split", True),
                Param("filter_stopwords", "drop english stopwords", False)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "tokenize"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        return TextList(tokenize_text(
            vals[0].value, int(self.get_param("min_token_length")),
            bool(self.get_param("to_lowercase")),
            bool(self.get_param("filter_stopwords"))))


class RegexTokenizer(Transformer):
    """Text -> TextList by a custom token pattern (reference
    RichTextFeature.tokenizeRegex — Lucene pattern analyzer)."""

    input_types = (Text,)
    output_type = TextList

    @classmethod
    def _declare_params(cls):
        return [Param("pattern", "regex matching TOKENS", r"\w+"),
                Param("to_lowercase", "lowercase before match", True),
                Param("min_token_length", "min token length", 1)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "tokenizeRegex"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        if not v:
            return TextList([])
        if bool(self.get_param("to_lowercase")):
            v = v.lower()
        ml = int(self.get_param("min_token_length"))
        # finditer + group(0): findall would return group captures (or
        # tuples) for patterns containing groups, corrupting the token list
        toks = [m.group(0)
                for m in re.finditer(str(self.get_param("pattern")), v)
                if len(m.group(0)) >= ml]
        return TextList(toks)


class StopWordsRemover(Transformer):
    """TextList -> TextList without english stopwords (reference
    RichListFeature.removeStopWords via Spark StopWordsRemover)."""

    input_types = (TextList,)
    output_type = TextList

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "rmStopWords"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        toks = vals[0].value or []
        return TextList([t for t in toks if t.lower() not in _STOPWORDS])


class NGramTransformer(Transformer):
    """TextList -> TextList of word n-grams joined by spaces (reference
    RichListFeature.ngram via Spark NGram)."""

    input_types = (TextList,)
    output_type = TextList

    @classmethod
    def _declare_params(cls):
        return [Param("n", "gram size", 2)]

    def __init__(self, n: int = 2, uid: Optional[str] = None, **params):
        params.setdefault("n", n)
        super().__init__(params.pop("operation_name", "ngram"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        toks = vals[0].value or []
        n = max(int(self.get_param("n")), 1)
        return TextList([" ".join(toks[i:i + n])
                         for i in range(max(len(toks) - n + 1, 0))])


class TextLenTransformer(Transformer):
    """Text -> Integral length (reference TextLenTransformer); empty -> 0."""

    input_types = (Text,)
    output_type = Integral

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "textLen"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        return Integral(0 if v is None else len(v))


class SubstringTransformer(Transformer):
    """(Text, Text) -> Binary: second contains first (reference
    SubstringTransformer)."""

    input_types = (Text, Text)
    output_type = Binary

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "substring"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        sub, s = vals[0].value, vals[1].value
        if sub is None or s is None:
            return Binary(None)
        return Binary(sub.lower() in s.lower())


def _ngrams(tokens: Sequence[str], n: int) -> Counter:
    joined = " ".join(tokens)
    return Counter(joined[i:i + n] for i in range(max(len(joined) - n + 1, 0)))


class NGramSimilarity(Transformer):
    """(TextList, TextList) -> RealNN cosine similarity over char n-grams
    (reference NGramSimilarity.scala, Lucene NGramDistance)."""

    input_types = (TextList, TextList)
    output_type = RealNN

    @classmethod
    def _declare_params(cls):
        return [Param("n", "gram size", 3)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "nGramSimilarity"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        a, b = vals[0].value or [], vals[1].value or []
        if not a or not b:
            return RealNN(0.0)
        n = int(self.get_param("n"))
        ca, cb = _ngrams(a, n), _ngrams(b, n)
        dot = sum(ca[g] * cb[g] for g in ca.keys() & cb.keys())
        na = math.sqrt(sum(v * v for v in ca.values()))
        nb = math.sqrt(sum(v * v for v in cb.values()))
        return RealNN(dot / (na * nb) if na and nb else 0.0)


class JaccardSimilarity(Transformer):
    """(MultiPickList, MultiPickList) -> RealNN (reference
    JaccardSimilarity.scala); both empty -> 1.0."""

    input_types = (MultiPickList, MultiPickList)
    output_type = RealNN

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "jaccardSimilarity"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        a = set(vals[0].value or ())
        b = set(vals[1].value or ())
        if not a and not b:
            return RealNN(1.0)
        union = len(a | b)
        return RealNN(len(a & b) / union if union else 0.0)


class OpStringIndexer(Estimator):
    """Text -> RealNN frequency-rank index (reference OpStringIndexer;
    unseen/null handled per handle_invalid like StringIndexer)."""

    input_types = (Text,)
    output_type = RealNN

    @classmethod
    def _declare_params(cls):
        return [Param("handle_invalid", "error|skip|keep", "keep")]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "stringIndexer"),
                         uid=uid, **params)

    def fit_columns(self, *cols: Column) -> Transformer:
        counts = Counter(v for v in cols[0].data
                         if v is not None and v != "")
        labels = [w for w, _ in counts.most_common()]
        return OpStringIndexerModel(
            labels=labels,
            handle_invalid=str(self.get_param("handle_invalid")),
            operation_name=self.operation_name)


class OpStringIndexerModel(Transformer):
    input_types = (Text,)
    output_type = RealNN

    def __init__(self, labels: Optional[Sequence[str]] = None,
                 handle_invalid: str = "keep",
                 uid: Optional[str] = None, **params):
        self.labels = list(labels or [])
        self.handle_invalid = handle_invalid
        self._index = {w: i for i, w in enumerate(self.labels)}
        super().__init__(params.pop("operation_name", "stringIndexer"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        idx = self._index.get(v)
        if idx is None:
            if self.handle_invalid == "error":
                raise ValueError(f"Unseen label: {v!r}")
            idx = len(self.labels)  # keep: unseen bucket
        return RealNN(float(idx))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(labels=self.labels, handle_invalid=self.handle_invalid)
        return d


class OpIndexToString(Transformer):
    """RealNN index -> Text label (reference OpIndexToString)."""

    input_types = (RealNN,)
    output_type = Text

    def __init__(self, labels: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None, **params):
        self.labels = list(labels or [])
        super().__init__(params.pop("operation_name", "indexToString"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        i = int(vals[0].value)
        return Text(self.labels[i] if 0 <= i < len(self.labels) else None)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(labels=self.labels)
        return d


class OpCountVectorizer(Estimator):
    """TextList -> OPVector of top-K vocabulary counts (reference
    OpCountVectorizer wrapping Spark CountVectorizer)."""

    input_types = (TextList,)
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        return [Param("vocab_size", "max vocabulary", 512),
                Param("min_df", "min docs containing term", 1),
                Param("binary", "0/1 instead of counts", False)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "countVec"),
                         uid=uid, **params)

    def _vocab(self, col: Column) -> List[str]:
        df: Counter = Counter()
        for toks in col.data:
            if toks:
                df.update(set(toks))
        min_df = int(self.get_param("min_df"))
        vocab = [w for w, c in df.most_common() if c >= min_df]
        return vocab[: int(self.get_param("vocab_size"))]

    def fit_columns(self, *cols: Column) -> Transformer:
        return OpCountVectorizerModel(
            vocab=self._vocab(cols[0]),
            binary=bool(self.get_param("binary")),
            operation_name=self.operation_name)


class OpCountVectorizerModel(Transformer):
    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, vocab: Optional[Sequence[str]] = None,
                 binary: bool = False, idf: Optional[np.ndarray] = None,
                 uid: Optional[str] = None, **params):
        self.vocab = list(vocab or [])
        self.binary = bool(binary)
        self.idf = None if idf is None else np.asarray(idf, np.float64)
        self._index = {w: i for i, w in enumerate(self.vocab)}
        super().__init__(params.pop("operation_name", "countVec"),
                         uid=uid, **params)

    def _encode(self, toks) -> np.ndarray:
        out = np.zeros(len(self.vocab), np.float32)
        for t in toks or []:
            i = self._index.get(t)
            if i is not None:
                out[i] += 1.0
        if self.binary:
            out = (out > 0).astype(np.float32)
        if self.idf is not None:
            out = out * self.idf
        return out

    def transform_value(self, *vals):
        return OPVector(self._encode(vals[0].value))

    def transform_columns(self, *cols: Column) -> Column:
        # columnar path: one pass over the token lists + vocab metadata
        # (reference CountVectorizer publishes its vocabulary as vector
        # metadata; ModelInsights reads term provenance from it)
        X = np.stack([self._encode(toks) for toks in cols[0].data]) \
            if len(cols[0]) else np.zeros((0, len(self.vocab)), np.float32)
        return Column(kind=ColumnKind.VECTOR, data=X,
                      metadata=self.output_metadata())

    def output_metadata(self) -> Optional["VectorMetadata"]:
        from ..data.vector import VectorColumnMetadata, VectorMetadata
        parent = (self.input_features[0].name
                  if self.input_features else "text")
        ptype = (self.input_features[0].type_name
                 if self.input_features else "TextList")
        return VectorMetadata(
            name=self.output_name(),
            columns=[VectorColumnMetadata(
                parent_feature_name=parent, parent_feature_type=ptype,
                indicator_value=term) for term in self.vocab])

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(vocab=self.vocab, binary=self.binary,
                 idf=self.idf if self.idf is not None else None)
        return d


class TfIdfVectorizer(OpCountVectorizer):
    """TextList -> OPVector TF-IDF (reference `idf` dsl on tokenized text
    wrapping Spark IDF)."""

    def __init__(self, uid: Optional[str] = None, **params):
        Estimator.__init__(self, "tfidf", uid=uid, **params)

    def fit_columns(self, *cols: Column) -> Transformer:
        vocab = self._vocab(cols[0])
        index = {w: i for i, w in enumerate(vocab)}
        n_docs = len(cols[0])
        df = np.zeros(len(vocab), np.float64)
        for toks in cols[0].data:
            for w in set(toks or []):
                i = index.get(w)
                if i is not None:
                    df[i] += 1.0
        idf = np.log((n_docs + 1.0) / (df + 1.0))
        return OpCountVectorizerModel(vocab=vocab, idf=idf,
                                      operation_name=self.operation_name)


# -- light analyzers (reference leaned on JVM libs; behavior-parity impls) --

# Script ranges decide non-Latin languages outright (deterministic — these
# scripts map 1:1 or nearly so to a language for detection purposes).
_SCRIPT_LANGS: List[Tuple[int, int, str]] = [
    (0x0400, 0x04FF, "ru"),   # Cyrillic (uk split off below)
    (0x0370, 0x03FF, "el"),   # Greek
    (0x0590, 0x05FF, "he"),   # Hebrew
    (0x0600, 0x06FF, "ar"),   # Arabic (fa split off below)
    (0x0900, 0x097F, "hi"),   # Devanagari
    (0x0980, 0x09FF, "bn"),   # Bengali
    (0x0B80, 0x0BFF, "ta"),   # Tamil
    (0x0C00, 0x0C7F, "te"),   # Telugu
    (0x0E00, 0x0E7F, "th"),   # Thai
    (0x10A0, 0x10FF, "ka"),   # Georgian
    (0x0530, 0x058F, "hy"),   # Armenian
    (0x1100, 0x11FF, "ko"),   # Hangul Jamo
    (0xAC00, 0xD7AF, "ko"),   # Hangul syllables
    (0x3040, 0x309F, "ja"),   # Hiragana
    (0x30A0, 0x30FF, "ja"),   # Katakana
    (0x4E00, 0x9FFF, "zh"),   # CJK unified (ja wins if kana present)
]

# Latin-script profiles: top stopwords + characteristic trigrams +
# diacritics distinctive of the language (the same n-gram-profile family
# as Optimaize's detector, hand-compacted). Stopword hit = 2, trigram = 1,
# diacritic = 3 (rarely shared between these languages).
_LATIN_PROFILES: Dict[str, Tuple[set, set, str]] = {
    "en": (set("the and of to in is you that it he was for are with".split()),
           {"the", "ing", "and", "ion", "ent"}, ""),
    "de": (set("der die das und ist ein nicht mit sich den auf werden"
               .split()),
           {"der", "ein", "ich", "sch", "und"}, "äöüß"),
    "fr": (set("le la les de et un une est que dans pour qui pas vous"
               .split()),
           {"les", "des", "ent", "que", "ait"}, "àâçéèêëîïôùûœ"),
    "es": (set("el la los las de y un una es que en por con para no"
               .split()),
           {"que", "ión", "los", "ado", "nte"}, "áéíóúñ¿¡"),
    "pt": (set("o a os as de e um uma é que em não com para mais".split()),
           {"que", "ção", "não", "ado", "com"}, "ãõáâêéíóôúç"),
    "it": (set("il lo la i gli le di e un una è che in per non".split()),
           {"che", "ion", "lla", "ato", "gli"}, "àèéìòù"),
    "nl": (set("de het een en van ik dat niet met op zijn voor".split()),
           {"een", "van", "het", "ijk", "aar"}, "ĳ"),
    "sv": (set("och att det som en på är av för med den inte".split()),
           {"och", "att", "för", "ing", "den"}, "åäö"),
    "da": (set("og at det som en på er af for med den ikke".split()),
           {"det", "og", "ikke", "der", "til"}, "æøå"),
    "no": (set("og i det som en på er av for med den ikke å".split()),
           {"det", "og", "ikke", "som", "til"}, "æøå"),
    "fi": (set("ja on ei se että en hän oli mutta kun".split()),
           {"en ", "in ", "ssa", "lla", "sta"}, "äö"),
    "pl": (set("i w nie na się z do to że jest jak po".split()),
           {"nie", "się", "rze", "ych", "ego"}, "ąćęłńóśźż"),
    "cs": (set("a je se v na to že s z do o ale".split()),
           {"je", "na", "pro", "ost", "ter"}, "áčďéěíňóřšťúůýž"),
    "ro": (set("și de la a în cu o pe un este nu ce".split()),
           {"ul ", "în ", "are", "eșt", "lui"}, "ăâîșț"),
    "tr": (set("ve bir bu da ne için de ile çok ama ben".split()),
           {"bir", "lar", "ler", "içi", "dır"}, "çğıöşü"),
    "hu": (set("a az és hogy nem is egy van ez meg".split()),
           {"egy", "nek", "ban", "ogy", "tal"}, "áéíóöőúüű"),
    "id": (set("yang dan di itu dengan untuk tidak ini dari ke".split()),
           {"ang", "men", "kan", "nya", "ber"}, ""),
    "vi": (set("là và của có không được cho người trong một".split()),
           {"ng ", "nh ", "anh", "ông", "ười"},
           "ăâđêôơưáàảãạếềểễệ"),
}


def build_language_profiles(samples: Dict[str, str], top_tokens: int = 24,
                            top_trigrams: int = 10) -> Dict[str, Any]:
    """Train Latin-script language profiles from sample text — the
    Optimaize-profile-building role. Returns the JSON structure
    `LangDetector(model_path=...)` loads: per language, the most frequent
    tokens (stopword slot), most frequent letter trigrams, and observed
    non-ASCII marks."""
    out: Dict[str, Any] = {}
    for lang, text in samples.items():
        low = text.lower()
        toks = Counter(t for t in tokenize_text(low) if len(t) <= 6)
        grams = Counter(low[i:i + 3] for i in range(max(len(low) - 2, 0))
                        if low[i:i + 3].isalpha())
        marks = "".join(sorted({c for c in low if ord(c) > 0x7f}))
        out[lang] = {
            "stopwords": [w for w, _ in toks.most_common(top_tokens)],
            "trigrams": [g for g, _ in grams.most_common(top_trigrams)],
            "marks": marks,
        }
    return out


def load_language_profiles(path: str) -> Dict[str, Tuple[set, set, str]]:
    """JSON profile file -> the _LATIN_PROFILES runtime shape. Loaded
    profiles EXTEND the builtin table (same-language entries override)."""
    with open(path) as fh:
        raw = json.load(fh)
    return {lang: (set(p.get("stopwords", ())),
                   set(p.get("trigrams", ())),
                   str(p.get("marks", "")))
            for lang, p in raw.items()}


def detect_language(text: str,
                    extra_profiles: Optional[
                        Dict[str, Tuple[set, set, str]]] = None
                    ) -> Optional[str]:
    """Best-effort language code for a document: script ranges decide
    non-Latin languages; Latin scripts score stopword/trigram/diacritic
    profiles over ~18 languages (reference LangDetector wraps Optimaize's
    n-gram profiles — same algorithm family, hand-compacted tables;
    `extra_profiles` adds trained ones, see build_language_profiles)."""
    if not text:
        return None
    # script pass
    script_counts: Dict[str, int] = {}
    kana = False
    for ch in text[:512]:
        cp = ord(ch)
        if cp < 0x80:
            continue
        if 0x3040 <= cp <= 0x30FF:
            kana = True
        for lo, hi, lang in _SCRIPT_LANGS:
            if lo <= cp <= hi:
                script_counts[lang] = script_counts.get(lang, 0) + 1
                break
    if script_counts:
        lang = max(script_counts, key=script_counts.get)
        if lang == "zh" and kana:
            return "ja"
        head = text[:512]
        if lang == "ru" and any(c in head for c in "іїєґ"):
            return "uk"  # letters absent from Russian orthography
        if lang == "ar" and any(c in head for c in "\u067e\u0686\u0698\u06af"):
            return "fa"  # pe/che/zhe/gaf: Persian additions to Arabic script
        return lang
    # latin pass (capped like the script pass: multi-KB documents gain no
    # accuracy from scanning past the first 512 chars)
    low = text[:512].lower()
    toks = set(tokenize_text(low))
    grams = {low[i:i + 3] for i in range(max(len(low) - 2, 0))}
    profiles = _LATIN_PROFILES if not extra_profiles \
        else {**_LATIN_PROFILES, **extra_profiles}
    best, score = None, 0
    for lang, (stops, tris, marks) in profiles.items():
        s = 2 * len(toks & stops) + len(grams & tris)
        s += 3 * sum(1 for m in marks if m in low)
        if s > score:
            best, score = lang, s
    return best or "unknown"


class LangDetector(Transformer):
    """Text -> PickList language code over ~30 languages: deterministic
    script detection (Cyrillic/Greek/Hebrew/Arabic/CJK/Hangul/Thai/indic/
    ...) + stopword/trigram/diacritic profiles for 18 Latin-script
    languages (reference LangDetector via Optimaize's n-gram profiles)."""

    input_types = (Text,)
    output_type = PickList

    @classmethod
    def _declare_params(cls):
        return [Param("model_path", "JSON language-profile file "
                      "(build_language_profiles output) extending the "
                      "builtin table; None = builtin only", None)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "langDetect"),
                         uid=uid, **params)
        self._profiles: Optional[Dict[str, Tuple[set, set, str]]] = None
        self._profiles_loaded = False

    def _extra_profiles(self):
        if not self._profiles_loaded:
            self._profiles_loaded = True
            path = self.get_param("model_path")
            if path:
                self._profiles = load_language_profiles(path)
        return self._profiles

    def transform_value(self, *vals):
        return PickList(detect_language(vals[0].value,
                                        self._extra_profiles()))


_MIME_MAGIC: List[Tuple[bytes, str]] = [
    (b"\x89PNG", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF8", "image/gif"),
    (b"%PDF", "application/pdf"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"<?xml", "application/xml"),
    (b"{", "application/json"),
]


def load_mime_magic(path: str) -> List[Tuple[bytes, str]]:
    """JSON magic-rule file -> prepended detection table (the Tika
    custom-mimetypes.xml role): [{"magic_hex": "424d", "mime":
    "image/bmp"}, ...]. Longest-prefix entries should come first."""
    with open(path) as fh:
        raw = json.load(fh)
    return [(bytes.fromhex(r["magic_hex"]), str(r["mime"])) for r in raw]


def detect_mime(b64_value: Optional[str],
                extra_magic: Optional[List[Tuple[bytes, str]]] = None
                ) -> Optional[str]:
    """MIME type of a base64 payload via magic bytes, or None for
    empty/undecodable input (shared by the scalar and map detectors).
    `extra_magic` rules are checked before the builtin table."""
    if not b64_value:
        return None
    try:
        head = b64mod.b64decode(
            b64_value[:64] + "=" * (-len(b64_value[:64]) % 4))
    except Exception:
        return None
    for magic, mime in (extra_magic or []):
        if head.startswith(magic):
            return mime
    for magic, mime in _MIME_MAGIC:
        if head.startswith(magic):
            return mime
    try:
        head.decode("utf-8")
        return "text/plain"
    except UnicodeDecodeError:
        return "application/octet-stream"


class MimeTypeDetector(Transformer):
    """Base64 -> PickList MIME type via magic bytes (reference
    MimeTypeDetector via Tika; `model_path` loads extra magic rules the
    way Tika loads custom-mimetypes.xml)."""

    input_types = (Text,)   # Base64 is a Text subtype
    output_type = PickList

    @classmethod
    def _declare_params(cls):
        return [Param("model_path", "JSON magic-rule file checked before "
                      "the builtin table; None = builtin only", None)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "mimeDetect"),
                         uid=uid, **params)
        self._magic: Optional[List[Tuple[bytes, str]]] = None
        self._magic_loaded = False

    def _extra_magic(self):
        if not self._magic_loaded:
            self._magic_loaded = True
            path = self.get_param("model_path")
            if path:
                self._magic = load_mime_magic(path)
        return self._magic

    def transform_value(self, *vals):
        return PickList(detect_mime(vals[0].value, self._extra_magic()))


# Per-region phone metadata: (country code, set of valid NATIONAL number
# lengths, trunk prefix stripped from national format). A hand-compacted
# slice of the ITU numbering plans libphonenumber ships in full — covers
# the regions the reference's PhoneNumberParser tests exercise plus the
# majors. NANP members share cc=1 with 10-digit nationals and no trunk 0.
_PHONE_REGIONS: Dict[str, Tuple[int, frozenset, str]] = {
    "US": (1, frozenset({10}), ""), "CA": (1, frozenset({10}), ""),
    "MX": (52, frozenset({10}), ""),
    "GB": (44, frozenset({9, 10}), "0"), "IE": (353, frozenset({7, 8, 9}), "0"),
    "DE": (49, frozenset(range(6, 12)), "0"),
    "FR": (33, frozenset({9}), "0"), "ES": (34, frozenset({9}), ""),
    "IT": (39, frozenset(range(8, 12)), ""),
    "PT": (351, frozenset({9}), ""), "NL": (31, frozenset({9}), "0"),
    "BE": (32, frozenset({8, 9}), "0"), "CH": (41, frozenset({9}), "0"),
    "AT": (43, frozenset(range(7, 14)), "0"),
    "SE": (46, frozenset(range(7, 10)), "0"),
    "NO": (47, frozenset({8}), ""), "DK": (45, frozenset({8}), ""),
    "FI": (358, frozenset(range(6, 12)), "0"),
    "PL": (48, frozenset({9}), ""), "CZ": (420, frozenset({9}), ""),
    "RO": (40, frozenset({9}), "0"), "GR": (30, frozenset({10}), ""),
    "TR": (90, frozenset({10}), "0"), "RU": (7, frozenset({10}), "8"),
    "UA": (380, frozenset({9}), "0"), "IL": (972, frozenset({8, 9}), "0"),
    "SA": (966, frozenset({8, 9}), "0"), "AE": (971, frozenset({8, 9}), "0"),
    "IN": (91, frozenset({10}), "0"), "PK": (92, frozenset({9, 10}), "0"),
    "BD": (880, frozenset({8, 9, 10}), "0"),
    "CN": (86, frozenset({11}), "0"), "JP": (81, frozenset({9, 10}), "0"),
    "KR": (82, frozenset(range(8, 11)), "0"),
    "TW": (886, frozenset({8, 9}), "0"),
    "SG": (65, frozenset({8}), ""), "HK": (852, frozenset({8}), ""),
    "MY": (60, frozenset(range(7, 10)), "0"),
    "TH": (66, frozenset({8, 9}), "0"), "VN": (84, frozenset({9, 10}), "0"),
    "ID": (62, frozenset(range(8, 12)), "0"),
    "PH": (63, frozenset({8, 10}), "0"),
    "AU": (61, frozenset({9}), "0"), "NZ": (64, frozenset(range(8, 10)), "0"),
    "BR": (55, frozenset({10, 11}), "0"), "AR": (54, frozenset({10}), "0"),
    "CL": (56, frozenset({9}), ""), "CO": (57, frozenset({10}), ""),
    "PE": (51, frozenset({9}), "0"),
    "ZA": (27, frozenset({9}), "0"), "NG": (234, frozenset({8, 10}), "0"),
    "EG": (20, frozenset({9, 10}), "0"), "KE": (254, frozenset({9}), "0"),
}

# cc -> candidate regions (longest-prefix match over 1-3 digit codes)
_CC_TO_REGIONS: Dict[int, List[str]] = {}
for _r, (_cc, _lens, _tp) in _PHONE_REGIONS.items():
    _CC_TO_REGIONS.setdefault(_cc, []).append(_r)


def _resolve_phone(raw: str, default_region: str = "US"
                   ) -> Tuple[bool, Optional[str], Optional[str]]:
    """(is_valid, region, e164) — THE phone resolution path (reference
    PhoneNumberParser.scala:566 wraps libphonenumber; this is a compacted
    50-region metadata table with the same decision shape: resolve region
    from +cc or the default, strip trunk prefix, check national length).
    Validity (parse_phone) and normalization (parse_phone_e164) are views
    of this one function so they can never disagree."""
    if not raw:
        return False, None, None
    s = raw.strip()
    digits = re.sub(r"[^\d+]", "", s)
    if digits.count("+") > 1 or ("+" in digits and not digits.startswith("+")):
        return False, None, None
    if digits.startswith("+"):
        body = digits[1:]
        if not body.isdigit():
            return False, None, None
        for cc_len in (3, 2, 1):
            cc = int(body[:cc_len]) if len(body) >= cc_len else -1
            for region in _CC_TO_REGIONS.get(cc, ()):
                _, lens, _trunk = _PHONE_REGIONS[region]
                if len(body) - cc_len in lens:
                    return True, region, "+" + body
        # unknown cc: fall back to the ITU E.164 structural bound
        ok = 8 <= len(body) <= 15
        return ok, None, ("+" + body) if ok else None
    if not digits.isdigit() or not digits:
        return False, None, None
    region = default_region.upper()
    meta = _PHONE_REGIONS.get(region)
    if meta is None:
        # structurally plausible but no metadata to produce a +cc form
        return 7 <= len(digits) <= 15, None, None
    cc, lens, trunk = meta
    national = digits
    cc_str = str(cc)
    # NANP-style: national form may carry the country code (1-555-...)
    if national.startswith(cc_str) and (len(national) - len(cc_str)) in lens:
        national = national[len(cc_str):]
    elif trunk and national.startswith(trunk) and \
            (len(national) - len(trunk)) in lens:
        national = national[len(trunk):]
    ok = len(national) in lens
    return ok, region, f"+{cc}{national}" if ok else None


def parse_phone(raw: str, default_region: str = "US"
                ) -> Tuple[bool, Optional[str]]:
    """(is_valid, region) for a raw phone string — see _resolve_phone."""
    ok, region, _ = _resolve_phone(raw, default_region)
    return ok, region


class PhoneNumberParser(Transformer):
    """Phone -> Binary validity against per-region numbering metadata
    (country code, national length set, trunk prefix) for ~50 regions
    (reference PhoneNumberParser.scala:566 via libphonenumber)."""

    input_types = (Text,)
    output_type = Binary

    @classmethod
    def _declare_params(cls):
        return [Param("default_region", "region for bare numbers", "US"),
                Param("strict", "strict length validation", True)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "phoneValid"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        if not v:
            return Binary(None)
        ok, _region = parse_phone(v, str(self.get_param("default_region")))
        if not ok and not bool(self.get_param("strict")):
            digits = re.sub(r"\D", "", v)
            ok = 7 <= len(digits) <= 15
        return Binary(bool(ok))


def parse_phone_e164(raw: str, default_region: str = "US") -> Optional[str]:
    """Normalized ``+<cc><national>`` form, or None when invalid
    (reference RichPhoneFeature.parsePhone -> libphonenumber E164).
    Same single resolution path as parse_phone (_resolve_phone)."""
    return _resolve_phone(raw, default_region)[2]


class PhoneParser(Transformer):
    """Phone/Text -> normalized E.164 Text, empty when unparseable
    (reference RichPhoneFeature.parsePhone / parsePhoneDefaultCountry)."""

    input_types = (Text,)
    output_type = Text

    @classmethod
    def _declare_params(cls):
        return [Param("default_region", "region for bare numbers", "US")]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "parsePhone"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        if not v:
            return Text(None)
        return Text(parse_phone_e164(v, str(self.get_param("default_region"))))


class OpIDF(Estimator):
    """OPVector -> OPVector rescaled by inverse document frequency
    (reference RichVectorFeature.idf:56 wrapping Spark ml IDF): per column
    j, idf_j = log((m + 1) / (df_j + 1)) with df_j = #rows where x_j > 0;
    columns under min_doc_freq get idf 0 (Spark's semantics). Fit is one
    columnwise reduction over the dense matrix."""

    input_types = (OPVector,)
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        return [Param("min_doc_freq", "df below this zeroes the column", 0)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "idf"), uid=uid,
                         **params)

    def fit_columns(self, *cols: Column) -> Transformer:
        X = np.asarray(cols[0].data, np.float32)
        m = X.shape[0]
        df = (X > 0).sum(axis=0).astype(np.float64)
        idf = np.log((m + 1.0) / (df + 1.0))
        idf[df < int(self.get_param("min_doc_freq"))] = 0.0
        return OpIDFModel(idf=idf, operation_name=self.operation_name)


class OpIDFModel(Transformer):
    input_types = (OPVector,)
    output_type = OPVector

    def __init__(self, idf: Optional[Sequence[float]] = None,
                 uid: Optional[str] = None, **params):
        self.idf = np.asarray([] if idf is None else idf, np.float32)
        super().__init__(params.pop("operation_name", "idf"), uid=uid,
                         **params)

    def transform_columns(self, *cols: Column) -> Column:
        vec = cols[0]
        if not len(self.idf):  # unfitted default: identity
            return vec
        return Column(kind=ColumnKind.VECTOR,
                      data=np.asarray(vec.data, np.float32) * self.idf[None, :],
                      metadata=vec.metadata)

    def transform_value(self, *vals):
        x = np.asarray(vals[0].value, np.float32)
        return OPVector(x * self.idf if len(self.idf) else x)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(idf=[float(v) for v in self.idf])
        return d


class EmailToPickList(Transformer):
    """Email -> PickList of the domain (reference RichEmailFeature
    .toEmailDomain)."""

    input_types = (Text,)
    output_type = PickList

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "emailDomain"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        from ..types import Email
        e = vals[0] if isinstance(vals[0], Email) else Email(vals[0].value)
        return PickList(e.domain())


_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9.!#$%&'*+/=?^_`{|}~-]+@"
    r"[A-Za-z0-9](?:[A-Za-z0-9-]{0,61}[A-Za-z0-9])?"
    r"(?:\.[A-Za-z0-9](?:[A-Za-z0-9-]{0,61}[A-Za-z0-9])?)+\Z")


class ValidEmailTransformer(Transformer):
    """Email -> Binary RFC-shaped validity (reference RichEmailFeature
    .isValidEmail:591 / ValidEmailTransformer)."""

    input_types = (Text,)
    output_type = Binary

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "validEmail"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        if not v:
            return Binary(None)
        return Binary(bool(_EMAIL_RE.match(v)))


class EmailPrefixTransformer(Transformer):
    """Email -> Text local part (reference RichEmailFeature
    .toEmailPrefix:578)."""

    input_types = (Text,)
    output_type = Text

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "emailPrefix"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        from ..types import Email
        e = vals[0] if isinstance(vals[0], Email) else Email(vals[0].value)
        return Text(e.prefix())


class UrlPartsTransformer(Transformer):
    """URL -> Text domain or protocol (reference RichURLFeature
    .toDomain:630 / .toProtocol:635); `part` selects which. Parsing
    delegates to the URL type helpers (types/text.py) — ONE urllib-based
    parser in the codebase, java.net.URL.getHost semantics (userinfo and
    port stripped)."""

    input_types = (Text,)
    output_type = Text

    @classmethod
    def _declare_params(cls):
        return [Param("part", "domain|protocol", "domain")]

    def __init__(self, part: str = "domain", uid: Optional[str] = None,
                 **params):
        params.setdefault("part", part)
        super().__init__(params.pop("operation_name", "urlParts"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        from ..types import URL
        u = vals[0] if isinstance(vals[0], URL) else URL(vals[0].value)
        return Text(u.domain() if str(self.get_param("part")) == "domain"
                    else u.protocol())


class ValidUrlTransformer(Transformer):
    """URL -> Binary validity, optionally restricted to protocols
    (reference RichURLFeature.isValidUrl:642,650 — defaults http/https/ftp,
    dotless hosts like localhost accepted, matching java.net.URL parsing).
    Delegates to URL.is_valid (types/text.py)."""

    input_types = (Text,)
    output_type = Binary

    @classmethod
    def _declare_params(cls):
        return [Param("protocols", "accepted schemes",
                      ["http", "https", "ftp"])]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "validUrl"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        from ..types import URL
        if vals[0].value is None:
            return Binary(None)
        u = vals[0] if isinstance(vals[0], URL) else URL(vals[0].value)
        return Binary(u.is_valid(tuple(self.get_param("protocols"))))


class UrlToDomainPickList(Transformer):
    """URL -> PickList of the domain when the URL is valid, empty
    otherwise (reference RichURLFeature.vectorize:676: `if (v.isValid)
    v.domain.toPickList else PickList.empty`) — the derivation step of
    the URL transmogrify default."""

    input_types = (Text,)
    output_type = PickList

    @classmethod
    def _declare_params(cls):
        return [Param("protocols", "accepted schemes",
                      ["http", "https", "ftp"])]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "urlDomainPick"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        from ..types import URL
        u = vals[0] if isinstance(vals[0], URL) else URL(vals[0].value)
        if u.value is None or not u.is_valid(tuple(self.get_param("protocols"))):
            return PickList(None)
        return PickList(u.domain())


class TextToMultiPickList(Transformer):
    """Text -> MultiPickList singleton set (reference RichTextFeature
    .toMultiPickList:58)."""

    input_types = (Text,)
    output_type = MultiPickList

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "toMultiPickList"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        return MultiPickList(set() if not v else {v})
