"""Text-processing transformers.

Reference: core/.../impl/feature/{TextTokenizer(196), NGramSimilarity,
JaccardSimilarity, OpCountVectorizer, TextLenTransformer, SubstringTransformer,
OpStringIndexer, OpIndexToString, LangDetector, MimeTypeDetector,
PhoneNumberParser(566)}.scala + utils/.../text analyzers.

Host/device split (SURVEY hard-parts): tokenization/parsing stays host-side
(strings never reach the device); everything downstream emits fixed-width
numeric columns. The reference leaned on Lucene/Optimaize/Tika/libphonenumber
(all JVM); these are self-contained re-implementations of the behaviors the
AutoML pipeline actually consumes — analyzers are pluggable the same way the
reference's TextAnalyzer interface is.
"""
from __future__ import annotations

import base64 as b64mod
import math
import re
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.dataset import Column, column_from_values
from ..stages.base import Estimator, Transformer
from ..stages.params import Param
from ..types import (
    Binary, ColumnKind, Integral, MultiPickList, OPVector, PickList, Real,
    RealNN, Text, TextList,
)

# token = maximal run of unicode alphanumerics or apostrophes (underscore is
# a separator). For pure-ASCII text this is exactly the C++ fused tokenizer's
# [A-Za-z0-9'] rule (native/hashing.cpp:104), so the native fast path can be
# used whenever the input is ASCII; non-ASCII text keeps unicode tokens like
# Lucene's (unicode-aware) standard analyzer instead of mangling them.
_TOKEN_RE = re.compile(r"(?:[^\W_]|')+", re.UNICODE)
_STOPWORDS = {
    "a", "an", "and", "are", "as", "at", "be", "but", "by", "for", "if",
    "in", "into", "is", "it", "no", "not", "of", "on", "or", "such", "that",
    "the", "their", "then", "there", "these", "they", "this", "to", "was",
    "will", "with",
}


def tokenize_text(value: Optional[str], min_token_length: int = 1,
                  to_lowercase: bool = True,
                  filter_stopwords: bool = False) -> List[str]:
    """The default analyzer (reference TextTokenizer.Analyzer / Lucene
    standard analyzer behavior)."""
    if not value:
        return []
    s = value.lower() if to_lowercase else value
    toks = [t for t in _TOKEN_RE.findall(s) if len(t) >= min_token_length]
    if filter_stopwords:
        toks = [t for t in toks if t not in _STOPWORDS]
    return toks


class TextTokenizer(Transformer):
    """Text -> TextList (reference TextTokenizer.scala:196)."""

    input_types = (Text,)
    output_type = TextList

    @classmethod
    def _declare_params(cls):
        return [Param("min_token_length", "min token length", 1),
                Param("to_lowercase", "lowercase before split", True),
                Param("filter_stopwords", "drop english stopwords", False)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "tokenize"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        return TextList(tokenize_text(
            vals[0].value, int(self.get_param("min_token_length")),
            bool(self.get_param("to_lowercase")),
            bool(self.get_param("filter_stopwords"))))


class TextLenTransformer(Transformer):
    """Text -> Integral length (reference TextLenTransformer); empty -> 0."""

    input_types = (Text,)
    output_type = Integral

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "textLen"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        return Integral(0 if v is None else len(v))


class SubstringTransformer(Transformer):
    """(Text, Text) -> Binary: second contains first (reference
    SubstringTransformer)."""

    input_types = (Text, Text)
    output_type = Binary

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "substring"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        sub, s = vals[0].value, vals[1].value
        if sub is None or s is None:
            return Binary(None)
        return Binary(sub.lower() in s.lower())


def _ngrams(tokens: Sequence[str], n: int) -> Counter:
    joined = " ".join(tokens)
    return Counter(joined[i:i + n] for i in range(max(len(joined) - n + 1, 0)))


class NGramSimilarity(Transformer):
    """(TextList, TextList) -> RealNN cosine similarity over char n-grams
    (reference NGramSimilarity.scala, Lucene NGramDistance)."""

    input_types = (TextList, TextList)
    output_type = RealNN

    @classmethod
    def _declare_params(cls):
        return [Param("n", "gram size", 3)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "nGramSimilarity"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        a, b = vals[0].value or [], vals[1].value or []
        if not a or not b:
            return RealNN(0.0)
        n = int(self.get_param("n"))
        ca, cb = _ngrams(a, n), _ngrams(b, n)
        dot = sum(ca[g] * cb[g] for g in ca.keys() & cb.keys())
        na = math.sqrt(sum(v * v for v in ca.values()))
        nb = math.sqrt(sum(v * v for v in cb.values()))
        return RealNN(dot / (na * nb) if na and nb else 0.0)


class JaccardSimilarity(Transformer):
    """(MultiPickList, MultiPickList) -> RealNN (reference
    JaccardSimilarity.scala); both empty -> 1.0."""

    input_types = (MultiPickList, MultiPickList)
    output_type = RealNN

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "jaccardSimilarity"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        a = set(vals[0].value or ())
        b = set(vals[1].value or ())
        if not a and not b:
            return RealNN(1.0)
        union = len(a | b)
        return RealNN(len(a & b) / union if union else 0.0)


class OpStringIndexer(Estimator):
    """Text -> RealNN frequency-rank index (reference OpStringIndexer;
    unseen/null handled per handle_invalid like StringIndexer)."""

    input_types = (Text,)
    output_type = RealNN

    @classmethod
    def _declare_params(cls):
        return [Param("handle_invalid", "error|skip|keep", "keep")]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "stringIndexer"),
                         uid=uid, **params)

    def fit_columns(self, *cols: Column) -> Transformer:
        counts = Counter(v for v in cols[0].data
                         if v is not None and v != "")
        labels = [w for w, _ in counts.most_common()]
        return OpStringIndexerModel(
            labels=labels,
            handle_invalid=str(self.get_param("handle_invalid")),
            operation_name=self.operation_name)


class OpStringIndexerModel(Transformer):
    input_types = (Text,)
    output_type = RealNN

    def __init__(self, labels: Optional[Sequence[str]] = None,
                 handle_invalid: str = "keep",
                 uid: Optional[str] = None, **params):
        self.labels = list(labels or [])
        self.handle_invalid = handle_invalid
        self._index = {w: i for i, w in enumerate(self.labels)}
        super().__init__(params.pop("operation_name", "stringIndexer"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        idx = self._index.get(v)
        if idx is None:
            if self.handle_invalid == "error":
                raise ValueError(f"Unseen label: {v!r}")
            idx = len(self.labels)  # keep: unseen bucket
        return RealNN(float(idx))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(labels=self.labels, handle_invalid=self.handle_invalid)
        return d


class OpIndexToString(Transformer):
    """RealNN index -> Text label (reference OpIndexToString)."""

    input_types = (RealNN,)
    output_type = Text

    def __init__(self, labels: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None, **params):
        self.labels = list(labels or [])
        super().__init__(params.pop("operation_name", "indexToString"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        i = int(vals[0].value)
        return Text(self.labels[i] if 0 <= i < len(self.labels) else None)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(labels=self.labels)
        return d


class OpCountVectorizer(Estimator):
    """TextList -> OPVector of top-K vocabulary counts (reference
    OpCountVectorizer wrapping Spark CountVectorizer)."""

    input_types = (TextList,)
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        return [Param("vocab_size", "max vocabulary", 512),
                Param("min_df", "min docs containing term", 1),
                Param("binary", "0/1 instead of counts", False)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "countVec"),
                         uid=uid, **params)

    def _vocab(self, col: Column) -> List[str]:
        df: Counter = Counter()
        for toks in col.data:
            if toks:
                df.update(set(toks))
        min_df = int(self.get_param("min_df"))
        vocab = [w for w, c in df.most_common() if c >= min_df]
        return vocab[: int(self.get_param("vocab_size"))]

    def fit_columns(self, *cols: Column) -> Transformer:
        return OpCountVectorizerModel(
            vocab=self._vocab(cols[0]),
            binary=bool(self.get_param("binary")),
            operation_name=self.operation_name)


class OpCountVectorizerModel(Transformer):
    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, vocab: Optional[Sequence[str]] = None,
                 binary: bool = False, idf: Optional[np.ndarray] = None,
                 uid: Optional[str] = None, **params):
        self.vocab = list(vocab or [])
        self.binary = bool(binary)
        self.idf = None if idf is None else np.asarray(idf, np.float64)
        self._index = {w: i for i, w in enumerate(self.vocab)}
        super().__init__(params.pop("operation_name", "countVec"),
                         uid=uid, **params)

    def _encode(self, toks) -> np.ndarray:
        out = np.zeros(len(self.vocab), np.float32)
        for t in toks or []:
            i = self._index.get(t)
            if i is not None:
                out[i] += 1.0
        if self.binary:
            out = (out > 0).astype(np.float32)
        if self.idf is not None:
            out = out * self.idf
        return out

    def transform_value(self, *vals):
        return OPVector(self._encode(vals[0].value))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(vocab=self.vocab, binary=self.binary,
                 idf=self.idf if self.idf is not None else None)
        return d


class TfIdfVectorizer(OpCountVectorizer):
    """TextList -> OPVector TF-IDF (reference `idf` dsl on tokenized text
    wrapping Spark IDF)."""

    def __init__(self, uid: Optional[str] = None, **params):
        Estimator.__init__(self, "tfidf", uid=uid, **params)

    def fit_columns(self, *cols: Column) -> Transformer:
        vocab = self._vocab(cols[0])
        index = {w: i for i, w in enumerate(vocab)}
        n_docs = len(cols[0])
        df = np.zeros(len(vocab), np.float64)
        for toks in cols[0].data:
            for w in set(toks or []):
                i = index.get(w)
                if i is not None:
                    df[i] += 1.0
        idf = np.log((n_docs + 1.0) / (df + 1.0))
        return OpCountVectorizerModel(vocab=vocab, idf=idf,
                                      operation_name=self.operation_name)


# -- light analyzers (reference leaned on JVM libs; behavior-parity impls) --

_LANG_PROFILES = {
    "en": set("the and ing ion to of in er it is".split()),
    "fr": set("le la les de et un une est que dans".split()),
    "de": set("der die das und ist ein nicht mit sich den".split()),
    "es": set("el la los de y un una es que en".split()),
}


class LangDetector(Transformer):
    """Text -> PickList language code (reference LangDetector via Optimaize;
    here a stopword-profile heuristic over the same output contract)."""

    input_types = (Text,)
    output_type = PickList

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "langDetect"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        if not v:
            return PickList(None)
        toks = set(tokenize_text(v))
        best, score = None, 0
        for lang, words in _LANG_PROFILES.items():
            s = len(toks & words)
            if s > score:
                best, score = lang, s
        return PickList(best or "unknown")


_MIME_MAGIC: List[Tuple[bytes, str]] = [
    (b"\x89PNG", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF8", "image/gif"),
    (b"%PDF", "application/pdf"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"<?xml", "application/xml"),
    (b"{", "application/json"),
]


class MimeTypeDetector(Transformer):
    """Base64 -> PickList MIME type via magic bytes (reference
    MimeTypeDetector via Tika)."""

    input_types = (Text,)   # Base64 is a Text subtype
    output_type = PickList

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "mimeDetect"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        if not v:
            return PickList(None)
        try:
            head = b64mod.b64decode(v[:64] + "=" * (-len(v[:64]) % 4))
        except Exception:
            return PickList(None)
        for magic, mime in _MIME_MAGIC:
            if head.startswith(magic):
                return PickList(mime)
        try:
            head.decode("utf-8")
            return PickList("text/plain")
        except UnicodeDecodeError:
            return PickList("application/octet-stream")


class PhoneNumberParser(Transformer):
    """Phone -> Binary validity (reference PhoneNumberParser.scala:566 via
    libphonenumber; NANP-style structural validation)."""

    input_types = (Text,)
    output_type = Binary

    @classmethod
    def _declare_params(cls):
        return [Param("default_region", "region for bare numbers", "US"),
                Param("strict", "strict length validation", True)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "phoneValid"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        if not v:
            return Binary(None)
        digits = re.sub(r"[^\d+]", "", v)
        if digits.startswith("+"):
            body = digits[1:]
            ok = 8 <= len(body) <= 15 and body.isdigit()
        else:
            region = str(self.get_param("default_region"))
            n = len(digits)
            ok = digits.isdigit() and (
                (region == "US" and (n == 10 or (n == 11 and
                                                 digits.startswith("1"))))
                or (region != "US" and 7 <= n <= 15))
        return Binary(bool(ok))


class EmailToPickList(Transformer):
    """Email -> PickList of the domain (reference RichEmailFeature
    .toEmailDomain)."""

    input_types = (Text,)
    output_type = PickList

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "emailDomain"),
                         uid=uid, **params)

    def transform_value(self, *vals):
        v = vals[0].value
        if not v or "@" not in v:
            return PickList(None)
        local, _, domain = v.rpartition("@")
        return PickList(domain if local and domain else None)
