"""Topic-model and embedding stages: OpLDA, OpWord2Vec.

Reference: core/.../impl/feature/OpLDA.scala:60 (LDA over a count vector ->
topic-distribution vector, params k/maxIter/optimizer) and OpWord2Vec.scala
(TextList -> averaged word vectors). Kernels live in ops/lda.py and
ops/embeddings.py; these stages provide the estimator/model contract,
vector metadata, and persistence.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np

from ..automl.vectorizers.base import VectorizerModel
from ..data.dataset import Column
from ..data.vector import VectorColumnMetadata, VectorMetadata
from ..stages.base import Estimator
from ..stages.params import Param
from ..types import OPVector, TextList


def _as_matrix(col: Column) -> np.ndarray:
    X = np.asarray(col.data, np.float32)
    if X.ndim == 1:
        X = X[:, None]
    return X


class OpLDA(Estimator):
    """OPVector (term counts) -> OPVector of topic distributions.

    Reference OpLDA.scala:60 defaults: k=10, maxIter=10 (online) — here EM
    runs a fixed 50 iterations (pure matmuls; far cheaper per iteration
    than Spark's distributed EM)."""

    input_types = (OPVector,)
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        return [Param("k", "number of topics", 10, lambda v: v >= 2),
                Param("max_iter", "EM iterations", 50, lambda v: v > 0),
                Param("doc_concentration", "alpha prior", 1.1),
                Param("topic_concentration", "eta prior", 1.01),
                Param("seed", "init seed", 42)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "lda"), uid=uid,
                         **params)

    def fit_columns(self, *cols: Column) -> "OpLDAModel":
        from ..ops.lda import fit_lda

        C = _as_matrix(cols[0])
        k = int(self.get_param("k"))
        _, beta = fit_lda(
            C, jax.random.PRNGKey(int(self.get_param("seed"))),
            n_topics=k, n_iter=int(self.get_param("max_iter")),
            alpha=float(self.get_param("doc_concentration")),
            eta=float(self.get_param("topic_concentration")))
        model = OpLDAModel(
            beta=np.asarray(beta),
            alpha=float(self.get_param("doc_concentration")),
            operation_name=self.operation_name)
        parent = self.input_features[0] if self.input_features else None
        model.set_metadata(VectorMetadata(
            name=self.output_name(),
            columns=[VectorColumnMetadata(
                parent_feature_name=parent.name if parent else "lda",
                parent_feature_type=parent.type_name if parent else "OPVector",
                descriptor_value=f"topic_{t}") for t in range(k)]))
        return model


class OpLDAModel(VectorizerModel):
    """Frozen topics; transform = variational fold-in (topicDistribution)."""

    input_types = (OPVector,)

    def __init__(self, beta: Optional[np.ndarray] = None, alpha: float = 1.1,
                 uid: Optional[str] = None, **params):
        self.beta = np.asarray(beta, np.float32) if beta is not None else \
            np.zeros((0, 0), np.float32)
        self.alpha = float(alpha)
        super().__init__(params.pop("operation_name", "lda"), uid=uid,
                         **params)

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        from ..ops.lda import lda_fold_in

        return np.asarray(lda_fold_in(_as_matrix(cols[0]),
                                      self.beta, alpha=self.alpha))

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(beta=self.beta, alpha=self.alpha)
        return d


class OpWord2Vec(Estimator):
    """TextList -> OPVector document embedding (mean of word vectors).

    Reference OpWord2Vec.scala wraps Spark Word2Vec (vectorSize=100 default,
    skip-gram SGD); here word vectors come from ALS factorization of the
    hashed windowed co-occurrence matrix (ops/embeddings.py) — deterministic
    given the seed and shaped for the MXU."""

    input_types = (TextList,)
    output_type = OPVector

    @classmethod
    def _declare_params(cls):
        return [Param("vector_size", "embedding dim", 100, lambda v: v >= 2),
                Param("vocab_bins", "hashed vocabulary size", 2048),
                Param("window_size", "co-occurrence window", 5),
                Param("num_iterations", "ALS iterations", 10),
                Param("seed", "hash + init seed", 42)]

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(params.pop("operation_name", "w2v"), uid=uid,
                         **params)

    def fit_columns(self, *cols: Column) -> "OpWord2VecModel":
        from ..ops.embeddings import cooccurrence_matrix, factorize_embeddings

        seed = int(self.get_param("seed"))
        bins = int(self.get_param("vocab_bins"))
        dim = int(self.get_param("vector_size"))
        C = cooccurrence_matrix(cols[0].data, bins,
                                window=int(self.get_param("window_size")),
                                seed=seed)
        emb = factorize_embeddings(
            C, jax.random.PRNGKey(seed), dim=dim,
            n_iter=int(self.get_param("num_iterations")))
        model = OpWord2VecModel(embeddings=np.asarray(emb), seed=seed,
                                operation_name=self.operation_name)
        parent = self.input_features[0] if self.input_features else None
        model.set_metadata(VectorMetadata(
            name=self.output_name(),
            columns=[VectorColumnMetadata(
                parent_feature_name=parent.name if parent else "w2v",
                parent_feature_type=parent.type_name if parent else "TextList",
                descriptor_value=f"dim_{j}") for j in range(dim)]))
        return model


class OpWord2VecModel(VectorizerModel):
    input_types = (TextList,)

    def __init__(self, embeddings: Optional[np.ndarray] = None, seed: int = 42,
                 uid: Optional[str] = None, **params):
        self.embeddings = np.asarray(embeddings, np.float32) \
            if embeddings is not None else np.zeros((0, 0), np.float32)
        self.seed = int(seed)
        super().__init__(params.pop("operation_name", "w2v"), uid=uid,
                         **params)

    def transform_block(self, cols: Sequence[Column]) -> np.ndarray:
        from ..ops.embeddings import mean_pool_docs

        return mean_pool_docs(cols[0].data, self.embeddings, seed=self.seed)

    def save_args(self) -> Dict[str, Any]:
        d = super().save_args()
        d.update(embeddings=self.embeddings, seed=self.seed)
        return d
