"""Zero-downtime champion/challenger rollout.

State machine (docs/fleet.md "Rollout"):

    IDLE -> WARMING -> SHADOW -> SWAPPED   (terminal until next start)
                   \\         \\-> REJECTED (bad challenger torn down)
                    \\-> REJECTED (challenger failed to come up)

- WARMING: the challenger model dir is prewarmed (``serve
  --prewarm-only`` via the supervisor, stamping ITS manifest) and a
  challenger replica pool spawns NEXT TO the champions. Champions never
  stop serving; a challenger that fails to join is rejected without a
  single request touching it.
- SHADOW: the router mirrors a configurable fraction of successful
  single-record responses into :meth:`RolloutManager.observe` as RAW
  bytes — the request thread pays one random() and one bounded-queue
  put, nothing else; parsing, score extraction and re-scoring on a
  challenger replica all run on the rollout's worker thread, and both
  scores accumulate into calibration-bin histograms. Responses always
  come from v1; a request is never double-answered.
- VERDICT: after ``min_shadow`` mirrored pairs, the v1-vs-v2 prediction
  distributions are compared with the EXISTING drift engine
  (monitor/drift: JS on the full histograms, PSI with the
  sampling-noise compensation on coarsened bins, score-mean shift) —
  champion/challenger IS train-vs-score drift with "train" replaced by
  "the model you trust".
- SWAP: one atomic pool swap under the fleet lock (Router.swap_pools);
  in-flight champion requests finish on their old handles, every later
  pick sees v2. The old champions drain (router removal -> outstanding
  == 0 -> SIGTERM) and stop. ``fleet_rollout_swapped``.
- REJECTED: the challenger pool tears down the same drain path;
  champions never stopped serving. ``fleet_rollout_rejected``.
"""
from __future__ import annotations

import json
import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..monitor import drift
from ..monitor.profile import score_hist
from ..utils.metrics import collector
from .router import CONN_ERRORS, ReplicaHandle, Router, http_json

_log = logging.getLogger("transmogrifai_tpu.fleet")

Record = Dict[str, Any]

IDLE = "idle"
WARMING = "warming"
SHADOW = "shadow"
SWAPPED = "swapped"
REJECTED = "rejected"


class RolloutConflict(RuntimeError):
    """A rollout is already in flight (or still draining): the request
    is well-formed but cannot proceed NOW — the fleet frontend maps
    this to HTTP 409, while challenger startup FAILURES stay plain
    errors (HTTP 500): retrying a conflict is right, retrying a broken
    challenger artifact is not."""

#: default score-distribution comparison bins (the monitor's
#: calibration-bin convention, monitor/profile.DEFAULT_PRED_BINS x4 for
#: a sharper JS at rollout sample sizes)
SHADOW_BINS = 40


def response_score(row: Record, field: Optional[str] = None
                   ) -> Optional[float]:
    """The scalar prediction out of one /score response row — the same
    shape monitor/profile.score_of reads: {result: {"probability_1":
    ..}} for classifiers, {result: number} otherwise. Field auto-detects
    when not pinned."""
    for v in row.values():
        if isinstance(v, dict):
            for k in ((field,) if field else ("probability_1",
                                              "prediction")):
                if k in v:
                    try:
                        f = float(v[k])
                    except (TypeError, ValueError):
                        continue
                    if np.isfinite(f):
                        return f
        elif isinstance(v, (int, float)) and np.isfinite(float(v)):
            return float(v)
    return None


class RolloutManager:
    """Drive one champion/challenger rollout at a time.

    Collaborators are duck-typed for testability: `supervisor` needs
    ``ensure_manifest``/``spawn_pool``/``stop_replicas``; `router` needs
    the pool/swap/shadow API. `score_lo`/`score_hi` bound the score
    histograms — [0, 1] (probabilities) unless the champion's
    monitor.json prediction profile pins a range."""

    def __init__(self, supervisor: Any, router: Router, *,
                 lock: Optional[threading.RLock] = None,
                 score_lo: float = 0.0, score_hi: float = 1.0,
                 score_field: Optional[str] = None,
                 max_pred_js: float = 0.25,
                 max_psi: float = 0.25,
                 max_score_shift: float = 0.2,
                 queue_max: int = 1024):
        self.supervisor = supervisor
        self.router = router
        self.lock = lock or router.lock
        self.score_lo = float(score_lo)
        self.score_hi = float(score_hi)
        self.score_field = score_field
        self.max_pred_js = float(max_pred_js)
        self.max_psi = float(max_psi)
        self.max_score_shift = float(max_score_shift)
        #: per-ROLLOUT verdict-threshold overrides (start(thresholds=),
        #: reset on every start): a retrain cycle relaxes the
        #: comparison for ITS adapted candidate without disarming the
        #: guards for later operator-initiated rollouts
        self._thresholds: Dict[str, float] = {}
        self.state = IDLE
        self.challenger_dir: Optional[str] = None
        self.fraction = 0.0
        self.min_shadow = 0
        self.shadow_pairs = 0
        self.shadow_dropped = 0
        self.shadow_errors = 0
        self.last_verdict: Optional[Dict[str, Any]] = None
        self._v1_hist = np.zeros(SHADOW_BINS, np.float64)
        self._v2_hist = np.zeros(SHADOW_BINS, np.float64)
        self._v1_sum = 0.0
        self._v2_sum = 0.0
        #: raw (request bytes, response bytes) pairs — parsing happens
        #: on the WORKER thread, so the request thread's only shadow
        #: cost is one random() and one put_nowait
        self._q: "queue.Queue[Tuple[bytes, bytes]]" = queue.Queue(
            maxsize=queue_max)
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle ----------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self.lock:
            return {"state": self.state,
                    "challenger_dir": self.challenger_dir,
                    "fraction": self.fraction,
                    "min_shadow": self.min_shadow,
                    "shadow_pairs": self.shadow_pairs,
                    "shadow_dropped": self.shadow_dropped,
                    "shadow_errors": self.shadow_errors,
                    "last_verdict": self.last_verdict}

    def start(self, challenger_dir: str, *, replicas: Optional[int] = None,
              fraction: float = 0.2, min_shadow: int = 256,
              thresholds: Optional[Dict[str, float]] = None) -> Dict:
        """Begin a rollout: prewarm + spawn the challenger pool, then
        open the shadow tap. Raises on a concurrent rollout; a
        challenger that cannot come up is REJECTED here (champions were
        never touched). `thresholds` overrides max_pred_js / max_psi /
        max_score_shift for THIS rollout only (the retrain controller's
        adapted-candidate relaxation); the next start() is back at the
        manager's base thresholds."""
        with self.lock:
            if self.state in (WARMING, SHADOW):
                # refuse BEFORE touching the worker: stopping it here
                # would orphan the rollout that owns it (tap open, pairs
                # queuing, nobody left to reach a verdict)
                raise RolloutConflict(f"a rollout is already "
                                      f"{self.state} "
                                      f"({self.challenger_dir})")
        # the PREVIOUS (completed) rollout's worker may still be
        # finishing its swap/teardown (stop_replicas drains): it must be
        # fully gone before its queue and histograms are reused, or
        # rollout B's verdict would be computed from A-era shadow pairs
        # by two racing workers
        old_worker = self._worker
        if old_worker is not None and old_worker.is_alive():
            self._stop.set()
            old_worker.join(60.0)
            if old_worker.is_alive():
                raise RolloutConflict("the previous rollout is still "
                                      "draining its pools; retry "
                                      "shortly")
        with self.lock:
            if self.state in (WARMING, SHADOW):
                # a racing start() won the gap between check and claim
                raise RolloutConflict(f"a rollout is already "
                                      f"{self.state} "
                                      f"({self.challenger_dir})")
            self.state = WARMING
            self.challenger_dir = challenger_dir
            self.fraction = float(fraction)
            self.min_shadow = int(min_shadow)
            self._thresholds = {
                k: float(v) for k, v in (thresholds or {}).items()
                if k in ("max_pred_js", "max_psi", "max_score_shift")}
            self.shadow_pairs = 0
            self.shadow_dropped = 0
            self.shadow_errors = 0
            self.last_verdict = None
            self._v1_hist[:] = 0.0
            self._v2_hist[:] = 0.0
            self._v1_sum = self._v2_sum = 0.0
            # stale pairs mirrored for the PREVIOUS champion generation
            # must not seed this rollout's verdict
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            n = int(replicas or max(len(self.router.champions), 1))
        collector.event("fleet_rollout_started",
                        challenger=challenger_dir, fraction=fraction,
                        min_shadow=min_shadow, replicas=n)
        _log.info("fleet: rollout started — challenger %s, %d replica(s),"
                  " shadow fraction %.2f, verdict after %d pairs",
                  challenger_dir, n, fraction, min_shadow)
        try:
            self.supervisor.ensure_manifest(challenger_dir)
            pool = self.supervisor.spawn_pool(challenger_dir, n,
                                              pool="challenger")
        except Exception as e:
            with self.lock:
                self.state = REJECTED
                self.last_verdict = {"reasons": [f"challenger failed to "
                                                 f"start: {e}"]}
            collector.event("fleet_rollout_rejected",
                            challenger=challenger_dir,
                            reason="startup_failure", error=str(e))
            raise
        with self.lock:
            # ONE atomic claim: an abort() that won the race flipped
            # state off WARMING (and set _stop) under this same lock,
            # so either we see it here — and tear the fresh pool down —
            # or it runs after SHADOW is visible and takes the normal
            # abort path against a fully-wired rollout. Clearing _stop
            # anywhere outside this block would clobber that signal.
            aborted = self.state != WARMING
            if not aborted:
                self.router.set_challengers(pool)
                self._stop.clear()
                self.state = SHADOW
                self.router.shadow_hook = self.observe
                self.router.shadow_fraction = self.fraction
                worker = threading.Thread(target=self._shadow_loop,
                                          name="fleet-shadow",
                                          daemon=True)
                self._worker = worker
        if aborted:
            # an operator abort() landed while the challenger was
            # warming: the freshly-spawned pool must not leak and the
            # abort must WIN — a resurrected rollout would shadow
            # traffic the operator believes is torn down
            self.supervisor.stop_replicas(pool, drain=False,
                                          router=self.router)
            return self.status()
        worker.start()
        return self.status()

    # -- shadow path --------------------------------------------------------
    def observe(self, request_body: bytes, response_body: bytes) -> bool:
        """Router hook: one mirrored (request, champion response) pair,
        RAW bytes. Enqueue-and-return — parsing, score extraction and
        challenger scoring all happen on the worker thread, so the
        request thread's only shadow cost is this put; a full queue
        DROPS the sample (counted): shadow scoring must never apply
        backpressure to live traffic. Returns False on a drop — the
        router marks the request's trace so the tail sampler keeps
        evidence of shadow starvation."""
        try:
            self._q.put_nowait((request_body, response_body))
            return True
        except queue.Full:
            with self.lock:
                self.shadow_dropped += 1
            return False

    def _shadow_loop(self) -> None:
        while not self._stop.is_set():
            try:
                req_body, resp_body = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                self._score_raw_pair(req_body, resp_body)
            except Exception:
                with self.lock:
                    self.shadow_errors += 1
                _log.exception("fleet: shadow scoring failed")
            if self._verdict_due():
                self._decide()

    def _score_raw_pair(self, req_body: bytes, resp_body: bytes) -> None:
        """Worker-side half of one mirrored pair: parse both sides,
        extract the champion score, re-score on a challenger."""
        try:
            record = json.loads(req_body)
            row = json.loads(resp_body)
        except (json.JSONDecodeError, ValueError):
            return  # the served response already left; nothing to do
        if not (isinstance(record, dict) and isinstance(row, dict)):
            return  # bulk bodies are batch jobs, not live traffic
        v1 = response_score(row, self.score_field)
        if v1 is None:
            return
        self._score_pair(record, v1)

    def _pick_challenger(self) -> Optional[Tuple[ReplicaHandle, str, int]]:
        with self.lock:
            ready = [h for h in self.router.challengers
                     if h.healthy and not h.stopping]
            if not ready:
                return None
            h = min(ready, key=lambda r: r.outstanding)
            h.outstanding += 1
            return h, h.host, h.port

    def _score_pair(self, record: Record, v1: float) -> None:
        picked = self._pick_challenger()
        if picked is None:
            with self.lock:
                self.shadow_dropped += 1
            return
        h, host, port = picked
        try:
            status, data = http_json(
                host, port, "POST", "/score",
                body=json.dumps(record).encode(),
                timeout=self.router.request_timeout)
        except CONN_ERRORS + (TimeoutError,):
            with self.lock:
                self.shadow_errors += 1
            return
        finally:
            with self.lock:
                h.outstanding = max(h.outstanding - 1, 0)
        if status != 200:
            with self.lock:
                self.shadow_errors += 1
            return
        v2 = response_score(json.loads(data), self.score_field)
        if v2 is None:
            with self.lock:
                self.shadow_errors += 1
            return
        with self.lock:
            self._v1_hist += score_hist(np.asarray([v1]), self.score_lo,
                                        self.score_hi, SHADOW_BINS)
            self._v2_hist += score_hist(np.asarray([v2]), self.score_lo,
                                        self.score_hi, SHADOW_BINS)
            self._v1_sum += v1
            self._v2_sum += v2
            self.shadow_pairs += 1

    def _verdict_due(self) -> bool:
        with self.lock:
            return (self.state == SHADOW
                    and self.shadow_pairs >= self.min_shadow)

    # -- verdict ------------------------------------------------------------
    def verdict(self) -> Dict[str, Any]:
        """Compare the shadowed v1-vs-v2 prediction distributions with
        the drift engine's metrics; {"clean": bool, "reasons": [...]}.
        Same arithmetic the serve monitor applies to train-vs-score
        prediction drift, including the small-sample PSI compensation."""
        with self.lock:
            h1, h2 = self._v1_hist.copy(), self._v2_hist.copy()
            n = self.shadow_pairs
            s1, s2 = self._v1_sum, self._v2_sum
            ov = dict(self._thresholds)
        js_max = ov.get("max_pred_js", self.max_pred_js)
        psi_max = ov.get("max_psi", self.max_psi)
        shift_max = ov.get("max_score_shift", self.max_score_shift)
        js = drift.js_divergence_hist(h1, h2)
        c1, c2 = drift.coarsen(h1), drift.coarsen(h2)
        psi = drift.psi(c1, c2)
        psi_thr = psi_max + 2.0 * drift.psi_sampling_noise(c1, c2)
        shift = abs(s2 / n - s1 / n) if n else 0.0
        reasons: List[str] = []
        if js > js_max:
            reasons.append(f"prediction_js {js:.4f} > {js_max}")
        if psi > psi_thr:
            reasons.append(f"prediction_psi {psi:.4f} > {psi_thr:.4f}")
        if shift > shift_max:
            reasons.append(f"score_shift {shift:.4f} > "
                           f"{shift_max}")
        return {"clean": not reasons, "reasons": reasons,
                "shadow_pairs": n, "js": round(js, 6),
                "psi": round(psi, 6), "psi_threshold": round(psi_thr, 6),
                "mean_shift": round(shift, 6),
                "v1_mean": round(s1 / n, 6) if n else None,
                "v2_mean": round(s2 / n, 6) if n else None}

    def _decide(self) -> None:
        v = self.verdict()
        with self.lock:
            if self.state != SHADOW:
                return  # a concurrent decision already landed
            self.last_verdict = v
            # close the tap before acting so no new pairs race the swap
            self.router.shadow_hook = None
            self.router.shadow_fraction = 0.0
            self.state = SWAPPED if v["clean"] else REJECTED
            challenger_dir = self.challenger_dir
        self._stop.set()
        if v["clean"]:
            self._swap(challenger_dir, v)
        else:
            self._reject(challenger_dir, v)

    def _swap(self, challenger_dir: str, v: Dict[str, Any]) -> None:
        old = self.router.swap_pools()
        collector.event("fleet_rollout_swapped", challenger=challenger_dir,
                        shadow_pairs=v["shadow_pairs"], js=v["js"],
                        psi=v["psi"], mean_shift=v["mean_shift"])
        _log.info("fleet: rollout SWAPPED to %s (js=%.4f psi=%.4f "
                  "shift=%.4f over %d shadow pairs); draining %d old "
                  "champion(s)", challenger_dir, v["js"], v["psi"],
                  v["mean_shift"], v["shadow_pairs"], len(old))
        # the retired champions bleed off in-flight work, then stop —
        # zero dropped requests by construction; state stays SWAPPED
        # (terminal-informational) until the next start()
        self.supervisor.stop_replicas(old, drain=True, router=self.router)

    def _reject(self, challenger_dir: str, v: Dict[str, Any]) -> None:
        with self.lock:
            pool = list(self.router.challengers)
        self.router.set_challengers([])
        collector.event("fleet_rollout_rejected",
                        challenger=challenger_dir,
                        reason="; ".join(v["reasons"]),
                        shadow_pairs=v["shadow_pairs"], js=v["js"],
                        psi=v["psi"], mean_shift=v["mean_shift"])
        _log.warning("fleet: rollout REJECTED — %s; tearing down %d "
                     "challenger(s), champions keep serving",
                     "; ".join(v["reasons"]), len(pool))
        self.supervisor.stop_replicas(pool, drain=True, router=self.router)

    def abort(self) -> None:
        """Operator abort: close the tap, tear the challengers down."""
        with self.lock:
            if self.state not in (WARMING, SHADOW):
                return
            self.router.shadow_hook = None
            self.router.shadow_fraction = 0.0
            self.state = REJECTED
            # an abort is an OPERATOR decision, not a shadow verdict:
            # the marker lets a consumer (the retrain controller) tell
            # "the model failed at traffic" from "someone needed the
            # slot" — the latter must not ban the candidate
            self.last_verdict = {"clean": False, "reasons": ["aborted"],
                                 "aborted": True}
            pool = list(self.router.challengers)
            challenger_dir = self.challenger_dir
        self._stop.set()
        self.router.set_challengers([])
        collector.event("fleet_rollout_rejected",
                        challenger=challenger_dir, reason="aborted")
        self.supervisor.stop_replicas(pool, drain=True, router=self.router)
