"""Fleet telemetry: per-replica state MERGED, not concatenated.

Everything the serving stack measures was deliberately shaped as a
mergeable sufficient statistic (ROADMAP: the DrJAX MapReduce shape,
PAPERS arxiv 2403.07128 — applied here host-side across processes
instead of across chips):

- latency histograms are fixed log-spaced bucket counts, so the fleet
  p50/p99 comes from SUMMED buckets
  (:meth:`~transmogrifai_tpu.utils.metrics.LatencyHistogram.merge`),
  exactly what one histogram recording every replica's stream would
  hold — not an average of per-replica quantiles (which is wrong
  whenever replicas see different mixes);
- engine counters (requests/batches/rows/shed/post-warmup compiles) are
  plain sums;
- drift-monitor window state is histogram mass + null counts + score
  moments: the fleet sums the per-replica CURRENT windows
  (``GET /drift/window``) into one pooled window and runs ONE
  DriftPolicy verdict on it. That pooling is the statistical point: a
  fleet of N replicas each holding 1/N of a window must alert exactly
  like one replica holding the whole window — per-replica small windows
  must NOT alert where the pooled window wouldn't (the
  ``psi_sampling_noise`` compensation and ``min_rows`` floor see the
  pooled row count).

With N=1 every merge is the identity, so fleet endpoints equal the
single replica's — the golden-parity acceptance pin.
"""
from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..monitor import drift
from ..monitor.alerts import DriftPolicy
from ..monitor.profile import ReferenceProfile
from ..monitor.window import WindowSnapshot
from ..utils.metrics import LatencyHistogram

#: engine counters that merge by summation across replicas
#: (pad_rows/bucket_rows back the fleet-wide pad fraction of the
#: request-tracing segment decomposition, observability.md)
_SUM_KEYS = ("requests", "batches", "rows", "shed",
             "post_warmup_compiles", "pad_rows", "bucket_rows")

#: FALLBACK namespace tag for pooled /drift window_ids when replica
#: window states carry no monitor nonce (stub replicas in tests): a
#: restarted fleet's window indices restart at 0, and the retrain
#: controller's quarantine ledger keys on (champion_hash, window_id)
#: FOREVER — without a fresh tag a new incarnation's pooled window
#: could collide with a quarantined id and suppress genuinely new
#: drift. Real fleets get a tag digested from the contributing
#: monitors' own nonces (fleet_drift), which also covers a single
#: replica restarting WITHIN a long-lived fleet process.
_POOL_NONCE = os.urandom(4).hex()


def merge_latency(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge a list of LatencyHistogram.to_json() payloads (same
    histogram name across replicas) into one to_json() payload —
    bucket-sum exact, identity for a single element."""
    if not docs:
        return LatencyHistogram().to_json()
    out = LatencyHistogram.from_json(docs[0])
    for d in docs[1:]:
        out.merge(LatencyHistogram.from_json(d))
    return out.to_json()


def fleet_metrics(replica_metrics: List[Dict[str, Any]],
                  per_replica: Optional[List[Dict[str, Any]]] = None
                  ) -> Dict[str, Any]:
    """The fleet ``GET /metrics`` payload from per-replica /metrics
    documents: counters summed, latency histograms bucket-sum merged
    per histogram name. `per_replica` (handle.describe() dicts) rides
    along so an operator can see the spread behind the merge."""
    docs = [m for m in replica_metrics if isinstance(m, dict)]
    out: Dict[str, Any] = {"replicas": len(docs)}
    for k in _SUM_KEYS:
        out[k] = sum(int(m.get(k) or 0) for m in docs)
    out["warm"] = all(bool(m.get("warm")) for m in docs) if docs else False
    names: List[str] = []
    for m in docs:
        for nm in (m.get("latency") or {}):
            if nm not in names:
                names.append(nm)
    out["latency"] = {
        nm: merge_latency([m["latency"][nm] for m in docs
                           if nm in (m.get("latency") or {})])
        for nm in names}
    if per_replica is not None:
        out["per_replica"] = per_replica
    return out


def fleet_requests(replica_payloads: List[Dict[str, Any]],
                   router_payload: Optional[Dict[str, Any]] = None,
                   top: int = 20) -> Dict[str, Any]:
    """The fleet ``GET /requests`` payload: per-segment latency
    histograms merged by EXACT bucket sum across replicas (same
    arithmetic as fleet /metrics latency — the merged p99 of the
    `device` segment IS the p99 of the union stream), kept traces
    POOLED (router-side + every replica's ring) and ranked
    slowest-first, counters summed. The router's own segment
    histograms (route/upstream walls) stay separate under
    ``router_segments`` — summing a hop's wall into the replica
    segments would double-count the time."""
    docs = [p for p in replica_payloads if isinstance(p, dict)]
    names: List[str] = []
    for d in docs:
        for nm in (d.get("segments") or {}):
            if nm not in names:
                names.append(nm)
    segments = {
        nm: merge_latency([d["segments"][nm] for d in docs
                           if nm in (d.get("segments") or {})])
        for nm in names}
    kept: List[Dict[str, Any]] = []
    counters = {"traces": 0, "kept": 0, "in_flight": 0}
    by_reason: Dict[str, int] = {}
    sources = docs + ([router_payload]
                      if isinstance(router_payload, dict) else [])
    for d in sources:
        kept.extend(k for k in (d.get("kept") or [])
                    if isinstance(k, dict))
        c = d.get("counters") or {}
        for key in counters:
            counters[key] += int(c.get(key) or 0)
        for reason, n in (c.get("kept_by_reason") or {}).items():
            by_reason[reason] = by_reason.get(reason, 0) + int(n)
    counters["kept_by_reason"] = by_reason
    # outcome keeps (error/shed/retry/shadow_drop) rank ahead of
    # merely-slow/sampled ones, slowest-first within each class: a
    # bounded top-K must not let a burst of tail-latency keeps crowd
    # out the one failed request the operator is hunting
    kept.sort(key=lambda k: (
        0 if k.get("kept") not in ("sample", "slow") else 1,
        -(k.get("wall_ms") if isinstance(k.get("wall_ms"),
                                         (int, float)) else 0.0)))
    # router+replica records of one request share a trace id — surface
    # how many kept traces have their cross-hop twin in the pool
    ids: Dict[str, set] = {}
    for k in kept:
        tid = k.get("trace_id")
        if isinstance(tid, str):
            ids.setdefault(tid, set()).add(k.get("origin"))
    out: Dict[str, Any] = {
        "replicas": len(docs),
        "segments": segments,
        "kept": kept[:int(top)],
        "counters": counters,
        "joined_traces": sum(1 for o in ids.values() if len(o) > 1),
    }
    if isinstance(router_payload, dict):
        out["router_segments"] = router_payload.get("segments") or {}
    return out


def fleet_history(replica_payloads: List[Dict[str, Any]],
                  router_gauges: Optional[List[Dict[str, Any]]] = None
                  ) -> Dict[str, Any]:
    """The fleet ``GET /metrics/history`` payload: every replica's gauge
    ring keyed by replica id, plus the router's own ring. Gauge series
    are deliberately NOT summed across replicas — each snapshot is
    stamped on its own process clock, and aligning unsynchronized
    clocks is exactly the cross-process timestamp arithmetic this layer
    refuses to do; per-replica series + the summed counters in /metrics
    carry the same information honestly."""
    replicas: Dict[str, Any] = {}
    for d in replica_payloads:
        if isinstance(d, dict) and d.get("replica"):
            replicas[str(d["replica"])] = d.get("gauges") or []
    return {"router": list(router_gauges or []), "replicas": replicas}


def merge_window_states(states: List[Dict[str, Any]]) -> WindowSnapshot:
    """Sum per-replica ``/drift/window`` states into ONE pooled
    WindowSnapshot — component-wise addition of every sufficient
    statistic. Merging a single state reproduces it exactly (golden
    parity); merging N is bit-exact with a monitor that observed all N
    traffic streams, because each component is a plain sum and f64
    addition of the per-replica partial sums is the same arithmetic the
    single monitor's host merge performs."""
    hists: Dict[str, np.ndarray] = {}
    nulls: Dict[str, float] = {}
    rows = 0.0
    wall = 0.0
    pred_hist: Optional[np.ndarray] = None
    pred_count = 0.0
    pred_sum = 0.0
    index = 0
    for st in states:
        if not isinstance(st, dict):
            continue
        rows += float(st.get("rows") or 0.0)
        wall = max(wall, float(st.get("wall_s") or 0.0))
        index = max(index, int(st.get("window_index") or 0))
        for nm, h in (st.get("hists") or {}).items():
            arr = np.asarray(h, np.float64)
            if nm in hists:
                hists[nm] = hists[nm] + arr
            else:
                hists[nm] = arr
            nulls[nm] = nulls.get(nm, 0.0) + float(
                (st.get("nulls") or {}).get(nm, 0.0))
        ph = st.get("pred_hist")
        if ph is not None:
            arr = np.asarray(ph, np.float64)
            pred_hist = arr if pred_hist is None else pred_hist + arr
            pred_count += float(st.get("pred_count") or 0.0)
            pred_sum += float(st.get("pred_sum") or 0.0)
    return WindowSnapshot(index=index, rows=rows, wall_s=wall,
                          hists=hists, nulls=nulls, pred_hist=pred_hist,
                          pred_count=pred_count, pred_sum=pred_sum)


def fleet_drift(profile: ReferenceProfile,
                states: List[Dict[str, Any]],
                policy: Optional[DriftPolicy] = None,
                per_replica: Optional[List[Dict[str, Any]]] = None
                ) -> Dict[str, Any]:
    """The fleet ``GET /drift`` payload: pool the replica window states,
    run the SAME drift engine (monitor/drift.window_report) once on the
    pooled window. One verdict for the whole fleet, evaluated at the
    pooled sample size."""
    policy = policy or DriftPolicy()
    good = [s for s in states if isinstance(s, dict)]
    snap = merge_window_states(good)
    report = drift.window_report(profile, snap, policy)
    # pooled window identity, DETERMINISTIC per poll cycle: the same
    # still-open pooled window polled twice yields the same id, so an
    # alert consumer (the retrain controller's /drift poll) dedupes
    # repeat reads; a rollover bumps the max window_index and mints a
    # fresh id. The namespace tag digests the contributing monitors'
    # OWN nonces (each ServeMonitor mints one per construction): a
    # restarted replica — or a restarted fleet — brings a fresh monitor,
    # its indices restart at 0, and without a fresh tag its pooled "w3"
    # would collide with dedupe/quarantine state recorded against a
    # previous incarnation's windows, silently suppressing genuinely
    # new drift. Falls back to the per-process nonce when states carry
    # no nonce (stub replicas). Model hash rides along for the
    # stale-alert check.
    nonces = sorted({str(s.get("nonce")) for s in good
                     if isinstance(s, dict) and s.get("nonce")})
    tag = (hashlib.sha256("|".join(nonces).encode()).hexdigest()[:8]
           if nonces else _POOL_NONCE)
    report["window_id"] = (f"{profile.model_hash or 'unstamped'}:"
                           f"fleet-{tag}:w{int(snap.index)}")
    report["model_content_hash"] = profile.model_hash
    out: Dict[str, Any] = {
        "replicas_reporting": len(good),
        "rows_pooled": snap.rows,
        "policy": policy.to_json(),
        "pooled": report,
        "alerting": bool(report["alerts"]),
    }
    if per_replica is not None:
        out["per_replica"] = per_replica
    return out
