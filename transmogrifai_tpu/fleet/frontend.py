"""Fleet HTTP frontend + the ``fleet`` CLI body.

One stdlib ThreadingHTTPServer in front of the replica pool — the same
transport-thin discipline as ``serve/frontend.py``: every decision lives
in :class:`FleetFrontend` (which tests and bench drive in-process), the
handler only maps it onto HTTP.

Endpoints:
  POST /score          routed to the least-loaded healthy replica
                       (retry-once on connection error; fleet-level 503
                       when every replica sheds)
  GET  /healthz        fleet health: replica table + rollout state
  GET  /metrics        MERGED telemetry: counters summed, latency
                       histograms bucket-sum merged (fleet/telemetry)
  GET  /drift          pooled drift verdict over the replicas' current
                       window states (one DriftPolicy evaluation)
  GET  /drain          fleet drain: healthz -> 503 (LB rotation), then
                       the operator stops the fleet
  POST /rollout        {"model_dir": .., "fraction": .., "min_shadow":
                       ..} -> start a champion/challenger rollout
  GET  /rollout        rollout status (state machine + last verdict)
  POST /rollout/abort  tear the challenger down, keep champions
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ..monitor.alerts import DriftPolicy
from ..monitor.profile import ReferenceProfile
from ..utils.metrics import collector
from ..workflow.io import load_monitor_profile
from . import telemetry
from .rollout import RolloutConflict, RolloutManager
from .router import (FleetUnavailable, HealthProber, Router, get_json)
from .supervisor import Supervisor

_log = logging.getLogger("transmogrifai_tpu.fleet")

Record = Dict[str, Any]


class FleetFrontend:
    """The in-process fleet API (HTTP handler, tests and bench share).

    Wires Supervisor (processes) + Router (traffic) + RolloutManager
    (model versions) + telemetry (merged observability) behind one
    object. `profile`/`policy` power the pooled /drift verdict; both are
    optional (fleets of unmonitored models simply 404 /drift, like a
    single replica would)."""

    def __init__(self, supervisor: Supervisor, router: Router,
                 rollout: Optional[RolloutManager] = None, *,
                 profile: Optional[ReferenceProfile] = None,
                 policy: Optional[DriftPolicy] = None):
        self.supervisor = supervisor
        self.router = router
        self.rollout = rollout
        self.profile = profile
        self.policy = policy or DriftPolicy()
        self._draining = threading.Event()
        # one persistent poll pool: telemetry scrapes fan out over the
        # replicas concurrently without paying thread churn per scrape
        import concurrent.futures as cf
        self._poll_pool = cf.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="fleet-poll")

    def close(self) -> None:
        self._poll_pool.shutdown(wait=False)

    # -- scoring ------------------------------------------------------------
    def forward_score(self, body: bytes):
        return self.router.forward_score(body)

    def submit(self, record: Record) -> Record:
        """In-process single-record scoring through the full router path
        (bench + tests). Raises FleetUnavailable/TimeoutError like the
        HTTP surface; raises RuntimeError on replica-side 4xx/5xx."""
        status, data = self.router.forward_score(
            json.dumps(record).encode())
        if status != 200:
            raise RuntimeError(f"replica returned {status}: "
                               f"{data[:200]!r}")
        return json.loads(data)

    # -- health / drain -----------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self) -> Dict[str, Any]:
        if not self._draining.is_set():
            self._draining.set()
            collector.event("fleet_drain")
            _log.info("fleet: draining — /healthz now 503")
        return self.healthz()

    def healthz(self) -> Dict[str, Any]:
        reps = [h.describe() for h in self.router.replicas()]
        healthy = self.router.healthy_count()
        status = "ok" if healthy > 0 else "down"
        if self._draining.is_set():
            status = "draining"
        out = {"status": status, "healthy_replicas": healthy,
               "draining": self._draining.is_set(), "replicas": reps}
        if self.rollout is not None:
            out["rollout"] = self.rollout.status()
        return out

    # -- merged telemetry ---------------------------------------------------
    def _poll_champions(self, path: str) -> List[Any]:
        """(describe, payload-or-None) per champion: addresses are
        snapshotted under the fleet lock (a restart may be rewriting a
        port on another thread), then the GETs run CONCURRENTLY on the
        persistent poll pool — one hung replica costs the scrape one
        timeout, not N of them."""
        with self.router.lock:
            targets = [(h.host, h.port, h.describe())
                       for h in self.router.champions]
        if not targets:
            return []
        futs = [self._poll_pool.submit(get_json, host, port, path)
                for host, port, _ in targets]
        return [(desc, f.result())
                for (_, _, desc), f in zip(targets, futs)]

    def metrics(self) -> Dict[str, Any]:
        docs: List[Dict[str, Any]] = []
        per: List[Dict[str, Any]] = []
        for desc, m in self._poll_champions("/metrics"):
            if m is not None:
                docs.append(m)
            per.append(desc)
        out = telemetry.fleet_metrics(docs, per_replica=per)
        out["router"] = {
            "requests": self.router.n_requests,
            "retries": self.router.n_retries,
            "shed": self.router.n_shed,
            "latency": self.router.hist.to_json(),
        }
        return out

    def drift(self) -> Optional[Dict[str, Any]]:
        """Pooled fleet drift (None -> 404 when monitoring is off):
        every champion's current window state, summed, one verdict."""
        if self.profile is None:
            return None
        states: List[Dict[str, Any]] = []
        per: List[Dict[str, Any]] = []
        for desc, st in self._poll_champions("/drift/window"):
            if st is not None and "error" not in st:
                states.append(st)
                per.append({"name": desc["name"], "url": desc["url"],
                            "rows": st.get("rows")})
        return telemetry.fleet_drift(self.profile, states,
                                     policy=self.policy, per_replica=per)

    # -- rollout ------------------------------------------------------------
    def start_rollout(self, model_dir: str, *, fraction: float = 0.2,
                      min_shadow: int = 256,
                      replicas: Optional[int] = None) -> Dict[str, Any]:
        if self.rollout is None:
            raise RuntimeError("rollout manager not configured")
        return self.rollout.start(model_dir, fraction=fraction,
                                  min_shadow=min_shadow,
                                  replicas=replicas)


class _FleetHandler(BaseHTTPRequestHandler):
    server_version = "transmogrifai-tpu-fleet"
    frontend: FleetFrontend  # attached by make_fleet_server

    def log_message(self, fmt: str, *args: Any) -> None:
        _log.debug("fleet http: " + fmt, *args)

    def _reply(self, code: int, payload: Any,
               raw: Optional[bytes] = None) -> None:
        body = raw if raw is not None else json.dumps(
            payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        fe = self.server.frontend  # type: ignore[attr-defined]
        try:
            if self.path == "/healthz":
                h = fe.healthz()
                self._reply(503 if h["status"] in ("down", "draining")
                            else 200, h)
            elif self.path == "/metrics":
                self._reply(200, fe.metrics())
            elif self.path == "/drain":
                self._reply(200, fe.drain())
            elif self.path == "/drift":
                d = fe.drift()
                if d is None:
                    self._reply(404, {"error": "drift monitoring not "
                                               "enabled for this fleet"})
                else:
                    self._reply(200, d)
            elif self.path == "/rollout":
                if fe.rollout is None:
                    self._reply(404, {"error": "no rollout manager"})
                else:
                    self._reply(200, fe.rollout.status())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})
        except Exception as e:  # pragma: no cover - systemic faults
            _log.exception("fleet: GET %s failed", self.path)
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    def do_POST(self) -> None:  # noqa: N802
        fe = self.server.frontend  # type: ignore[attr-defined]
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if self.path == "/score":
                try:
                    status, data = fe.forward_score(body)
                    self._reply(status, None, raw=data)
                except FleetUnavailable as e:
                    self._reply(e.status, {"error": str(e),
                                           "error_type": "FleetUnavailable"})
                except TimeoutError as e:
                    self._reply(504, {"error": str(e)})
            elif self.path == "/rollout":
                doc = json.loads(body or b"{}")
                out = fe.start_rollout(
                    str(doc["model_dir"]),
                    fraction=float(doc.get("fraction", 0.2)),
                    min_shadow=int(doc.get("min_shadow", 256)),
                    replicas=doc.get("replicas"))
                self._reply(200, out)
            elif self.path == "/rollout/abort":
                if fe.rollout is None:
                    self._reply(404, {"error": "no rollout manager"})
                else:
                    fe.rollout.abort()
                    self._reply(200, fe.rollout.status())
            elif self.path == "/drain":
                # REST-proper alias of GET /drain (which the fleet keeps
                # for parity with the replica endpoint + curl ergonomics)
                self._reply(200, fe.drain())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})
        except (json.JSONDecodeError, KeyError, ValueError) as e:
            self._reply(400, {"error": f"{type(e).__name__}: {e}"})
        except RolloutConflict as e:
            # retryable: another rollout holds the slot right now
            self._reply(409, {"error": str(e)})
        except Exception as e:
            # incl. challenger STARTUP failures (broken artifact, prewarm
            # rc != 0): a 409 would invite retry loops against a model
            # that can never come up
            _log.exception("fleet: POST %s failed", self.path)
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})


def make_fleet_server(frontend: FleetFrontend, host: str = "127.0.0.1",
                      port: int = 0) -> ThreadingHTTPServer:
    httpd = ThreadingHTTPServer((host, port), _FleetHandler)
    httpd.daemon_threads = True
    httpd.frontend = frontend  # type: ignore[attr-defined]
    return httpd


# -- the `fleet` CLI body -----------------------------------------------------

def run_fleet(args: Any) -> int:
    """Body of ``python -m transmogrifai_tpu fleet`` (cli.py parses):
    prewarm-if-needed, spawn N replicas, route until SIGTERM, drain."""
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s")

    metrics_loc = getattr(args, "metrics_location", None) or \
        os.path.join(args.model_dir, "fleet_metrics")
    os.makedirs(metrics_loc, exist_ok=True)
    collector.enable("fleet")
    collector.attach_event_log(os.path.join(metrics_loc, "events.jsonl"))

    serve_args: List[str] = []
    if getattr(args, "max_batch", None):
        serve_args += ["--max-batch", str(args.max_batch)]
    if getattr(args, "buckets", None):
        serve_args += ["--buckets", str(args.buckets)]
    if getattr(args, "max_wait_ms", None) is not None:
        serve_args += ["--max-wait-ms", str(args.max_wait_ms)]
    if getattr(args, "max_queue", None):
        serve_args += ["--max-queue", str(args.max_queue)]
    if getattr(args, "single_record", None):
        serve_args += ["--single-record", args.single_record]
    if getattr(args, "monitor", None):
        serve_args += ["--monitor", args.monitor]

    lock = threading.RLock()
    supervisor = Supervisor(
        args.model_dir, replicas=int(args.replicas), lock=lock,
        metrics_root=os.path.join(metrics_loc, "replicas"),
        host=getattr(args, "replica_host", "127.0.0.1"),
        serve_args=serve_args,
        max_restarts=int(getattr(args, "max_restarts", 20)))
    router = Router(lock, request_timeout=float(
        getattr(args, "request_timeout_s", 30.0)))

    profile = policy = None
    if getattr(args, "monitor", "auto") != "off":
        doc = load_monitor_profile(args.model_dir)
        if doc is not None:
            try:
                profile = ReferenceProfile.from_json(doc)
                policy = DriftPolicy()
            except Exception:
                _log.exception("fleet: unusable monitor.json; pooled "
                               "/drift disabled")

    try:
        router.set_champions(supervisor.start())
    except Exception:
        _log.exception("fleet: startup failed")
        supervisor.stop()
        collector.detach_event_log()
        collector.disable()
        return 1
    prober = HealthProber(router, interval_s=float(
        getattr(args, "probe_interval_s", 0.5))).start()
    # score-comparison bounds pinned at construction (the shadow worker
    # reads them on its own thread): the champion's prediction profile
    # when it has one, else the [0, 1] probability default
    pred = profile.prediction if profile is not None else None
    rollout = RolloutManager(
        supervisor, router, lock=lock,
        score_lo=pred.lo if pred else 0.0,
        score_hi=pred.hi if pred else 1.0,
        score_field=pred.field if pred else None)
    frontend = FleetFrontend(supervisor, router, rollout,
                             profile=profile, policy=policy)
    httpd = make_fleet_server(frontend, host=args.host, port=args.port)
    host, port = httpd.server_address[:2]
    _log.info("fleet: %d replica(s) of %s behind http://%s:%s",
              int(args.replicas), args.model_dir, host, port)

    def _graceful(signum: int, frame: Any) -> None:
        _log.info("fleet: signal %s — draining and shutting down", signum)
        frontend.drain()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    def _drain_signal(signum: int, frame: Any) -> None:
        frontend.drain()

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
        if hasattr(signal, "SIGUSR1"):
            signal.signal(signal.SIGUSR1, _drain_signal)
    except ValueError:  # not on the main thread (tests drive in-process)
        pass

    try:
        httpd.serve_forever(poll_interval=0.1)
    finally:
        httpd.server_close()
        prober.stop()
        if rollout is not None:
            rollout.abort()
        supervisor.stop(router=router)
        frontend.close()
        collector.save(os.path.join(metrics_loc,
                                    "fleet_stage_metrics.json"))
        collector.save_chrome_trace(os.path.join(metrics_loc,
                                                 "fleet_trace.json"))
        collector.detach_event_log()
        collector.disable()
        _log.info("fleet: drained; router served %d request(s), "
                  "%d retried, %d shed", router.n_requests,
                  router.n_retries, router.n_shed)
    return 0
