"""Fleet HTTP frontend + the ``fleet`` CLI body.

One stdlib ThreadingHTTPServer in front of the replica pool — the same
transport-thin discipline as ``serve/frontend.py``: every decision lives
in :class:`FleetFrontend` (which tests and bench drive in-process), the
handler only maps it onto HTTP.

Endpoints:
  POST /score          routed to the least-loaded healthy replica
                       (retry-once on connection error; fleet-level 503
                       when every replica sheds); X-Tmog-* request
                       headers pass through to the replica, the
                       X-Tmog-Trace echo names the serving replica
  GET  /healthz        fleet health: replica table + rollout state
  GET  /metrics        MERGED telemetry: counters summed, latency
                       histograms bucket-sum merged (fleet/telemetry)
  GET  /metrics/history  per-replica gauge rings + the router's own
                       (time-series; observability.md)
  GET  /requests       request tracing: per-segment histograms merged
                       by exact bucket sum + pooled tail-kept traces
  GET  /debugz         fleet-process thread dump + router health bits
  GET  /drift          pooled drift verdict over the replicas' current
                       window states (one DriftPolicy evaluation)
  GET  /drain          fleet drain: healthz -> 503 (LB rotation), then
                       the operator stops the fleet
  POST /rollout        {"model_dir": .., "fraction": .., "min_shadow":
                       ..} -> start a champion/challenger rollout
  GET  /rollout        rollout status (state machine + last verdict)
  POST /rollout/abort  tear the challenger down, keep champions
  POST /retrain        manual retrain trigger ({"force": bool}); 409 on
                       a concurrent cycle, mirroring RolloutConflict
                       (docs/retraining.md)
  GET  /retrainz       retrain controller status: state machine, last
                       candidate verdict, quarantine list
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from ..monitor.alerts import DriftPolicy
from ..monitor.profile import ReferenceProfile
from ..retrain.controller import RetrainConflict
from ..serve import reqtrace
from ..serve.reqtrace import (GaugeSampler, ReqTracer, RequestTrace,
                              thread_dump)
from ..utils.metrics import GaugeRing, collector
from ..workflow.io import load_monitor_profile
from . import telemetry
from .rollout import RolloutConflict, RolloutManager
from .router import (FleetUnavailable, HealthProber, Router, get_json)
from .supervisor import Supervisor

_log = logging.getLogger("transmogrifai_tpu.fleet")

Record = Dict[str, Any]


class FleetFrontend:
    """The in-process fleet API (HTTP handler, tests and bench share).

    Wires Supervisor (processes) + Router (traffic) + RolloutManager
    (model versions) + telemetry (merged observability) behind one
    object. `profile`/`policy` power the pooled /drift verdict; both are
    optional (fleets of unmonitored models simply 404 /drift, like a
    single replica would)."""

    def __init__(self, supervisor: Supervisor, router: Router,
                 rollout: Optional[RolloutManager] = None, *,
                 profile: Optional[ReferenceProfile] = None,
                 policy: Optional[DriftPolicy] = None,
                 retrain: Optional[Any] = None):
        self.supervisor = supervisor
        self.router = router
        self.rollout = rollout
        self.profile = profile
        self.policy = policy or DriftPolicy()
        #: retrain controller (retrain/controller.py) — optional; when
        #: wired, successful single-record bodies tap into its traffic
        #: ring and POST /retrain + GET /retrainz come alive
        self.retrain = retrain
        #: which champion model dir self.profile was loaded for — after
        #: a rollout swap the pooled /drift verdict must compare against
        #: the NEW champion's profile or drift could never clear on it
        self._profile_dir: Optional[str] = None
        self._draining = threading.Event()
        # router-side request tracer (observability.md "Request
        # tracing"): the frontend guarantees one exists — it mints the
        # trace ids the hop header carries — and shares it with the
        # Router so forward_score can stamp route/upstream segments.
        # A Router built bare (unit tests) keeps tracer=None and pays
        # nothing.
        if router.tracer is None:
            router.tracer = ReqTracer("router", origin="router",
                                      enabled=reqtrace.env_enabled())
        self.tracer = router.tracer
        self.gauges = GaugeRing()
        # one persistent poll pool: telemetry scrapes fan out over the
        # replicas concurrently without paying thread churn per scrape
        import concurrent.futures as cf
        self._poll_pool = cf.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="fleet-poll")

    def close(self) -> None:
        self._poll_pool.shutdown(wait=False)

    # -- scoring ------------------------------------------------------------
    def forward_score(self, body: bytes,
                      trace: Optional[RequestTrace] = None,
                      headers: Optional[Dict[str, str]] = None):
        status, data = self.router.forward_score(body, trace=trace,
                                                 headers=headers)
        # traffic tap for the retrain controller's "recent window":
        # successful SINGLE-record bodies only (bulk bodies are batch
        # jobs), one bounded deque append on the request thread
        if (self.retrain is not None and status == 200
                and body[:1] == b"{"):
            self.retrain.tap(body)
        return status, data

    def submit(self, record: Record) -> Record:
        """In-process single-record scoring through the full router path
        (bench + tests). Raises FleetUnavailable/TimeoutError like the
        HTTP surface; raises RuntimeError on replica-side 4xx/5xx."""
        rt = self.tracer.start(None)
        try:
            # through the frontend's own forward_score so in-process
            # callers feed the retrain traffic tap exactly like HTTP ones
            status, data = self.forward_score(
                json.dumps(record).encode(), trace=rt)
        except FleetUnavailable as e:
            self.tracer.finish(rt, status=e.status,
                               error_type="FleetUnavailable")
            raise
        except TimeoutError:
            self.tracer.finish(rt, status=504,
                               error_type="TimeoutError")
            raise
        self.tracer.finish(rt, status=status)
        if status != 200:
            raise RuntimeError(f"replica returned {status}: "
                               f"{data[:200]!r}")
        return json.loads(data)

    # -- health / drain -----------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self) -> Dict[str, Any]:
        if not self._draining.is_set():
            self._draining.set()
            collector.event("fleet_drain")
            _log.info("fleet: draining — /healthz now 503")
        return self.healthz()

    def healthz(self) -> Dict[str, Any]:
        reps = [h.describe() for h in self.router.replicas()]
        healthy = self.router.healthy_count()
        status = "ok" if healthy > 0 else "down"
        if self._draining.is_set():
            status = "draining"
        out = {"status": status, "healthy_replicas": healthy,
               "draining": self._draining.is_set(), "replicas": reps}
        if self.rollout is not None:
            out["rollout"] = self.rollout.status()
        if self.retrain is not None:
            out["retrain_state"] = self.retrain.effective_state()
        return out

    # -- merged telemetry ---------------------------------------------------
    def _poll_champions(self, path: str) -> List[Any]:
        """(describe, payload-or-None) per champion: addresses are
        snapshotted under the fleet lock (a restart may be rewriting a
        port on another thread), then the GETs run CONCURRENTLY on the
        persistent poll pool — one hung replica costs the scrape one
        timeout, not N of them."""
        with self.router.lock:
            targets = [(h.host, h.port, h.describe())
                       for h in self.router.champions]
        if not targets:
            return []
        futs = [self._poll_pool.submit(get_json, host, port, path)
                for host, port, _ in targets]
        return [(desc, f.result())
                for (_, _, desc), f in zip(targets, futs)]

    def metrics(self) -> Dict[str, Any]:
        docs: List[Dict[str, Any]] = []
        per: List[Dict[str, Any]] = []
        for desc, m in self._poll_champions("/metrics"):
            if m is not None:
                docs.append(m)
            per.append(desc)
        out = telemetry.fleet_metrics(docs, per_replica=per)
        out["router"] = {
            "requests": self.router.n_requests,
            "retries": self.router.n_retries,
            "shed": self.router.n_shed,
            "latency": self.router.hist.to_json(),
        }
        return out

    def requests(self) -> Dict[str, Any]:
        """The fleet ``GET /requests`` payload: per-replica segment
        histograms merged by exact bucket sum, kept traces pooled with
        the router's own ring (fleet/telemetry.fleet_requests)."""
        docs = [m for _, m in self._poll_champions("/requests")
                if m is not None]
        return telemetry.fleet_requests(
            docs, router_payload=self.tracer.requests_payload())

    def history(self) -> Dict[str, Any]:
        """The fleet ``GET /metrics/history`` payload: per-replica gauge
        rings + the router's (fleet/telemetry.fleet_history)."""
        docs = [m for _, m in self._poll_champions("/metrics/history")
                if m is not None]
        return telemetry.fleet_history(docs,
                                       router_gauges=self.gauges.to_json())

    def sample_gauges(self) -> Dict[str, Any]:
        """Router-side gauge snapshot (GaugeSampler's read)."""
        with self.router.lock:
            outstanding = sum(h.outstanding
                              for h in self.router.champions)
            n_requests = self.router.n_requests
            n_retries = self.router.n_retries
            n_shed = self.router.n_shed
        return {"healthy_replicas": self.router.healthy_count(),
                "outstanding": outstanding,
                "requests": n_requests,
                "retries": n_retries,
                "shed": n_shed,
                "in_flight": self.tracer.in_flight,
                "draining": self.draining}

    def debugz(self) -> Dict[str, Any]:
        """Fleet-process "why is it stuck" snapshot: thread dump +
        router health bits (each replica serves its OWN /debugz with
        its batcher/dispatcher state)."""
        with self.router.lock:
            outstanding = sum(h.outstanding
                              for h in self.router.replicas())
        out = {"threads": thread_dump(),
               "healthy_replicas": self.router.healthy_count(),
               "outstanding": outstanding,
               "in_flight": self.tracer.in_flight,
               "draining": self.draining}
        if self.rollout is not None:
            out["rollout_state"] = self.rollout.state
        return out

    def _current_profile(self) -> Optional[ReferenceProfile]:
        """The reference profile of the CURRENTLY serving champion
        pool. A rollout swap changes the champion model dir; the pooled
        verdict must then compare against the new champion's
        monitor.json (the retrain acceptance pin "drift clears on the
        new champion" depends on it). Falls back to the as-constructed
        profile when the dir has none (stub replicas in tests)."""
        if self.profile is None:
            return None  # monitoring off for this fleet stays off —
            # a swap must not silently turn /drift on
        with self.router.lock:
            pool = self.router.champions
            model_dir = pool[0].model_dir if pool else None
        if model_dir and model_dir != self._profile_dir:
            from ..workflow.io import load_monitor_profile
            doc = load_monitor_profile(model_dir)
            if doc is not None:
                try:
                    self.profile = ReferenceProfile.from_json(doc)
                except Exception:
                    _log.exception("fleet: unusable monitor.json under "
                                   "%s; keeping the previous pooled-"
                                   "drift profile", model_dir)
            elif self._profile_dir is not None:
                _log.warning("fleet: champion %s has no monitor.json; "
                             "pooled /drift keeps the previous "
                             "champion's profile", model_dir)
            # artifacts are immutable: remember the dir either way so a
            # profile-less (or corrupt) champion logs ONCE, not on
            # every 2s poll for the rest of the fleet's life
            self._profile_dir = model_dir
        return self.profile

    def drift(self) -> Optional[Dict[str, Any]]:
        """Pooled fleet drift (None -> 404 when monitoring is off):
        every champion's current window state, summed, one verdict."""
        profile = self._current_profile()
        if profile is None:
            return None
        states: List[Dict[str, Any]] = []
        per: List[Dict[str, Any]] = []
        for desc, st in self._poll_champions("/drift/window"):
            if st is not None and "error" not in st:
                states.append(st)
                per.append({"name": desc["name"], "url": desc["url"],
                            "rows": st.get("rows")})
        return telemetry.fleet_drift(profile, states,
                                     policy=self.policy, per_replica=per)

    # -- rollout ------------------------------------------------------------
    def start_rollout(self, model_dir: str, *, fraction: float = 0.2,
                      min_shadow: int = 256,
                      replicas: Optional[int] = None) -> Dict[str, Any]:
        if self.rollout is None:
            raise RuntimeError("rollout manager not configured")
        return self.rollout.start(model_dir, fraction=fraction,
                                  min_shadow=min_shadow,
                                  replicas=replicas)

    # -- retrain ------------------------------------------------------------
    def start_retrain(self, *, force: bool = False) -> Dict[str, Any]:
        """Manual retrain trigger (``POST /retrain``). Raises
        RetrainConflict (HTTP 409) on a concurrent cycle or an
        un-forced cooldown/storm suppression."""
        if self.retrain is None:
            raise RuntimeError("retrain controller not configured")
        return self.retrain.trigger(reason="manual", force=force)

    def retrainz(self) -> Optional[Dict[str, Any]]:
        """The ``GET /retrainz`` payload (None -> 404 when no
        controller is wired)."""
        return None if self.retrain is None else self.retrain.status()


class _FleetHandler(BaseHTTPRequestHandler):
    server_version = "transmogrifai-tpu-fleet"
    frontend: FleetFrontend  # attached by make_fleet_server

    def log_message(self, fmt: str, *args: Any) -> None:
        _log.debug("fleet http: " + fmt, *args)

    @staticmethod
    def _trace_echo(fe: FleetFrontend,
                    rt: Optional[RequestTrace]) -> Optional[str]:
        """The X-Tmog-Trace reply header: trace id + the replica that
        actually served (known after forward_score reads the replica's
        own echo)."""
        if rt is None:
            return None
        return reqtrace.format_trace_header(rt.trace_id,
                                            replica=rt.replica)

    def _reply(self, code: int, payload: Any,
               raw: Optional[bytes] = None,
               trace_header: Optional[str] = None) -> None:
        body = raw if raw is not None else json.dumps(
            payload, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if trace_header:
            self.send_header(reqtrace.TRACE_HEADER, trace_header)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        fe = self.server.frontend  # type: ignore[attr-defined]
        try:
            if self.path == "/healthz":
                h = fe.healthz()
                self._reply(503 if h["status"] in ("down", "draining")
                            else 200, h)
            elif self.path == "/metrics":
                self._reply(200, fe.metrics())
            elif self.path == "/metrics/history":
                self._reply(200, fe.history())
            elif self.path == "/requests":
                self._reply(200, fe.requests())
            elif self.path == "/debugz":
                self._reply(200, fe.debugz())
            elif self.path == "/drain":
                self._reply(200, fe.drain())
            elif self.path == "/drift":
                d = fe.drift()
                if d is None:
                    self._reply(404, {"error": "drift monitoring not "
                                               "enabled for this fleet"})
                else:
                    self._reply(200, d)
            elif self.path == "/rollout":
                if fe.rollout is None:
                    self._reply(404, {"error": "no rollout manager"})
                else:
                    self._reply(200, fe.rollout.status())
            elif self.path == "/retrainz":
                r = fe.retrainz()
                if r is None:
                    self._reply(404, {"error": "no retrain controller "
                                               "configured for this "
                                               "fleet"})
                else:
                    self._reply(200, r)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})
        except Exception as e:  # pragma: no cover - systemic faults
            _log.exception("fleet: GET %s failed", self.path)
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})

    def do_POST(self) -> None:  # noqa: N802
        fe = self.server.frontend  # type: ignore[attr-defined]
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if self.path == "/score":
                # hop context: adopt a client-supplied trace id when one
                # arrived, pass every X-Tmog-* header through to the
                # replica (the debug-sleep chaos hook rides this too)
                rt = fe.tracer.start(
                    self.headers.get(reqtrace.TRACE_HEADER))
                t0 = time.perf_counter()
                fwd = {k: v for k, v in self.headers.items()
                       if k.lower().startswith("x-tmog-")}
                status = None
                err: Optional[str] = None
                try:
                    try:
                        status, data = fe.forward_score(body, trace=rt,
                                                        headers=fwd)
                    except FleetUnavailable as e:
                        status, err = e.status, "FleetUnavailable"
                        self._reply(status,
                                    {"error": str(e),
                                     "error_type": "FleetUnavailable"},
                                    trace_header=self._trace_echo(fe,
                                                                  rt))
                    except TimeoutError as e:
                        status, err = 504, "TimeoutError"
                        self._reply(504, {"error": str(e)},
                                    trace_header=self._trace_echo(fe,
                                                                  rt))
                    else:
                        t1 = time.perf_counter()
                        self._reply(status, None, raw=data,
                                    trace_header=self._trace_echo(fe,
                                                                  rt))
                        if rt is not None:
                            rt.seg("respond",
                                   time.perf_counter() - t1)
                except OSError:
                    # client hung up mid-reply: still worth keeping
                    err = err or "ClientDisconnect"
                    raise
                finally:
                    # finish on EVERY exit (incl. a failed reply write)
                    # or in_flight leaks and the trace is dropped
                    fe.tracer.finish(rt, time.perf_counter() - t0,
                                     status=status, error_type=err)
            elif self.path == "/rollout":
                doc = json.loads(body or b"{}")
                out = fe.start_rollout(
                    str(doc["model_dir"]),
                    fraction=float(doc.get("fraction", 0.2)),
                    min_shadow=int(doc.get("min_shadow", 256)),
                    replicas=doc.get("replicas"))
                self._reply(200, out)
            elif self.path == "/rollout/abort":
                if fe.rollout is None:
                    self._reply(404, {"error": "no rollout manager"})
                else:
                    fe.rollout.abort()
                    self._reply(200, fe.rollout.status())
            elif self.path == "/retrain":
                if fe.retrain is None:
                    self._reply(404, {"error": "no retrain controller "
                                               "configured for this "
                                               "fleet"})
                else:
                    doc = json.loads(body or b"{}")
                    out = fe.start_retrain(
                        force=bool(doc.get("force", False)))
                    self._reply(200, out)
            elif self.path == "/drain":
                # REST-proper alias of GET /drain (which the fleet keeps
                # for parity with the replica endpoint + curl ergonomics)
                self._reply(200, fe.drain())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})
        except (json.JSONDecodeError, KeyError, ValueError) as e:
            self._reply(400, {"error": f"{type(e).__name__}: {e}"})
        except (RolloutConflict, RetrainConflict) as e:
            # retryable: another rollout/retrain holds the slot NOW —
            # same 409 contract for both loops
            self._reply(409, {"error": str(e)})
        except Exception as e:
            # incl. challenger STARTUP failures (broken artifact, prewarm
            # rc != 0): a 409 would invite retry loops against a model
            # that can never come up
            _log.exception("fleet: POST %s failed", self.path)
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})


def make_fleet_server(frontend: FleetFrontend, host: str = "127.0.0.1",
                      port: int = 0) -> ThreadingHTTPServer:
    httpd = ThreadingHTTPServer((host, port), _FleetHandler)
    httpd.daemon_threads = True
    httpd.frontend = frontend  # type: ignore[attr-defined]
    return httpd


# -- the `fleet` CLI body -----------------------------------------------------

def run_fleet(args: Any) -> int:
    """Body of ``python -m transmogrifai_tpu fleet`` (cli.py parses):
    prewarm-if-needed, spawn N replicas, route until SIGTERM, drain."""
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(name)s %(levelname)s %(message)s")

    metrics_loc = getattr(args, "metrics_location", None) or \
        os.path.join(args.model_dir, "fleet_metrics")
    os.makedirs(metrics_loc, exist_ok=True)
    collector.enable("fleet")
    collector.attach_event_log(os.path.join(metrics_loc, "events.jsonl"))

    serve_args: List[str] = []
    if getattr(args, "max_batch", None):
        serve_args += ["--max-batch", str(args.max_batch)]
    if getattr(args, "buckets", None):
        serve_args += ["--buckets", str(args.buckets)]
    if getattr(args, "max_wait_ms", None) is not None:
        serve_args += ["--max-wait-ms", str(args.max_wait_ms)]
    if getattr(args, "max_queue", None):
        serve_args += ["--max-queue", str(args.max_queue)]
    if getattr(args, "single_record", None):
        serve_args += ["--single-record", args.single_record]
    if getattr(args, "monitor", None):
        serve_args += ["--monitor", args.monitor]
    if getattr(args, "request_trace", None):
        serve_args += ["--request-trace", args.request_trace]
    if getattr(args, "trace_sample", None) is not None:
        serve_args += ["--trace-sample", str(args.trace_sample)]

    lock = threading.RLock()
    supervisor = Supervisor(
        args.model_dir, replicas=int(args.replicas), lock=lock,
        metrics_root=os.path.join(metrics_loc, "replicas"),
        host=getattr(args, "replica_host", "127.0.0.1"),
        serve_args=serve_args,
        max_restarts=int(getattr(args, "max_restarts", 20)))
    router = Router(lock, request_timeout=float(
        getattr(args, "request_timeout_s", 30.0)),
        tracer=ReqTracer(
            "router", origin="router",
            enabled=(getattr(args, "request_trace", "on") != "off"
                     and reqtrace.env_enabled()),
            sample_rate=getattr(args, "trace_sample", None)))

    profile = policy = None
    if getattr(args, "monitor", "auto") != "off":
        doc = load_monitor_profile(args.model_dir)
        if doc is not None:
            try:
                profile = ReferenceProfile.from_json(doc)
                policy = DriftPolicy()
            except Exception:
                _log.exception("fleet: unusable monitor.json; pooled "
                               "/drift disabled")

    try:
        router.set_champions(supervisor.start())
    except Exception:
        _log.exception("fleet: startup failed")
        supervisor.stop()
        collector.detach_event_log()
        collector.disable()
        return 1
    prober = HealthProber(router, interval_s=float(
        getattr(args, "probe_interval_s", 0.5))).start()
    # score-comparison bounds pinned at construction (the shadow worker
    # reads them on its own thread): the champion's prediction profile
    # when it has one, else the [0, 1] probability default
    pred = profile.prediction if profile is not None else None
    rollout = RolloutManager(
        supervisor, router, lock=lock,
        score_lo=pred.lo if pred else 0.0,
        score_hi=pred.hi if pred else 1.0,
        score_field=pred.field if pred else None)
    # drift-triggered continuous retraining (docs/retraining.md):
    # --retrain auto wires a RetrainController when the model ships a
    # retrain.json recipe; its trigger source is the fleet's own pooled
    # /drift verdict (window_id + model hash ride the payload). Built
    # BEFORE the frontend so the controller rides its constructor
    # (construction happens-before the HTTP threads that read it);
    # the poll closure binds `frontend` late — start() runs after the
    # frontend exists.
    retrain_ctl = None
    if getattr(args, "retrain", "off") == "auto":
        from ..retrain import controller as RC
        from ..retrain.refit import load_recipe

        def _champion_dir() -> Optional[str]:
            with router.lock:
                pool = router.champions
                return pool[0].model_dir if pool else None

        def _pooled_drift():
            return frontend.drift()

        recipe_doc = load_recipe(args.model_dir)
        if recipe_doc is None:
            _log.warning("fleet: --retrain auto but %s has no "
                         "retrain.json recipe; controller disabled",
                         args.model_dir)
        else:
            # the recipe's rollout_* verdict relaxation is applied by
            # the controller PER retrain rollout (start(thresholds=)),
            # never to the shared manager — operator-initiated
            # POST /rollout keeps the fleet's base guards
            retrain_ctl = RC.RetrainController(
                _champion_dir,
                root=os.path.join(metrics_loc, "retrain"),
                rollout=rollout,
                # the controller keeps the recipe: after a swap the
                # champion dir is the CANDIDATE dir (the worker copies
                # retrain.json into it too, but the handed recipe makes
                # cycle 2 independent of that copy — continuous, not
                # one-shot)
                recipe=recipe_doc,
                policy=RC.RetrainPolicy(
                    min_interval_s=float(getattr(
                        args, "retrain_min_interval_s", 60.0)),
                    max_retrains_per_window=int(getattr(
                        args, "retrain_max_per_window", 4)),
                    fit_timeout_s=float(getattr(
                        args, "retrain_fit_timeout_s", 900.0))),
                drift_poll=_pooled_drift,
                drift_poll_interval_s=float(getattr(
                    args, "retrain_poll_interval_s", 2.0)),
                env=dict(supervisor.env))

    frontend = FleetFrontend(supervisor, router, rollout,
                             profile=profile, policy=policy,
                             retrain=retrain_ctl)
    if retrain_ctl is not None:
        retrain_ctl.start()
        _log.info("fleet: retrain controller armed (journal under %s)",
                  retrain_ctl.root)

    gauge_sampler = GaugeSampler(frontend.sample_gauges,
                                 ring=frontend.gauges).start()
    httpd = make_fleet_server(frontend, host=args.host, port=args.port)
    host, port = httpd.server_address[:2]
    _log.info("fleet: %d replica(s) of %s behind http://%s:%s",
              int(args.replicas), args.model_dir, host, port)

    def _graceful(signum: int, frame: Any) -> None:
        _log.info("fleet: signal %s — draining and shutting down", signum)
        frontend.drain()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    def _drain_signal(signum: int, frame: Any) -> None:
        frontend.drain()

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
        if hasattr(signal, "SIGUSR1"):
            signal.signal(signal.SIGUSR1, _drain_signal)
    except ValueError:  # not on the main thread (tests drive in-process)
        pass

    try:
        httpd.serve_forever(poll_interval=0.1)
    finally:
        httpd.server_close()
        gauge_sampler.stop()
        prober.stop()
        if retrain_ctl is not None:
            retrain_ctl.close()
        if rollout is not None:
            rollout.abort()
        supervisor.stop(router=router)
        frontend.close()
        collector.save(os.path.join(metrics_loc,
                                    "fleet_stage_metrics.json"))
        collector.save_chrome_trace(os.path.join(metrics_loc,
                                                 "fleet_trace.json"))
        collector.detach_event_log()
        collector.disable()
        _log.info("fleet: drained; router served %d request(s), "
                  "%d retried, %d shed", router.n_requests,
                  router.n_retries, router.n_shed)
    return 0
