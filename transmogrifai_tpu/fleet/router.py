"""Front router: spread ``POST /score`` over healthy serving replicas.

Same discipline as ``serve/frontend.py`` — stdlib only, transport thin,
logic testable in-process. The router owns a pool of
:class:`ReplicaHandle` objects (shared with the supervisor, which owns
the PROCESSES behind them) and for every request picks the healthy,
non-draining champion with the fewest outstanding requests
(least-outstanding-requests beats round-robin under heterogeneous
latency: a replica stuck compiling or GC-ing accumulates outstanding
and stops being selected).

Failure semantics, in order:

- CONNECTION error (refused/reset — the replica died mid-request): mark
  the replica unhealthy, retry ONCE on a different replica. Scoring is
  idempotent, so the retry can never corrupt state; the health prober
  brings the replica back when it answers /healthz again.
- HTTP 503 from a replica (its admission queue shed, or it is
  draining): try the remaining healthy replicas; when EVERY replica
  sheds, the fleet itself sheds (fleet-level 503 + ``fleet_shed``
  event) — backpressure propagates instead of queueing unboundedly.
- TIMEOUT: returned to the caller as 504, never retried (the request
  may still be executing; a retry would double the load exactly when
  the fleet is slowest).

Lock ownership: one fleet-wide RLock (``Router.lock``) guards every
mutable ReplicaHandle field and the pool lists; it is NEVER held across
a network call — pick under the lock, request outside it, account under
it again (docs/fleet.md "Lock ownership").
"""
from __future__ import annotations

import http.client
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..serve import reqtrace
from ..serve.reqtrace import ReqTracer, RequestTrace
from ..utils.metrics import LatencyHistogram, collector

_log = logging.getLogger("transmogrifai_tpu.fleet")

Record = Dict[str, Any]

#: connection-class failures that justify the one retry (the replica
#: process is gone or the socket broke; the request never completed on
#: the fleet's side). TimeoutError is deliberately ABSENT.
CONN_ERRORS = (ConnectionError, http.client.HTTPException, OSError)


class FleetUnavailable(RuntimeError):
    """No replica could take the request (fleet-level shed or every
    replica unreachable). Carries the HTTP status the frontend maps to:
    503 when replicas shed load, 502 when none answered at all."""

    def __init__(self, status: int, detail: str):
        self.status = status
        super().__init__(detail)


class ReplicaHandle:
    """One replica slot: identity + mutable runtime state.

    The supervisor owns the PROCESS (spawn/restart/stop) and rewrites
    ``host``/``port``/``healthy`` across incarnations; the router owns
    routing state (``outstanding``). Every mutable field is guarded by
    the one fleet lock both sides share."""

    def __init__(self, index: int, model_dir: str, pool: str = "champion",
                 host: str = "127.0.0.1", port: int = 0):
        self.index = index
        self.model_dir = model_dir
        self.pool = pool
        self.host = host
        self.port = port
        self.proc: Any = None
        self.metrics_dir: Optional[str] = None
        self.incarnation = 0
        self.restarts = 0
        self.healthy = False
        self.draining = False
        self.stopping = False
        self.outstanding = 0
        self.last_pick = 0
        self.last_error: Optional[str] = None

    @property
    def name(self) -> str:
        return f"{self.pool}-{self.index}"

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "url": self.url, "pool": self.pool,
                "model_dir": self.model_dir, "healthy": self.healthy,
                "draining": self.draining, "outstanding": self.outstanding,
                "incarnation": self.incarnation, "restarts": self.restarts,
                "last_error": self.last_error}


def http_exchange(host: str, port: int, method: str, path: str,
                  body: Optional[bytes] = None, timeout: float = 30.0,
                  headers: Optional[Dict[str, str]] = None
                  ) -> Tuple[int, bytes, Dict[str, str]]:
    """One HTTP exchange; returns (status, raw body, response headers).
    `headers` ride the request — the router propagates the
    ``X-Tmog-Trace`` hop context through here, and the replica's echo
    (carrying its replica id) comes back in the third element. Raises
    the CONN_ERRORS family on transport failure and TimeoutError when
    the replica accepted but did not answer in time."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        hdrs = dict(headers or {})
        if body and "Content-Type" not in hdrs:
            hdrs["Content-Type"] = "application/json"
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def http_json(host: str, port: int, method: str, path: str,
              body: Optional[bytes] = None, timeout: float = 30.0
              ) -> Tuple[int, bytes]:
    """One HTTP exchange against a replica; returns (status, raw body).
    Raises the CONN_ERRORS family on transport failure and TimeoutError
    when the replica accepted but did not answer in time."""
    status, data, _ = http_exchange(host, port, method, path, body=body,
                                    timeout=timeout)
    return status, data


def get_json(host: str, port: int, path: str,
             timeout: float = 5.0) -> Optional[Any]:
    """GET a JSON document off a replica address; None on any failure
    (telemetry polls must never take the fleet down). Callers snapshot
    ``handle.host``/``handle.port`` under the fleet lock first — a
    restart may be rewriting the port on another thread."""
    try:
        status, data = http_json(host, port, "GET", path,
                                 timeout=timeout)
        if status not in (200, 503):  # 503 healthz still carries JSON
            return None
        return json.loads(data)
    except CONN_ERRORS + (TimeoutError, json.JSONDecodeError, ValueError):
        return None


class Router:
    """Least-outstanding-requests spread over the champion pool, with
    the failure semantics in the module docstring. `shadow_hook` (set by
    fleet/rollout while a rollout is in SHADOW state) receives
    ``(record, response_row)`` for a sampled fraction of successful
    single-record requests — always AFTER the champion response is
    final, never on its latency path."""

    def __init__(self, lock: Optional[threading.RLock] = None, *,
                 request_timeout: float = 30.0,
                 tracer: Optional[ReqTracer] = None):
        #: THE fleet lock (shared with the Supervisor + RolloutManager)
        self.lock = lock or threading.RLock()
        self.request_timeout = float(request_timeout)
        self.champions: List[ReplicaHandle] = []
        self.challengers: List[ReplicaHandle] = []
        self.hist = LatencyHistogram("fleet_router")
        self.n_requests = 0
        self.n_retries = 0
        self.n_shed = 0
        self.shadow_hook: Optional[Callable[[Record, Record], None]] = None
        self.shadow_fraction = 0.0
        self._pick_seq = 0
        #: router-side request tracer (reqtrace; set by FleetFrontend /
        #: run_fleet): mints the trace id the X-Tmog-Trace header
        #: carries to the replica, records route/upstream segments
        self.tracer = tracer

    # -- pool management ---------------------------------------------------
    def set_champions(self, handles: List[ReplicaHandle]) -> None:
        with self.lock:
            self.champions = list(handles)

    def set_challengers(self, handles: List[ReplicaHandle]) -> None:
        with self.lock:
            self.challengers = list(handles)

    def swap_pools(self) -> List[ReplicaHandle]:
        """THE atomic champion/challenger swap (fleet/rollout calls on a
        clean verdict): one assignment under the fleet lock. Requests
        already routed keep their old handle and finish on it (the old
        processes stay up until drained); every pick after this instant
        sees only the new champions. Returns the retired pool."""
        with self.lock:
            old = self.champions
            self.champions = self.challengers
            for h in self.champions:
                h.pool = "champion"
            self.challengers = []
            self.shadow_hook = None
            self.shadow_fraction = 0.0
            return old

    def replicas(self) -> List[ReplicaHandle]:
        with self.lock:
            return list(self.champions) + list(self.challengers)

    def healthy_count(self) -> int:
        with self.lock:
            return sum(1 for h in self.champions
                       if h.healthy and not h.draining and not h.stopping)

    # -- routing -----------------------------------------------------------
    def _pick(self, exclude: set
              ) -> Optional[Tuple[ReplicaHandle, str, int]]:
        """(handle, host, port) of the chosen replica — the address is
        snapshotted under the lock because a supervisor restart rewrites
        the port on its own thread."""
        with self.lock:
            ready = [h for h in self.champions
                     if h.healthy and not h.draining and not h.stopping
                     and h.name not in exclude]
            if not ready:
                return None
            # least-outstanding, ties broken least-recently-picked: an
            # idle fleet round-robins instead of hammering replica 0
            h = min(ready, key=lambda r: (r.outstanding, r.last_pick))
            h.outstanding += 1
            self._pick_seq += 1
            h.last_pick = self._pick_seq
            return h, h.host, h.port

    def _done(self, h: ReplicaHandle) -> None:
        with self.lock:
            h.outstanding = max(h.outstanding - 1, 0)

    def _mark_conn_failure(self, h: ReplicaHandle, err: str) -> None:
        with self.lock:
            h.healthy = False
            h.last_error = err
        _log.warning("fleet: replica %s connection failure (%s); "
                     "marked unhealthy, retrying elsewhere", h.name, err)

    def forward_score(self, body: bytes, *,
                      trace: Optional[RequestTrace] = None,
                      headers: Optional[Dict[str, str]] = None
                      ) -> Tuple[int, bytes]:
        """Route one /score body to a champion. Returns (status, body)
        to pass through verbatim; raises FleetUnavailable when no
        replica could take it.

        `trace` (reqtrace, owned + finished by the CALLER — the fleet
        frontend, which still has the respond segment to stamp) gets the
        router segments: `route` (pick wall), `upstream` (replica
        exchange wall, summed across a retry), the retry count, and the
        serving replica id read from the X-Tmog-Trace echo. `headers`
        pass through to the replica — the hop-context header plus any
        client-supplied X-Tmog-* headers the frontend forwards."""
        t0 = time.perf_counter()
        tried: set = set()
        conn_failures = 0
        saw_shed = False
        pick_s = 0.0
        upstream_s = 0.0
        fwd_headers = dict(headers or {})
        if trace is not None:
            fwd_headers[reqtrace.TRACE_HEADER] = trace.trace_id
        try:
            while True:
                tp = time.perf_counter()
                picked = self._pick(tried)
                pick_s += time.perf_counter() - tp
                if picked is None:
                    break
                h, host, port = picked
                tried.add(h.name)
                tu = time.perf_counter()
                try:
                    status, data, rhead = http_exchange(
                        host, port, "POST", "/score", body=body,
                        timeout=self.request_timeout,
                        headers=fwd_headers)
                except TimeoutError:
                    upstream_s += time.perf_counter() - tu
                    self._done(h)
                    if trace is not None:
                        # caller-thread-owned record (reqtrace contract)
                        trace.replica = h.name  # tmoglint: disable=THR001
                    raise
                except CONN_ERRORS as e:
                    upstream_s += time.perf_counter() - tu
                    self._done(h)
                    self._mark_conn_failure(h, f"{type(e).__name__}: {e}")
                    conn_failures += 1
                    if conn_failures > 1:
                        break  # retry-ONCE: two dead sockets end it
                    with self.lock:
                        self.n_retries += 1
                    if trace is not None:
                        # caller-thread-owned record (reqtrace contract)
                        trace.retries += 1  # tmoglint: disable=THR001
                    collector.event("fleet_retry", replica=h.name,
                                    error=type(e).__name__)
                    continue
                upstream_s += time.perf_counter() - tu
                self._done(h)
                if status == 503:
                    # the replica shed (queue full) or is mid-drain: its
                    # refusal is not the fleet's — spread to the rest
                    saw_shed = True
                    continue
                self.hist.record(time.perf_counter() - t0)
                with self.lock:
                    self.n_requests += 1
                    hook, frac = self.shadow_hook, self.shadow_fraction
                if trace is not None:
                    # the serving replica NAMES ITSELF via the header
                    # echo; the handle name is the fallback (old
                    # replicas, stripped proxies). The trace is the
                    # calling request thread's own record (reqtrace
                    # single-owner contract)
                    _, attrs = reqtrace.parse_trace_header(
                        (rhead or {}).get(reqtrace.TRACE_HEADER))
                    trace.replica = attrs.get("replica") or h.name  # tmoglint: disable=THR001
                if hook is not None and status == 200:
                    self._maybe_shadow(hook, frac, body, data, trace)
                return status, data
            if saw_shed:
                with self.lock:
                    self.n_shed += 1
                    total = self.n_shed
                collector.event("fleet_shed", shed_total=total,
                                replicas_tried=len(tried))
                if trace is not None:
                    # caller-thread-owned record (reqtrace contract)
                    trace.shed = True  # tmoglint: disable=THR001
                raise FleetUnavailable(
                    503,
                    "every replica shed the request (fleet overloaded)")
            raise FleetUnavailable(
                502 if conn_failures else 503,
                f"no healthy replica available "
                f"({conn_failures} connection failure(s), "
                f"{len(tried)} tried)")
        finally:
            # segments stamp on EVERY exit (success, shed, timeout,
            # no-replica): the caller finishes the trace with the
            # status it replies with
            if trace is not None:
                trace.seg("route", pick_s)
                if upstream_s:
                    trace.seg("upstream", upstream_s)

    def _maybe_shadow(self, hook: Callable[[bytes, bytes], Any],
                      fraction: float, body: bytes, data: bytes,
                      trace: Optional[RequestTrace] = None) -> None:
        """Sample this request into the rollout's shadow stream: one
        random() and one bounded-queue put of the RAW bytes — parsing
        and challenger scoring happen on the rollout's worker thread,
        so the request path pays effectively nothing. The rollout
        worker discards bulk (list) bodies; only single-record requests
        count as live traffic. A DROPPED mirror (queue full — the hook
        returns False) marks the trace so the tail sampler keeps it:
        shadow starvation under load is exactly a tail event worth a
        kept trace."""
        import random
        if fraction <= 0.0 or random.random() >= fraction:
            return
        if hook(body, data) is False and trace is not None:
            # caller-thread-owned record (reqtrace contract)
            trace.shadow_dropped = True  # tmoglint: disable=THR001

    # -- drain coordination ------------------------------------------------
    def remove(self, handles: List[ReplicaHandle]) -> None:
        """Take handles out of both pools (no new picks; in-flight
        requests still hold their references and finish)."""
        gone = {h.name for h in handles}
        with self.lock:
            self.champions = [h for h in self.champions
                              if h.name not in gone]
            self.challengers = [h for h in self.challengers
                                if h.name not in gone]

    def wait_drained(self, handles: List[ReplicaHandle],
                     timeout: float = 30.0) -> bool:
        """Block until every handle's outstanding count reaches zero
        (rolling-restart coordination: remove() first, then this, then
        stop the process). True when fully drained."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.lock:
                left = sum(h.outstanding for h in handles)
            if left == 0:
                return True
            time.sleep(0.02)
        return False

    # -- health probing ----------------------------------------------------
    def probe_once(self) -> None:
        """One health sweep: GET /healthz per replica, state updated
        under the lock AFTER the request returns. The prober is also the
        recovery path for replicas the forwarder marked unhealthy."""
        for h in self.replicas():
            with self.lock:
                if h.stopping or h.proc is None and h.port == 0:
                    continue
                host, port = h.host, h.port
            doc = None
            try:
                status, data = http_json(host, port, "GET", "/healthz",
                                         timeout=2.0)
                doc = json.loads(data)
            except CONN_ERRORS + (TimeoutError, json.JSONDecodeError,
                                  ValueError):
                status = None
            with self.lock:
                was = h.healthy
                if doc is None:
                    h.healthy = False
                else:
                    h.draining = bool(doc.get("draining"))
                    h.healthy = (status == 200
                                 and doc.get("status") == "ok")
                now = h.healthy
            if was != now:
                _log.info("fleet: replica %s -> %s", h.name,
                          "healthy" if now else "unhealthy")


class HealthProber:
    """Background /healthz sweep at a fixed interval (daemon thread)."""

    def __init__(self, router: Router, interval_s: float = 0.5):
        self.router = router
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-prober", daemon=True)

    def start(self) -> "HealthProber":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(5.0)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.router.probe_once()
            except Exception:  # a probe bug must not kill health-keeping
                _log.exception("fleet: health probe sweep failed")
            self._stop.wait(self.interval_s)
