"""Serving FLEET: replicas, routing, merged telemetry, zero-downtime
rollout (docs/fleet.md).

PR 7/9 built one excellent serving replica (serve/ + monitor/); millions
of users need N of them, operated. This package turns one serving
process into a fleet:

- :mod:`supervisor` — N ``serve`` worker PROCESSES from one model dir,
  all sharing one ``TMOG_COMPILE_CACHE_DIR`` and the ``serve.json``
  prewarm manifest (the FLEET CONTRACT: a replica refuses to join when
  its model hash or bucket ladder disagrees), restart-on-crash with
  exponential backoff, and the compile-free-rejoin check read off the
  RecompileTracker counters;
- :mod:`router` — least-outstanding-requests spread over healthy
  replicas, per-replica /healthz probing, retry-once on connection
  error, fleet-level load shed when every replica sheds, drain
  coordination for rolling restarts;
- :mod:`telemetry` — fleet ``/metrics`` and ``/drift`` that MERGE
  per-replica state: latency histograms by exact bucket sum, monitor
  window sketches pooled before ONE DriftPolicy verdict (the DrJAX
  MapReduce shape applied host-side across processes);
- :mod:`rollout` — champion/challenger: model v2 loads BESIDE v1, a
  fraction of live traffic shadow-scores on v2 (responses always from
  v1), the drift engine compares the two prediction distributions, and
  a clean verdict atomically swaps the pools — a bad challenger tears
  down without a dropped request;
- :mod:`frontend` — the fleet HTTP server + the
  ``python -m transmogrifai_tpu fleet <model_dir> --replicas N`` CLI.

The loop closes one layer up: ``--retrain auto`` arms a
:class:`~transmogrifai_tpu.retrain.RetrainController` that tails the
fleet's pooled ``/drift`` verdict and drives drift -> refit -> validate
-> this package's rollout path (docs/retraining.md).
"""
from .frontend import FleetFrontend, make_fleet_server, run_fleet
from .rollout import RolloutConflict, RolloutManager
from .router import (FleetUnavailable, HealthProber, ReplicaHandle,
                     Router)
from .supervisor import Supervisor
from .telemetry import fleet_drift, fleet_metrics, merge_window_states

__all__ = [
    "FleetFrontend", "FleetUnavailable", "HealthProber", "ReplicaHandle",
    "RolloutConflict", "RolloutManager", "Router", "Supervisor",
    "fleet_drift", "fleet_metrics", "make_fleet_server",
    "merge_window_states", "run_fleet",
]
