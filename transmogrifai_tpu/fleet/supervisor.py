"""Replica supervisor: N ServingEngine worker PROCESSES from one model.

Each replica is a real ``python -m transmogrifai_tpu serve`` subprocess
— its own interpreter, its own XLA client, its own GIL — so the fleet
scales past one process's HTTP/assembly ceiling and a crash takes down
exactly one replica. What makes N processes cheap is the PR 7 prewarm
contract: every replica shares one ``TMOG_COMPILE_CACHE_DIR`` and adopts
the ``serve.json`` manifest written by ``serve --prewarm-only``, so
replica N+1 (and every supervisor RESTART) starts with ZERO true XLA
compiles — persistent-cache hits only. The supervisor enforces that
contract end to end:

- it runs ``serve --prewarm-only`` itself when the manifest is missing
  (populating the shared cache before the first replica spawns);
- replicas run ``--strict-manifest``: a replica whose model hash or
  bucket ladder disagrees with the manifest REFUSES to join (exit 2)
  instead of silently compiling a divergent ladder;
- after every restart it reads the replica's ``/metrics`` ``prewarm``
  block (the RecompileTracker counters, not log lines) and records a
  ``fleet_replica_up`` event carrying ``prewarm_compiles`` — the chaos
  pin asserts 0 there.

Crash handling: a watch thread polls child processes; a dead replica
emits ``fleet_replica_down``, then restarts with exponential backoff on
a FRESH port (the old port may linger in TIME_WAIT). Backoff doubles
per consecutive crash and resets after a healthy join, so a crash-loop
replica cannot melt the host while the rest of the fleet serves.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..utils.metrics import collector
from ..workflow.io import load_serve_manifest, verify_serve_manifest
from .router import CONN_ERRORS, ReplicaHandle, get_json, http_json

_log = logging.getLogger("transmogrifai_tpu.fleet")


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port that was free a moment ago. The bind/close gap
    is a real (tiny) race; replica spawn treats a failed bind as a crash
    and restarts on a fresh port, so the race self-heals."""
    s = socket.socket()
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


class Supervisor:
    """Own the replica processes of one fleet.

    `serve_args` is the pass-through list of extra ``serve`` CLI flags
    every replica gets (``["--max-batch", "64", "--monitor", "off"]``
    style). `metrics_root` (required) holds one subdirectory per replica
    INCARNATION — ``replica-0/r0``, ``replica-0/r1`` after one restart —
    each with its own events.jsonl + trace artifacts, because a kill -9
    never flushes the dying incarnation's files and the restarted one
    must not append to a half-written log. The fleet lock is shared with
    the Router so handle state has exactly one guard."""

    def __init__(self, model_dir: str, *, replicas: int = 2,
                 lock: Optional[threading.RLock] = None,
                 metrics_root: str,
                 host: str = "127.0.0.1",
                 serve_args: Sequence[str] = (),
                 env: Optional[Dict[str, str]] = None,
                 python: str = sys.executable,
                 startup_timeout_s: float = 180.0,
                 max_restarts: int = 20,
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 10.0):
        self.model_dir = model_dir
        self.n_replicas = int(replicas)
        self.lock = lock or threading.RLock()
        self.metrics_root = metrics_root
        self.host = host
        self.serve_args = list(serve_args)
        self.env = dict(os.environ)
        if env:
            self.env.update(env)
        self.python = python
        self.startup_timeout_s = float(startup_timeout_s)
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.handles: List[ReplicaHandle] = []
        self.rejoin_violations = 0
        self._next_index = 0
        self._stop = threading.Event()
        self._watch: Optional[threading.Thread] = None
        os.makedirs(metrics_root, exist_ok=True)
        if not self.env.get("TMOG_COMPILE_CACHE_DIR"):
            # the zero-compile rejoin contract NEEDS a shared persistent
            # cache; default one under the fleet's own metrics root
            # rather than silently running without
            cache = os.path.join(metrics_root, "compile_cache")
            self.env["TMOG_COMPILE_CACHE_DIR"] = cache
            _log.warning("fleet: TMOG_COMPILE_CACHE_DIR was unset; using "
                         "%s so replicas share one persistent cache",
                         cache)

    # -- manifest / prewarm -------------------------------------------------
    def ensure_manifest(self, model_dir: Optional[str] = None) -> Dict:
        """Make sure `model_dir` carries a FRESH serve.json manifest,
        running ``serve --prewarm-only`` in a child when it is missing
        OR stale (the deploy step, automated). Returns the manifest.
        This is what makes every subsequent replica start compile-free:
        the prewarm child populates the SHARED persistent cache with
        every ladder rung. Freshness matters because replicas run
        --strict-manifest: handing them a stale manifest (model
        re-saved since the last prewarm) would make every one refuse to
        join with only a generic failed-to-start error."""
        model_dir = model_dir or self.model_dir
        manifest = load_serve_manifest(model_dir)
        if manifest is not None:
            stale = verify_serve_manifest(model_dir, manifest)
            if not stale:
                return manifest
            _log.warning("fleet: serve.json under %s is STALE (%s) — "
                         "re-running the prewarm so replicas can join",
                         model_dir, "; ".join(stale))
        else:
            _log.info("fleet: no serve.json under %s", model_dir)
        cmd = [self.python, "-m", "transmogrifai_tpu", "serve", model_dir,
               "--prewarm-only"] + self.serve_args
        _log.info("fleet: running the prewarm: %s", " ".join(cmd))
        proc = subprocess.run(cmd, env=self.env, capture_output=True,
                              text=True, timeout=self.startup_timeout_s * 2)
        if proc.returncode != 0:
            raise RuntimeError(
                f"fleet: `serve --prewarm-only` failed rc="
                f"{proc.returncode}: {proc.stderr[-800:]}")
        manifest = load_serve_manifest(model_dir)
        if manifest is None:
            raise RuntimeError(f"fleet: prewarm wrote no serve.json "
                               f"under {model_dir}")
        return manifest

    # -- spawning -----------------------------------------------------------
    def _spawn_cmd(self, handle: ReplicaHandle) -> List[str]:
        return ([self.python, "-m", "transmogrifai_tpu", "serve",
                 handle.model_dir, "--host", self.host,
                 "--port", str(handle.port),
                 "--metrics-location", handle.metrics_dir,
                 # fleet-assigned identity: the replica echoes it in the
                 # X-Tmog-Trace header + stamps it on every kept trace,
                 # so a router-side record names the serving replica
                 "--replica-id", handle.name,
                 "--strict-manifest"] + self.serve_args)

    def _spawn(self, handle: ReplicaHandle) -> None:
        """Start one incarnation (no lock held: subprocess spawn and the
        port probe both touch the OS)."""
        port = free_port(self.host)
        with self.lock:
            restarts = handle.restarts
        inc_dir = os.path.join(self.metrics_root, handle.name,
                               f"r{restarts}")
        os.makedirs(inc_dir, exist_ok=True)
        log_path = os.path.join(inc_dir, "replica.log")
        with self.lock:
            handle.port = port
            handle.metrics_dir = inc_dir
            handle.incarnation = restarts
            handle.healthy = False
            handle.draining = False
            cmd = self._spawn_cmd(handle)  # address read under the lock
        with open(log_path, "ab") as lf:
            proc = subprocess.Popen(cmd, env=self.env,
                                    stdout=lf, stderr=lf)
        with self.lock:
            handle.proc = proc
        _log.info("fleet: spawned %s pid=%d port=%d (incarnation %d)",
                  handle.name, proc.pid, port, handle.incarnation)

    def _wait_healthy(self, handle: ReplicaHandle,
                      timeout: Optional[float] = None) -> bool:
        """Poll /healthz until the replica reports ok (model loaded,
        prewarm done, HTTP up) or its process dies."""
        deadline = time.monotonic() + (timeout or self.startup_timeout_s)
        while time.monotonic() < deadline:
            with self.lock:
                proc, host, port = handle.proc, handle.host, handle.port
            if proc is not None and proc.poll() is not None:
                return False  # died during startup (strict manifest etc.)
            try:
                status, data = http_json(host, port, "GET", "/healthz",
                                         timeout=2.0)
                if status == 200 and \
                        json.loads(data).get("status") == "ok":
                    with self.lock:
                        handle.healthy = True
                    return True
            except CONN_ERRORS + (TimeoutError, json.JSONDecodeError,
                                  ValueError):
                pass
            time.sleep(0.1)
        return False

    def _note_up(self, handle: ReplicaHandle) -> None:
        """fleet_replica_up + the compile-free-(re)join check: read the
        prewarm block the engine serves under /metrics (RecompileTracker
        counters) and flag any true compile a rejoin performed."""
        with self.lock:
            host, port = handle.host, handle.port
            restarts, incarnation = handle.restarts, handle.incarnation
        m = get_json(host, port, "/metrics") or {}
        prewarm = m.get("prewarm") or {}
        compiles = prewarm.get("compiles")
        cache_hits = prewarm.get("cache_hits")
        if restarts > 0 and isinstance(compiles, int) and compiles > 0:
            with self.lock:
                self.rejoin_violations += 1
            _log.warning(
                "fleet: %s REJOINED WITH %d TRUE XLA COMPILE(S) — the "
                "shared persistent cache missed (stale manifest? cache "
                "dir wiped?)", handle.name, compiles)
        collector.event("fleet_replica_up", replica=handle.name,
                        url=f"http://{host}:{port}",
                        incarnation=incarnation, restarts=restarts,
                        prewarm_compiles=compiles,
                        prewarm_cache_hits=cache_hits)

    def start(self) -> List[ReplicaHandle]:
        """Ensure the manifest, spawn the champion pool, wait for every
        replica to join, start the crash watch. Returns the handles (the
        Router takes the same list)."""
        self.ensure_manifest()
        new = self.spawn_pool(self.model_dir, self.n_replicas,
                              pool="champion")
        self._watch = threading.Thread(target=self._watch_loop,
                                       name="fleet-supervisor",
                                       daemon=True)
        self._watch.start()
        return new

    def spawn_pool(self, model_dir: str, n: int,
                   pool: str = "champion") -> List[ReplicaHandle]:
        """Spawn n replicas of `model_dir` and wait until ALL are
        healthy; raises (and tears the new pool down) when any fails to
        join — half a pool is not a pool."""
        batch: List[ReplicaHandle] = []
        with self.lock:
            for _ in range(n):
                h = ReplicaHandle(self._next_index, model_dir, pool=pool,
                                  host=self.host)
                self._next_index += 1
                self.handles.append(h)
                batch.append(h)
        for h in batch:
            self._spawn(h)
        failed = [h for h in batch if not self._wait_healthy(h)]
        if failed:
            names = [h.name for h in failed]
            self.stop_replicas(batch, drain=False)
            raise RuntimeError(f"fleet: replica(s) {names} failed to "
                               f"become healthy (see replica.log under "
                               f"{self.metrics_root})")
        for h in batch:
            self._note_up(h)
        return batch

    # -- crash watch --------------------------------------------------------
    def _watch_loop(self) -> None:
        """Poll child processes; a death is BOOKED here (proc cleared
        under the lock, so the next sweep cannot double-detect it) and
        the restart — backoff sleep + spawn + health wait, up to
        minutes — runs on its own thread: two replicas crashing
        together restart in parallel instead of the second corpse
        waiting out the first one's startup_timeout."""
        while not self._stop.is_set():
            with self.lock:
                snapshot = list(self.handles)
            for h in snapshot:
                if self._stop.is_set():
                    return
                with self.lock:
                    proc, stopping = h.proc, h.stopping
                if proc is None or stopping:
                    continue
                rc = proc.poll()
                if rc is None:
                    continue
                self._handle_crash(h, rc)
            self._stop.wait(0.2)

    def _handle_crash(self, h: ReplicaHandle, rc: int) -> None:
        with self.lock:
            h.healthy = False
            h.proc = None
            h.last_error = f"exited rc={rc}"
            h.restarts += 1
            restarts = h.restarts
        _log.warning("fleet: replica %s died rc=%s (restart %d/%d)",
                     h.name, rc, restarts, self.max_restarts)
        collector.event("fleet_replica_down", replica=h.name, rc=rc,
                        restarts=restarts)
        if restarts > self.max_restarts:
            _log.error("fleet: replica %s exceeded max_restarts=%d; "
                       "leaving it down", h.name, self.max_restarts)
            return
        threading.Thread(target=self._restart, args=(h, restarts),
                         name=f"fleet-restart-{h.name}",
                         daemon=True).start()

    def _restart(self, h: ReplicaHandle, restarts: int) -> None:
        backoff = min(self.backoff_base_s * (2 ** (restarts - 1)),
                      self.backoff_cap_s)
        # interruptible backoff: a stopping fleet must not wait out the
        # ladder before exiting
        if self._stop.wait(backoff):
            return
        with self.lock:
            if h.stopping:  # a rolling stop raced the crash
                return
        self._spawn(h)
        with self.lock:
            proc, stopping = h.proc, h.stopping
        if stopping:
            # a stop landed between the check and the spawn: the fresh
            # process must not outlive the fleet
            if proc is not None and proc.poll() is None:
                proc.terminate()
            return
        if self._wait_healthy(h):
            self._note_up(h)

    # -- stopping -----------------------------------------------------------
    def stop_replicas(self, handles: List[ReplicaHandle],
                      drain: bool = True, *,
                      router: Optional[Any] = None,
                      timeout: float = 30.0) -> None:
        """Rolling-stop coordination for a set of replicas: (1) mark
        stopping (the watch won't restart them; the router won't pick
        them), (2) optional router removal + outstanding-drain wait, (3)
        GET /drain so the replica's OWN /healthz degrades for any
        external prober, (4) SIGTERM (the replica's graceful drain path,
        which flushes its metrics artifacts), (5) SIGKILL stragglers."""
        with self.lock:
            for h in handles:
                h.stopping = True
        if router is not None:
            router.remove(handles)
            router.wait_drained(handles, timeout=timeout)
        for h in handles:
            with self.lock:
                host, port, proc = h.host, h.port, h.proc
            if drain:
                try:
                    http_json(host, port, "GET", "/drain", timeout=2.0)
                except CONN_ERRORS + (TimeoutError,):
                    pass
            if proc is not None and proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + timeout
        for h in handles:
            with self.lock:
                proc = h.proc
            if proc is None:
                continue
            try:
                proc.wait(max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                _log.warning("fleet: replica %s ignored SIGTERM; killing",
                             h.name)
                proc.kill()
                proc.wait(5.0)
            with self.lock:
                h.proc = None
                h.healthy = False
        with self.lock:
            self.handles = [h for h in self.handles if h not in handles]

    def stop(self, router: Optional[Any] = None) -> None:
        self._stop.set()
        if self._watch is not None:
            self._watch.join(5.0)
        with self.lock:
            handles = list(self.handles)
        self.stop_replicas(handles, drain=True, router=router)

    # -- chaos helper (tests / ci) ------------------------------------------
    def kill_replica(self, handle: ReplicaHandle,
                     sig: int = signal.SIGKILL) -> int:
        """kill -9 one replica (the chaos pin's hammer). Returns the
        pid. The watch thread notices the death and restarts it."""
        with self.lock:
            proc = handle.proc
        if proc is None:
            raise RuntimeError(f"{handle.name} has no live process")
        proc.send_signal(sig)
        return proc.pid
