"""Project generator CLI: ``python -m transmogrifai_tpu gen ...``.

Reference: cli module (2,369 LoC) — ``op gen --input data.csv --id id
--response label --schema schema.avsc`` builds a ready-to-run project
from a data schema (CliExec, CommandParser, SchemaSource, AvroField,
ProblemKind, ProblemSchema, ProjectGenerator/FileGenerator under
cli/src/main/scala/com/salesforce/op/cli/).

Here: a SchemaSource either parses an Avro schema (.avsc — field types
drive feature types and the problem kind, AvroField.scala semantics:
union[null, T] = nullable T, logical date/timestamp types map to
Date/DateTime) or inspects CSV/Avro DATA (type inference per column).
The generator emits a multi-file project: features.py (typed
FeatureBuilder declarations), app.py (workflow + OpApp entry),
params.json, test_app.py (smoke test) and README.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Avro primitive -> FeatureType (reference AvroField.AvroTypes: AInt,
# ABoolean, ALong, AFloat, ADouble, AString, AEnum)
_AVRO_TYPE_MAP = {
    "boolean": "Binary",
    "int": "Integral",
    "long": "Integral",
    "float": "Real",
    "double": "Real",
    "string": "Text",
    "enum": "PickList",
}
_AVRO_LOGICAL_MAP = {
    "date": "Date",
    "timestamp-millis": "DateTime",
    "timestamp-micros": "DateTime",
    "time-millis": "Integral",
}


@dataclass
class SchemaField:
    """One typed column (reference AvroField)."""

    name: str
    feature_type: str
    avro_type: Optional[str] = None  # primitive name when schema-driven
    nullable: bool = True


@dataclass
class SchemaSource:
    """Typed column list + where it came from (reference
    SchemaSource.scala: AvroSchemaFromFile | AutomaticSchema)."""

    fields: List[SchemaField]
    origin: str  # "avro-schema" | "data-inference"
    record_name: Optional[str] = None
    rows: List[Dict[str, Any]] = field(default_factory=list)

    def field_named(self, name: str) -> Optional[SchemaField]:
        return next((f for f in self.fields if f.name == name), None)

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_avro_schema(path: str) -> "SchemaSource":
        """Parse a .avsc record schema — no data scan needed (reference
        AvroSchemaFromFile)."""
        with open(path) as f:
            doc = json.load(f)
        if doc.get("type") != "record" or "fields" not in doc:
            raise ValueError(f"{path} is not an Avro record schema")
        fields: List[SchemaField] = []
        for fd in doc["fields"]:
            parsed = _parse_avro_field(fd)
            if parsed is not None:
                fields.append(parsed)
        if not fields:
            raise ValueError(f"No usable fields in Avro schema {path}")
        return SchemaSource(fields=fields, origin="avro-schema",
                            record_name=doc.get("name"))

    @staticmethod
    def from_data(path: str, limit: int = 1000) -> "SchemaSource":
        """Infer types by scanning data rows (reference AutomaticSchema)."""
        from .features.builder import infer_feature_type

        rows = _load_rows(path, limit)
        if not rows:
            raise ValueError(f"No rows read from {path}")
        keys: List[str] = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(k)
        fields = [
            SchemaField(name=k,
                        feature_type=infer_feature_type(
                            [r.get(k) for r in rows]).__name__)
            for k in keys
        ]
        return SchemaSource(fields=fields, origin="data-inference",
                            rows=rows)


def _parse_avro_field(fd: Dict[str, Any]) -> Optional[SchemaField]:
    """Schema.Field -> SchemaField (reference AvroField.from:166 —
    union [null, T] makes T nullable; unsupported complex types are
    skipped rather than failing the whole schema)."""
    t = fd.get("type")
    nullable = False
    if isinstance(t, list):  # union
        non_null = [x for x in t if x != "null"]
        if len(non_null) != 1:
            return None
        nullable = len(non_null) != len(t)
        t = non_null[0]
    logical = None
    if isinstance(t, dict):
        logical = t.get("logicalType")
        t = t.get("type")
    if not isinstance(t, str):
        return None
    if logical and logical in _AVRO_LOGICAL_MAP:
        ftype = _AVRO_LOGICAL_MAP[logical]
    elif t in _AVRO_TYPE_MAP:
        ftype = _AVRO_TYPE_MAP[t]
    else:
        return None  # records/maps/arrays: not feature columns
    return SchemaField(name=fd["name"], feature_type=ftype,
                       avro_type=t, nullable=nullable)


def _load_rows(path: str, limit: int = 1000) -> List[Dict[str, Any]]:
    if path.endswith(".avro"):
        from .readers.avro import read_avro_file
        out = []
        for i, r in enumerate(read_avro_file(path)):
            if i >= limit:
                break
            out.append(r)
        return out
    from .readers.readers import CSVReader
    return CSVReader(path).read()[:limit]


def detect_problem_kind(values: Sequence[Any]) -> str:
    """Data-driven kind: binary / multiclass / regression."""
    vals = [v for v in values if v is not None]
    distinct = set(vals)
    if len(distinct) <= 2:
        return "binary"
    if all(isinstance(v, (int, bool)) or
           (isinstance(v, float) and float(v).is_integer())
           for v in vals) and len(distinct) <= 30:
        return "multiclass"
    return "regression"


def detect_problem_kind_from_schema(f: SchemaField) -> Optional[str]:
    """Schema-driven kind (reference ProblemKind.from): a boolean
    response is binary, floating point is regression, enum is
    multiclass; int/long/string are ambiguous (reference prompts the
    user — here the caller passes --kind or provides data to refine)."""
    if f.avro_type == "boolean":
        return "binary"
    if f.avro_type in ("float", "double"):
        return "regression"
    if f.avro_type == "enum":
        return "multiclass"
    return None


_SELECTOR_BY_KIND = {
    "binary": "BinaryClassificationModelSelector",
    "multiclass": "MultiClassificationModelSelector",
    "regression": "RegressionModelSelector",
}

_FEATURES_TEMPLATE = '''"""{name} feature declarations (generated).

Edit types/extractions here; app.py imports PREDICTORS and RESPONSE.
Schema origin: {origin}.
"""
from transmogrifai_tpu import FeatureBuilder

{feature_decls}

PREDICTORS = [{predictor_names}]
RESPONSE = {response_var}
'''

_APP_TEMPLATE = '''"""{name}: generated by `python -m transmogrifai_tpu gen`.

Problem kind: {kind}. The workflow wires transmogrify -> SanityChecker
-> {selector}; tune grids or stages here.
"""
from transmogrifai_tpu.automl import {selector}
from transmogrifai_tpu.automl.preparators import SanityChecker
from transmogrifai_tpu.automl.transmogrifier import transmogrify
from transmogrifai_tpu.readers.readers import CSVReader
from transmogrifai_tpu.workflow import OpApp, OpWorkflowRunner, Workflow

from features import PREDICTORS, RESPONSE

DATA = {data_path!r}{data_note}


def build_workflow() -> Workflow:
    vectorized = transmogrify(PREDICTORS)
    checked = SanityChecker().set_input(RESPONSE, vectorized) \\
        .get_output()
    prediction = {selector}.with_cross_validation(
        num_folds=3, seed=42,
    ).set_input(RESPONSE, checked).get_output()
    return Workflow().set_result_features(prediction)


class {app_class}(OpApp):
    def runner(self) -> OpWorkflowRunner:
        return OpWorkflowRunner(build_workflow(),
                                train_reader=CSVReader(DATA),
                                score_reader=CSVReader(DATA))


if __name__ == "__main__":
    {app_class}().main()
'''

_TEST_TEMPLATE = '''"""Smoke test for the generated {name} project."""
import os
import subprocess
import sys

import pytest


def test_train_runs(tmp_path):
    import app
    proj = os.path.dirname(os.path.abspath(__file__))
    # resolve DATA exactly as the subprocess will (cwd = project dir)
    data = app.DATA if os.path.isabs(app.DATA) \\
        else os.path.join(proj, app.DATA)
    if not os.path.exists(data):
        pytest.skip(f"edit DATA in app.py first (placeholder: "
                    f"{{app.DATA!r}} does not exist)")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "app.py", "--run-type", "Train",
         "--model-location", str(tmp_path / "model")],
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert (tmp_path / "model").is_dir()
'''


def _pyname(col: str) -> str:
    return col.replace("-", "_").replace(" ", "_")


def _feature_decl(col: str, type_name: str, response: str) -> str:
    var = _pyname(col)
    role = "as_response" if col == response else "as_predictor"
    return (f'{var} = FeatureBuilder.{type_name}({col!r}).extract(\n'
            f'    lambda r: r.get({col!r})).{role}()')


def generate_project(input_path: Optional[str] = None,
                     response: str = "", output: str = ".",
                     id_col: Optional[str] = None,
                     name: Optional[str] = None,
                     schema_path: Optional[str] = None,
                     kind: Optional[str] = None) -> Dict[str, str]:
    """Build the project files; returns {filename: content}.

    Sources, in reference order (SchemaSource.scala): an explicit Avro
    schema wins (types and problem kind come from the schema, with data
    as a refinement for ambiguous int/long responses); otherwise the
    data file is scanned and types inferred.
    """
    if schema_path:
        src = SchemaSource.from_avro_schema(schema_path)
        if input_path:
            src.rows = _load_rows(input_path)
    elif input_path:
        src = SchemaSource.from_data(input_path)
    else:
        raise ValueError("need --input data and/or --schema avsc")

    rf = src.field_named(response)
    if rf is None:
        raise ValueError(f"Response column {response!r} not in schema "
                         f"(columns: {[f.name for f in src.fields]})")

    if src.rows and all(r.get(response) is None for r in src.rows):
        raise ValueError(
            f"Response column {response!r} has no values in the data file "
            f"(its columns: {sorted(src.rows[0])})")
    if kind is None:
        kind = detect_problem_kind_from_schema(rf) \
            if src.origin == "avro-schema" else None
        if kind is None and src.rows:
            kind = detect_problem_kind([r.get(response) for r in src.rows])
        if kind is None:
            raise ValueError(
                f"Problem kind is ambiguous from the schema alone for "
                f"{response!r} ({rf.avro_type}); pass --kind "
                f"binary|multiclass|regression or --input data")
    if kind not in _SELECTOR_BY_KIND:
        raise ValueError(f"Unknown problem kind {kind!r}")

    feats: List[Tuple[str, str]] = [
        (f.name, "RealNN" if f.name == response else f.feature_type)
        for f in src.fields if f.name != id_col]

    base = schema_path or input_path
    name = name or (src.record_name
                    or os.path.splitext(os.path.basename(base))[0].title())
    app_class = "".join(c for c in name.title() if c.isalnum()) or "App"

    decls = "\n".join(_feature_decl(c, t, response) for c, t in feats)
    predictors = ", ".join(_pyname(c) for c, _ in feats if c != response)
    features_py = _FEATURES_TEMPLATE.format(
        name=name, origin=src.origin, feature_decls=decls,
        predictor_names=predictors, response_var=_pyname(response))
    app_py = _APP_TEMPLATE.format(
        name=name, kind=kind, selector=_SELECTOR_BY_KIND[kind],
        data_path=os.path.abspath(input_path) if input_path else "data.csv",
        data_note=("" if input_path
                   else "  # PLACEHOLDER: point at your dataset"),
        app_class=app_class)
    test_py = _TEST_TEMPLATE.format(name=name)

    params = {"stage_params": {}, "model_location": "./model",
              "write_location": "./scores", "metrics_location": "./metrics"}
    data_hint = ("" if input_path else
                 "\n> **Before running:** `DATA` in `app.py` is a "
                 "placeholder (`data.csv`) — point it at your dataset.\n")
    readme = (f"# {name}\n\nGenerated by transmogrifai_tpu "
              f"(problem kind: **{kind}**, schema: {src.origin}, "
              f"{len(feats)} features).\n{data_hint}\n"
              f"- `features.py` — typed feature declarations\n"
              f"- `app.py` — workflow + Train/Score/Evaluate entry\n"
              f"- `params.json` — run configuration (OpParams)\n"
              f"- `test_app.py` — smoke test (`pytest test_app.py`)\n\n"
              f"```bash\npython app.py --run-type Train "
              f"--param-location params.json\n"
              f"python app.py --run-type Score --param-location params.json\n"
              f"```\n")

    os.makedirs(output, exist_ok=True)
    files = {"features.py": features_py,
             "app.py": app_py,
             "params.json": json.dumps(params, indent=2),
             "test_app.py": test_py,
             "README.md": readme}
    for fname, content in files.items():
        with open(os.path.join(output, fname), "w") as f:
            f.write(content)
    return files


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="transmogrifai_tpu")
    sub = p.add_subparsers(dest="command", required=True)
    gen = sub.add_parser("gen", help="generate a project from a dataset")
    gen.add_argument("--input", default=None, help="CSV or Avro data file")
    gen.add_argument("--schema", default=None,
                     help="Avro record schema (.avsc)")
    gen.add_argument("--response", required=True, help="label column")
    gen.add_argument("--id", default=None, help="id column to exclude")
    gen.add_argument("--kind", default=None,
                     choices=sorted(_SELECTOR_BY_KIND),
                     help="problem kind override")
    gen.add_argument("--output", default=".", help="project directory")
    gen.add_argument("--name", default=None, help="project name")
    tr = sub.add_parser(
        "trace-report",
        help="summarize a traced run dir (top spans by self-time, "
             "recompiles per program, kernel roofline, event-log counts); "
             "--check validates the Chrome-trace/event-log schemas "
             "(docs/observability.md)")
    tr.add_argument("dir", help="metrics dir written by a traced run "
                                "(metrics_location / BENCH_TRACE_DIR)")
    tr.add_argument("--check", action="store_true",
                    help="schema validation only; exit 1 on any problem")
    tr.add_argument("--requests", action="store_true",
                    help="request-tracing report: top-K slowest "
                         "tail-kept traces with their segment "
                         "breakdown; flags (exit 1) any request whose "
                         "segments do not cover its e2e wall within "
                         "tolerance (docs/observability.md)")
    tr.add_argument("--pod", action="store_true",
                    help="pod flight-recorder report: DIR is a pod "
                         "trace root holding rank-<k>/ dirs; merges "
                         "the ranks into one Chrome trace with rank "
                         "swimlanes and prints per-round skew, "
                         "straggler attribution, collective-wait share "
                         "and the MFU sink table; exit 1 on span "
                         "undercoverage or broken round alignment "
                         "(docs/observability.md)")
    tr.add_argument("--top", type=int, default=15,
                    help="rows in the self-time table (default 15)")
    sv = sub.add_parser(
        "serve",
        help="production serving engine over a saved model: AOT-prewarmed "
             "shape-bucketed executables, async micro-batching, HTTP/JSON "
             "frontend (docs/serving.md)")
    sv.add_argument("model_dir", help="saved WorkflowModel directory")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8765,
                    help="HTTP port (0 = ephemeral; default 8765)")
    sv.add_argument("--max-batch", type=int, default=64,
                    help="top bucket of the power-of-two ladder")
    sv.add_argument("--buckets", default=None,
                    help="explicit comma-separated bucket ladder "
                         "(overrides --max-batch)")
    sv.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="micro-batch fill window")
    sv.add_argument("--max-queue", type=int, default=1024,
                    help="admission queue bound (full -> 503 shed)")
    sv.add_argument("--single-record", choices=["bucket", "local"],
                    default="bucket",
                    help="batch-of-one route: the bucket-1 executable or "
                         "the pure-Python local replay")
    sv.add_argument("--example", default=None,
                    help="JSON file with one sample record for prewarm "
                         "batches (default: synthesized from feature "
                         "types)")
    sv.add_argument("--prewarm-only", action="store_true",
                    help="compile every bucket, populate the persistent "
                         "compile cache (TMOG_COMPILE_CACHE_DIR), write "
                         "the serve.json manifest and exit")
    sv.add_argument("--strict-manifest", action="store_true",
                    help="refuse to start (rc 2) when the serve.json "
                         "manifest's model hash / monitor stamp / bucket "
                         "ladder disagrees with the artifact (the fleet "
                         "replica contract, docs/fleet.md); default is a "
                         "startup warning")
    sv.add_argument("--metrics-location", default=None,
                    help="dir for events.jsonl + trace artifacts "
                         "(enables span collection + the recompile "
                         "watch; validate with trace-report --check)")
    sv.add_argument("--monitor", choices=["auto", "on", "off"],
                    default="auto",
                    help="continuous drift monitoring against the "
                         "model's monitor.json training profile "
                         "(docs/monitoring.md); auto = on when the "
                         "profile exists")
    sv.add_argument("--monitor-window-rows", type=int, default=4096,
                    help="tumbling drift window size in rows")
    sv.add_argument("--monitor-window-seconds", type=float, default=60.0,
                    help="close a non-empty window after this long even "
                         "if under --monitor-window-rows")
    sv.add_argument("--monitor-health-gate", action="store_true",
                    help="degrade /healthz to 503 while a drift alert "
                         "is active (hard gate for load balancers)")
    sv.add_argument("--replica-id", default=None,
                    help="identity echoed in the X-Tmog-Trace reply "
                         "header and stamped on kept request traces "
                         "(the fleet supervisor passes the handle "
                         "name; default pid<N>)")
    sv.add_argument("--request-trace", choices=["on", "off"],
                    default="on",
                    help="per-request tracing: segment histograms, "
                         "tail-kept traces under GET /requests, "
                         "request_trace events "
                         "(docs/observability.md; TMOG_REQTRACE=0 "
                         "also disables)")
    sv.add_argument("--trace-sample", type=float, default=None,
                    help="probabilistic keep rate for unremarkable "
                         "requests (errors/sheds/retries/slow are "
                         "always kept; default TMOG_TRACE_SAMPLE or "
                         "0.01)")
    fl = sub.add_parser(
        "fleet",
        help="serving FLEET over a saved model: N replica worker "
             "processes sharing one compile cache behind a front router "
             "with merged /metrics + /drift and zero-downtime "
             "champion/challenger rollout (docs/fleet.md)")
    fl.add_argument("model_dir", help="saved WorkflowModel directory "
                                      "(run `serve --prewarm-only` "
                                      "first, or the fleet will)")
    fl.add_argument("--replicas", type=int, default=2,
                    help="champion replica count (default 2)")
    fl.add_argument("--host", default="127.0.0.1",
                    help="front-router bind host")
    fl.add_argument("--port", type=int, default=8766,
                    help="front-router HTTP port (0 = ephemeral)")
    fl.add_argument("--replica-host", default="127.0.0.1",
                    help="host replicas bind (and the router dials)")
    fl.add_argument("--max-batch", type=int, default=None,
                    help="per-replica bucket-ladder top (serve "
                         "--max-batch pass-through)")
    fl.add_argument("--buckets", default=None,
                    help="explicit per-replica bucket ladder "
                         "(pass-through)")
    fl.add_argument("--max-wait-ms", type=float, default=None,
                    help="per-replica micro-batch fill window "
                         "(pass-through)")
    fl.add_argument("--max-queue", type=int, default=None,
                    help="per-replica admission queue bound "
                         "(pass-through)")
    fl.add_argument("--single-record", choices=["bucket", "local"],
                    default=None, help="per-replica batch-of-one route "
                                       "(pass-through)")
    fl.add_argument("--monitor", choices=["auto", "on", "off"],
                    default="auto",
                    help="per-replica drift monitoring; the fleet pools "
                         "replica windows into ONE /drift verdict")
    fl.add_argument("--request-trace", choices=["on", "off"],
                    default="on",
                    help="per-request tracing across the fleet: the "
                         "router mints X-Tmog-Trace ids, replicas "
                         "stamp segments, GET /requests merges them "
                         "(pass-through to replicas too)")
    fl.add_argument("--trace-sample", type=float, default=None,
                    help="probabilistic keep rate for unremarkable "
                         "requests (router + replicas)")
    fl.add_argument("--probe-interval-s", type=float, default=0.5,
                    help="router /healthz probe cadence")
    fl.add_argument("--request-timeout-s", type=float, default=30.0,
                    help="per-replica request timeout (504 beyond it; "
                         "timeouts are never retried)")
    fl.add_argument("--max-restarts", type=int, default=20,
                    help="per-replica crash-restart budget")
    fl.add_argument("--metrics-location", default=None,
                    help="fleet events.jsonl + per-replica-incarnation "
                         "artifact dirs (default: "
                         "<model_dir>/fleet_metrics)")
    fl.add_argument("--retrain", choices=["auto", "off"], default="off",
                    help="drift-triggered continuous retraining "
                         "(docs/retraining.md): auto arms a "
                         "RetrainController when the model dir carries "
                         "a retrain.json recipe — pooled /drift alerts "
                         "launch a sandboxed refit, validated "
                         "candidates roll out via the "
                         "champion/challenger path")
    fl.add_argument("--retrain-min-interval-s", type=float, default=60.0,
                    help="cooldown between retrain cycle starts")
    fl.add_argument("--retrain-max-per-window", type=int, default=4,
                    help="storm breaker: max cycle starts per hour")
    fl.add_argument("--retrain-fit-timeout-s", type=float, default=900.0,
                    help="refit worker wall-clock budget, then SIGKILL")
    fl.add_argument("--retrain-poll-interval-s", type=float, default=2.0,
                    help="pooled /drift poll cadence of the controller")
    rw = sub.add_parser(
        "retrain-worker",
        help="sandboxed refit worker (one candidate model per run): the "
             "unit the retrain controller launches, times out, retries "
             "and quarantines (docs/retraining.md); normally spawned by "
             "the controller, manual runs take the same spec.json")
    rw.add_argument("spec", help="RefitSpec JSON written by the "
                                 "controller (champion dir, builder, "
                                 "history + window data, holdout split)")
    pl = sub.add_parser(
        "plan",
        help="plan-time autotuner (docs/planning.md): `calibrate` seeds "
             "the measured-cost corpus with a bounded micro-bench grid "
             "on the current backend, `show` summarizes the corpus, "
             "`explain` prints the resolved plan for a shape with "
             "per-decision predicted-vs-alternative costs")
    pl.add_argument("action", choices=["calibrate", "show", "explain"])
    pl.add_argument("--corpus-dir", default=None,
                    help="corpus directory (default TMOG_PLAN_CORPUS_DIR "
                         "or the per-user cache dir)")
    pl.add_argument("--budget-s", type=float, default=180.0,
                    help="calibrate: wall budget; families past it are "
                         "skipped (partial corpora are fine)")
    pl.add_argument("--scale", type=float, default=1.0,
                    help="calibrate: micro-bench size multiplier "
                         "(CI smokes pass <1 for speed)")
    pl.add_argument("--rows", type=int, default=1_000_000,
                    help="explain: sweep row count")
    pl.add_argument("--feat", type=int, default=64,
                    help="explain: feature count")
    pl.add_argument("--folds", type=int, default=5,
                    help="explain: CV fold count")
    pl.add_argument("--grids", type=int, default=12,
                    help="explain: grid-point count")
    pl.add_argument("--depth", type=int, default=6,
                    help="explain: tree depth")
    pl.add_argument("--bins", type=int, default=32,
                    help="explain: histogram bins")
    pl.add_argument("--shards", type=int, default=1,
                    help="explain: mesh batch-axis size (the grid-fuse "
                         "knee judges the sharded chunk's out-block)")
    pl.add_argument("--max-batch", type=int, default=64,
                    help="explain: serving ladder top")
    pl.add_argument("--json", action="store_true",
                    help="explain: machine-readable output")
    mo = sub.add_parser(
        "monitor",
        help="offline drift report: score a bulk file through the "
             "tileplane lane and compare feature/prediction "
             "distributions against the model's monitor.json training "
             "profile (docs/monitoring.md)")
    mo.add_argument("model_dir", help="saved WorkflowModel directory "
                                      "(with monitor.json)")
    mo.add_argument("data", help="CSV or Avro file of raw records")
    mo.add_argument("--profile", default=None,
                    help="explicit profile JSON (default: "
                         "<model_dir>/monitor.json)")
    mo.add_argument("--tile-rows", type=int, default=1024,
                    help="records per scoring tile (score_stream lane)")
    mo.add_argument("--window-rows", type=int, default=0,
                    help="tumbling window size; 0 = one window over the "
                         "whole file (default)")
    mo.add_argument("--fail-on-drift", action="store_true",
                    help="exit 3 when any drift_alert fires (CI/cron "
                         "gate)")
    mo.add_argument("--metrics-location", default=None,
                    help="dir for the events.jsonl drift_window/"
                         "drift_alert stream")
    for knob, hint in (("max-js", "per-feature JS divergence [0,1]"),
                       ("max-psi", "per-feature PSI"),
                       ("max-fill-diff", "abs fill-rate difference"),
                       ("max-fill-ratio", "fill-rate max/min ratio"),
                       ("max-pred-js", "prediction calibration JS"),
                       ("max-score-shift", "abs score-mean shift"),
                       ("min-rows", "min rows before a window can "
                                    "alert")):
        mo.add_argument(f"--{knob}", type=float, default=None,
                        help=f"alert threshold: {hint}")
    a = p.parse_args(argv)
    if a.command == "gen":
        files = generate_project(a.input, a.response, a.output,
                                 id_col=a.id, name=a.name,
                                 schema_path=a.schema, kind=a.kind)
        print(f"Generated {', '.join(files)} in {a.output}")
        return 0
    if a.command == "trace-report":
        # exit codes follow docs/static_analysis.md "Exit codes" (the
        # same table the tmoglint CLI uses): 0 clean, 1 problems,
        # 2 usage error (not a traced run dir)
        if a.pod:
            from .parallel.podtrace import pod_report_rc
            text, rc = pod_report_rc(a.dir, top=a.top)
            print(text)
            return rc
        if a.requests:
            from .utils.tracing import requests_report_rc
            text, rc = requests_report_rc(a.dir, top=a.top)
            print(text)
            return rc
        from .utils.tracing import trace_report_rc
        text, rc = trace_report_rc(a.dir, check=a.check, top=a.top)
        print(text)
        return rc
    if a.command == "serve":
        from .serve.frontend import run_serve
        return run_serve(a)
    if a.command == "fleet":
        from .fleet.frontend import run_fleet
        return run_fleet(a)
    if a.command == "plan":
        from .planner.calibrate import run_plan_cli
        return run_plan_cli(a)
    if a.command == "monitor":
        from .monitor.offline import run_monitor
        return run_monitor(a)
    if a.command == "retrain-worker":
        from .retrain.refit import run_retrain_worker
        return run_retrain_worker(a)
    return 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
