"""Raw-feature filtering (reference core/.../filters/, 1,360 LoC): exclude
unreliable raw features before training — see `raw_feature_filter`."""
from .raw_feature_filter import (
    ExclusionReasons, FeatureDistribution, RawFeatureFilter,
    RawFeatureFilterResults, RffResult, compute_distributions,
)

__all__ = [
    "ExclusionReasons", "FeatureDistribution", "RawFeatureFilter",
    "RawFeatureFilterResults", "RffResult", "compute_distributions",
]
